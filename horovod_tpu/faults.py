"""Deterministic, seed-driven fault injection.

The reference Horovod proves its elastic path with scripted failures in
``test/integration/elastic_common.py`` (discovery scripts that change
output over time, workers told to exit by epoch).  That works for
integration tests but leaves the *production* code paths untestable
without monkeypatching: there is no way to make the real discovery
call, the real spawn path, or the real checkpoint writer fail on
demand.  This module closes that gap with named in-code injection
sites that are inert by default and scriptable from the environment —
the same plan syntax drives unit tests, the elastic integration suite,
and ad-hoc "game day" runs of a real job.

Plan syntax (``HVD_TPU_FAULT_PLAN``)::

    [seed=N;]site:kind[:key=val[,key=val...]][;site:kind[:...]]...

Each entry names an injection *site* (a dotted string the code passes
to :func:`inject`), a fault *kind*, and optional selectors/arguments:

``kind``
    ``error``/``flake``  raise :class:`FaultInjected` (``msg=...``)
    ``crash``            ``os._exit(code)`` (default 1) — a hard worker
                         death, skipping atexit like a real SIGKILL
    ``hang``             sleep ``secs`` (default 3600) — a wedged
                         thread, distinguishable from a crash only by
                         heartbeat
    ``slow``             sleep ``secs`` (default 1.0) then continue —
                         a straggler host
    ``corrupt``          return ``True`` from :func:`inject`; the call
                         site cooperates (e.g. ``checkpoint.py``
                         flips bytes after writing)
    ``kill_at_step``     sugar for ``crash`` pinned to one training
                         step: requires ``step=K`` and fires exactly
                         when a site's ``step`` context equals K
                         (``worker.commit`` is the per-step-boundary
                         site) — the deterministic worker kill of the
                         kill-and-resize remesh tests
    ``resize_to``        cooperative (like ``corrupt``): requires
                         ``np=N``; :func:`inject` returns
                         ``{"np": N}`` and the call site resizes the
                         world (``discovery.resize`` in
                         ``elastic/discovery.py`` rescales the
                         discovered slot total) — a scripted,
                         seed-reproducible membership change

selectors
    ``nth=K``     fire on the K-th matching arrival only (1-based)
    ``times=M``   fire on M consecutive matching arrivals (default 1;
                  combined with ``nth``, fires on arrivals K..K+M-1;
                  ``times=0`` means every arrival)
    ``p=0.X``     fire with probability X per matching arrival, drawn
                  from the plan-seeded RNG — deterministic for a given
                  (seed, arrival sequence)
    anything else is matched against the keyword context the call site
    passes to :func:`inject` (``rank=1``, ``round=2``, ``host=10.0.0.3``
    ...); an entry only counts arrivals whose context matches.

Example — one discovery flake, then a crash of rank 1 in round 2::

    HVD_TPU_FAULT_PLAN='discovery.script:error:nth=1;worker.step:crash:rank=1,round=2,code=7'

Registered sites (grep ``faults.inject`` for ground truth):

==============================  ==========================================
``discovery.script``            before each discovery-script execution
``discovery.resize``            after each discovery poll (``resize_to``
                                rescales the discovered slot total)
``driver.spawn``                before each worker spawn (host/rank/round)
``worker.connect``              before the worker dials the rendezvous KV
``worker.heartbeat``            each worker heartbeat tick (rank/round)
``worker.commit``               each elastic-state commit (``step=`` is
                                the per-state commit counter — the
                                ``kill_at_step`` anchor)
``checkpoint.write``            after checkpoint bytes hit disk (corrupt)
``remesh.<phase>``              each remesh pipeline phase (pause/
                                snapshot/publish/barrier/reinit/fetch/
                                rebuild — fail any phase on demand)
``remesh.publish``              additionally honors ``corrupt``: the
                                published shard blob is damaged so the
                                receiver's checksum MUST catch it
``svc.submit``                  each exchange-service submission (host
                                and traced producers; ``producer=``,
                                ``kind=`` context) — an ``error`` kills
                                the service and the submission degrades
                                to synchronous inline dispatch
                                (``svc.fallback_sync``)
``svc.admit``                   each tenant-lane admission
                                (``tenant=`` context; svc/arbiter.py)
                                — an ``error`` kills the service
                                before the slot is taken, degrading
                                the submission to inline dispatch
``svc.drain``                   each service drain (remesh pause,
                                elastic restart, shutdown)
``svc.loop``                    each background-loop cycle tick
                                (``cycle=`` context) — kill the service
                                mid-flight between submissions
``topo.dcn_phase``              inside each cross-slice DCN hop's trace
                                span (``phase=``/``wire=`` context;
                                host-side, fires at trace time) — a
                                ``slow`` kind is the scripted straggler
                                the trace smoke injects: the delay lands
                                in that rank's DCN rail span and the
                                driver's ``/trace`` summary names it
``remediate.plan``              while an SLO remediation plans its
                                action (``tenant=``/``rung=`` context;
                                elastic/remediate.py) — a failure here
                                aborts before anything changed
``remediate.handoff``           inside the slice-handoff execution
                                (shrink donor / reshard / grow
                                recipient) — any fault mid-handoff
                                rolls back to the pre-handoff placement
``remediate.rollback``          inside that rollback itself — a fault
                                here leaves the placement UNSTABLE and
                                the abort record says so (the caller
                                escalates to the respawn path)
==============================  ==========================================

Every fired fault also triggers a flight-recorder dump
(``trace.on_fault`` — docs/tracing.md), so the span history around an
injected failure survives even a ``crash`` kind.

Worker scripts may add their own sites (``faults.inject("my.site")``)
— the registry is open.  Every fired fault increments the
``faults.injected.<site>.<kind>`` counter in :mod:`horovod_tpu.metrics`.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

from .exceptions import FaultInjected
from .utils.logging import get_logger

ENV_VAR = "HVD_TPU_FAULT_PLAN"

KINDS = ("error", "flake", "crash", "hang", "slow", "corrupt",
         "kill_at_step", "resize_to")

# Selector/argument keys that are NOT matched against inject() context.
_RESERVED = {"nth", "times", "p", "code", "secs", "msg", "np"}


def _parse_scalar(val: str) -> Any:
    """Plan values compare against context values; normalize numerics so
    ``rank=1`` matches ``inject(..., rank=1)``."""
    try:
        return int(val)
    except ValueError:
        pass
    try:
        return float(val)
    except ValueError:
        pass
    return val


class FaultSpec:
    """One plan entry: a (site, kind) with selectors and its own
    deterministic arrival counter."""

    def __init__(self, site: str, kind: str, args: Dict[str, Any]):
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} (one of {KINDS})"
            )
        self.site = site
        self.kind = "error" if kind == "flake" else kind
        self.np = int(args.pop("np", 0))            # resize_to target
        if self.kind == "resize_to" and self.np < 1:
            raise ValueError(
                "resize_to requires np=N (the target world size)"
            )
        if self.kind == "kill_at_step":
            # Sugar: a crash pinned to one step-counter value — the
            # seed-reproducible worker kill of remesh tests.  The step
            # selector matches the site's step= context
            # (State.commit's per-step arrival counter).
            if "step" not in args:
                raise ValueError(
                    "kill_at_step requires step=K (the commit counter "
                    "value to die at)"
                )
            self.kind = "crash"
        self.nth = int(args.pop("nth", 0))          # 0 = any arrival
        self.times = int(args.pop("times", 1))      # 0 = unbounded
        self.prob = float(args.pop("p", 1.0))
        self.code = int(args.pop("code", 1))
        self.secs = float(args.pop("secs", 3600.0 if self.kind == "hang"
                                   else 1.0))
        self.msg = str(args.pop("msg", ""))
        self.match = dict(args)                     # context selectors
        self.arrivals = 0                           # matching arrivals
        self.fired = 0

    def _context_matches(self, context: Dict[str, Any]) -> bool:
        for k, want in self.match.items():
            got = context.get(k)
            if got is None:
                return False
            if isinstance(want, (int, float)) and not isinstance(got, str):
                try:
                    if float(got) != float(want):
                        return False
                    continue
                except (TypeError, ValueError):
                    return False
            if str(got) != str(want):
                return False
        return True

    def should_fire(self, context: Dict[str, Any], rng: random.Random) -> bool:
        """Deterministic: counters advance only on matching arrivals, and
        the probabilistic draw comes from the plan's seeded RNG."""
        if not self._context_matches(context):
            return False
        self.arrivals += 1
        if self.nth:
            lo, hi = self.nth, (
                float("inf") if self.times == 0 else self.nth + self.times - 1
            )
            if not (lo <= self.arrivals <= hi):
                return False
        elif self.times and self.fired >= self.times:
            return False
        if self.prob < 1.0 and rng.random() >= self.prob:
            return False
        self.fired += 1
        return True

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        sel = {"nth": self.nth, "times": self.times, "p": self.prob,
               **self.match}
        return f"FaultSpec({self.site}:{self.kind}:{sel})"


class FaultPlan:
    """A parsed ``HVD_TPU_FAULT_PLAN``: specs grouped by site, one seeded
    RNG shared by all probabilistic entries, thread-safe counters."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for s in specs:
            self._by_site.setdefault(s.site, []).append(s)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        seed = 0
        specs: List[FaultSpec] = []
        for raw in spec.split(";"):
            entry = raw.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                seed = int(entry[5:])
                continue
            parts = entry.split(":", 2)
            if len(parts) < 2:
                raise ValueError(
                    f"malformed fault entry {entry!r}: want "
                    "'site:kind[:key=val,...]'"
                )
            site, kind = parts[0].strip(), parts[1].strip()
            args: Dict[str, Any] = {}
            if len(parts) == 3 and parts[2].strip():
                for kv in parts[2].split(","):
                    if "=" not in kv:
                        raise ValueError(
                            f"malformed fault arg {kv!r} in {entry!r}"
                        )
                    k, v = kv.split("=", 1)
                    args[k.strip()] = _parse_scalar(v.strip())
            specs.append(FaultSpec(site, kind, args))
        return cls(specs, seed=seed)

    def sites(self) -> List[str]:
        return sorted(self._by_site)

    def arm(self, site: str, context: Dict[str, Any]) -> Optional[FaultSpec]:
        """The first spec at ``site`` that fires for this arrival."""
        specs = self._by_site.get(site)
        if not specs:
            return None
        with self._lock:
            for s in specs:
                if s.should_fire(context, self._rng):
                    return s
        return None

    def counters(self) -> Dict[str, int]:
        """Per-entry fired counts (``site:kind`` -> fired) for tests."""
        with self._lock:
            out: Dict[str, int] = {}
            for site, specs in self._by_site.items():
                for s in specs:
                    key = f"{site}:{s.kind}"
                    out[key] = out.get(key, 0) + s.fired
            return out


_active: Optional[FaultPlan] = None
_active_loaded = False
_active_lock = threading.Lock()


def get_plan() -> Optional[FaultPlan]:
    """The process-wide plan: set via :func:`set_plan`, else parsed once
    from ``HVD_TPU_FAULT_PLAN``.  None (the default) disables every
    injection site at the cost of one dict lookup."""
    global _active, _active_loaded
    with _active_lock:
        if not _active_loaded:
            spec = os.environ.get(ENV_VAR, "")
            _active = FaultPlan.parse(spec) if spec.strip() else None
            _active_loaded = True
        return _active


def set_plan(plan: Optional[Any]) -> Optional[FaultPlan]:
    """Install a plan (a :class:`FaultPlan`, a spec string, or None to
    disarm).  Returns the installed plan.  Tests use this instead of
    mutating the environment."""
    global _active, _active_loaded
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan) if plan.strip() else None
    with _active_lock:
        _active = plan
        _active_loaded = True
        return _active


def reset() -> None:
    """Forget the installed plan; the next :func:`inject` re-reads the
    environment."""
    global _active, _active_loaded
    with _active_lock:
        _active = None
        _active_loaded = False


def inject(site: str, **context: Any):
    """Fault-injection call site.  Inert (returns False) without a
    matching armed fault.  ``error`` raises :class:`FaultInjected`;
    ``crash`` (and its ``kill_at_step`` sugar) hard-exits the process;
    ``hang``/``slow`` sleep; ``corrupt`` returns True so the caller
    corrupts its own output; ``resize_to`` returns ``{"np": N}`` so
    the caller resizes the world.
    """
    plan = get_plan()
    if plan is None:
        return False
    spec = plan.arm(site, context)
    if spec is None:
        return False
    from . import metrics

    metrics.inc_counter(f"faults.injected.{site}.{spec.kind}")
    # Flight-recorder anomaly trigger (trace/): an armed fault firing
    # dumps the span ring BEFORE the fault takes effect, so even a
    # 'crash' kind leaves the window around the injection on disk.
    try:
        from . import trace

        trace.on_fault(site, spec.kind)
    except Exception:  # observability must not change fault semantics
        pass
    log = get_logger()
    if spec.kind == "error":
        log.warning("fault injection: error at %s %s", site, context)
        raise FaultInjected(site, spec.msg)
    if spec.kind == "crash":
        log.warning("fault injection: crash(%d) at %s %s",
                    spec.code, site, context)
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(spec.code)
    if spec.kind in ("hang", "slow"):
        log.warning("fault injection: %s(%.1fs) at %s %s",
                    spec.kind, spec.secs, site, context)
        time.sleep(spec.secs)
        return False
    if spec.kind == "resize_to":
        # cooperative: the call site resizes the world to spec.np
        log.warning("fault injection: resize_to(np=%d) at %s %s",
                    spec.np, site, context)
        return {"np": spec.np}
    # corrupt: cooperate with the caller
    log.warning("fault injection: corrupt at %s %s", site, context)
    return True
