"""Multi-backend lowering plane (PR 20).

One plan pipeline, two accelerator families: a :class:`registry.Backend`
descriptor per family (rail names, peak table hook, kernel-lowering
table, discovery fn), resolved by ``HVD_TPU_BACKEND=auto|tpu|gpu``.
The gpu family lowers the fused quantized ring through
``ops/mosaic_quant.py``, discovers NVLink/IB topologies through
:mod:`gpu_topo`, and prices its rails through the same fitted cost
model every TPU consumer already uses.  See docs/backends.md.
"""

from . import gpu_topo, registry  # noqa: F401
from .registry import (  # noqa: F401
    RAILS,
    Backend,
    family,
    get,
    kernel_module_name,
    rail_labels,
    reset,
)
