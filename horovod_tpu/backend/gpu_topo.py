"""GPU topology discovery: NVLink domains and IB links as the two rails.

The topology subsystem models exactly two network classes — a fast
intra-domain rail and a ~10x-slower inter-domain rail — because that is
the shape of every scaled training fabric (arXiv:1810.11112's two-level
regime).  On TPU the pair is ICI/DCN; on a GPU cluster it is the NVLink
island inside a host (or NVSwitch pod) and the InfiniBand fabric
between hosts.  This module maps the second onto the first:

* one **NVLink domain** per host — devices sharing a ``process_index``
  (multi-process) or the whole local world (single-process) form a
  "slice"; NVLink prices as the ICI rail;
* **IB** between domains prices as the DCN rail;
* the result is a plain :class:`~horovod_tpu.topo.model.Topology`
  (``source="gpu"``), so the fitted cost model, hier/flat/hier_adasum
  resolution, the rail pipeliner, DRR pricing, fusion buffers, and the
  serve plane all run unchanged — they only ever see the two canonical
  rails.

``HVD_TPU_TOPO`` (spec string or JSON) is honored *upstream* in
``topo.model.discover`` before any backend discovery runs, so a forced
shape behaves identically under either family.  The ``TOPO_*_GBPS`` /
latency knobs override the GPU defaults below exactly as they override
the TPU ones.
"""

from __future__ import annotations

from typing import Sequence

from ..utils import env

# Link-parameter defaults for the gpu family (datasheet-order figures:
# NVLink4 ~450 GB/s/direction per GPU, 4x200Gbit HDR IB ~ 25 GB/s/GPU;
# the fitted cost model replaces both with measured values after the
# first HVD_TPU_TOPO_FIT window, so these only seed the first plans).
DEFAULT_NVLINK_GBPS = 300.0
DEFAULT_IB_GBPS = 25.0
DEFAULT_NVLINK_LAT_S = 2e-6
DEFAULT_IB_LAT_S = 10e-6


def _link_params() -> dict:
    """The topo link-parameter dict with gpu-family defaults; the same
    ``TOPO_*`` env knobs override (a job that measured its own fabric
    pins the figures exactly as on TPU)."""
    from ..topo import model as topo_model

    return dict(
        ici_gbps=env.get_float(env.TOPO_ICI_GBPS, DEFAULT_NVLINK_GBPS),
        dcn_gbps=env.get_float(env.TOPO_DCN_GBPS, DEFAULT_IB_GBPS),
        ici_latency_s=env.get_float(
            env.TOPO_ICI_LAT_US, DEFAULT_NVLINK_LAT_S * 1e6) * 1e-6,
        dcn_latency_s=env.get_float(
            env.TOPO_DCN_LAT_US, DEFAULT_IB_LAT_S * 1e6) * 1e-6,
        phase_overhead_s=env.get_float(
            env.TOPO_PHASE_OVERHEAD_US,
            topo_model.DEFAULT_PHASE_OVERHEAD_S * 1e6) * 1e-6,
    )


def discover(devices: Sequence):
    """Build a Topology from a GPU (or forced-gpu CPU test) device
    list: one NVLink domain per ``process_index``, IB between domains.
    Ragged domain sizes or non-domain-major device order collapse to
    one domain — the flat degenerate, exactly like the TPU path's
    ragged-slice fallback."""
    from ..topo import model as topo_model
    from ..utils.logging import get_logger

    params = _link_params()
    n = len(devices)
    host_of = []
    for d in devices:
        idx = getattr(d, "process_index", None)
        if idx is None:
            idx = getattr(d, "host_id", None)
        host_of.append(0 if idx is None else int(idx))
    ids = sorted(set(host_of))
    sizes = {i: host_of.count(i) for i in ids}
    if len(ids) < 2 or len(set(sizes.values())) != 1:
        if len(ids) >= 2:
            get_logger().warning(
                "backend.gpu: ragged NVLink domain sizes %s; treating "
                "the world as one domain (flat lowering)", sizes,
            )
        return topo_model.Topology(
            num_slices=1, slice_size=n, source="gpu", **params
        )
    # Contiguity contract (shared with the TPU path): device order must
    # be domain-major for the slice-major group math to hold.
    size = sizes[ids[0]]
    blocks = [host_of[i * size:(i + 1) * size] for i in range(len(ids))]
    if any(len(set(b)) != 1 for b in blocks):
        get_logger().warning(
            "backend.gpu: device order is not NVLink-domain-major; "
            "treating the world as one domain (flat lowering)"
        )
        return topo_model.Topology(
            num_slices=1, slice_size=n, source="gpu", **params
        )
    return topo_model.Topology(
        num_slices=len(ids), slice_size=size, source="gpu", **params
    )
