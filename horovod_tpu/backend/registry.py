"""Backend registry: one descriptor per accelerator family.

The reference serves four wire backends (MPI/NCCL/Gloo/oneCCL) behind
one plan pipeline by keeping the backend-specific pieces — which links
exist, how fast they are, which kernels lower a fused collective — in
per-backend operation tables (``horovod/common/ops/``).  This module is
that seam for the JAX stack: a :class:`Backend` descriptor bundles the
four things that actually differ between a TPU pod and a GPU cluster,
and everything above it (the XIR lowering pass, the two-rail pipeliner,
DRR rail pricing, fusion buffers, the exchange service, the arbiter,
the serve plane) keys off the *canonical* two-rail model and never
notices which family is underneath:

* **rail names** — the canonical fast/slow rails (``ici``/``dcn``)
  mapped to the family's physical spelling (NVLink ≈ ICI, IB ≈ DCN on
  gpu; identity on tpu).  ``topo.model.rail_labels`` serves them to
  ``/tenants`` and ``/prof``.
* **peak table hook** — the datasheet bf16 peak list ``prof/peak.py``
  resolves MFU denominators against (TPU v2–v6 vs A100/H100/...).
* **kernel-lowering table** — op class → kernel module: the fused
  quantized ring lowers through ``ops/pallas_quant.py`` on tpu and
  ``ops/mosaic_quant.py`` on gpu (``quantized.fused_kernel_module``).
* **discovery fn** — device list → :class:`~horovod_tpu.topo.model.Topology`:
  slice_index/coords grouping on tpu, NVLink-domain/IB grouping on gpu
  (``backend/gpu_topo.py``).  The ``HVD_TPU_TOPO`` override bypasses
  both, unchanged.

Resolution (:func:`family`): ``HVD_TPU_BACKEND=auto|tpu|gpu`` — the env
override first (CPU test meshes force either family without hardware),
else ``jax.devices()[0].platform`` (``gpu``/``cuda``/``rocm`` → gpu,
anything else → tpu, the safe pre-PR-20 default).  The gpu family's
``default_quant_backend`` is ``fused``: on a GPU mesh quantized reduce
ops route through the mosaic ring by default, exactly as
``HVD_TPU_QUANT_BACKEND=fused`` does on TPU.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, Optional, Sequence, Tuple

from ..exceptions import HorovodTpuError
from ..utils import env

#: Canonical rail tags every pricing/pipelining consumer keys on.
RAILS = ("ici", "dcn")

#: jax platform strings that resolve to the gpu family under "auto".
_GPU_PLATFORMS = ("gpu", "cuda", "rocm")

#: Spellings accepted by the HVD_TPU_BACKEND knob, canonicalized.
_FAMILY_ALIASES = {
    "tpu": "tpu", "axon": "tpu",
    "gpu": "gpu", "cuda": "gpu", "rocm": "gpu", "nvidia": "gpu",
}


@dataclasses.dataclass(frozen=True)
class Backend:
    """One accelerator family's lowering plane.

    ``rails`` maps the canonical tags to the family's physical labels;
    ``peak_table`` lazily returns the ``(device_kind substring, bf16
    TFLOP/s)`` list (a hook, so the tables stay in ``prof/peak.py``);
    ``kernels`` maps op classes to kernel module names; ``discover``
    builds a Topology from a device list (late-bound — topology and
    registry import each other lazily)."""

    name: str
    platforms: Tuple[str, ...]
    rails: Dict[str, str]
    peak_table: Callable[[], list]
    kernels: Dict[str, str]
    discover: Callable[[Sequence], "object"]
    default_quant_backend: str = "phase"

    def rail_label(self, rail: str) -> str:
        """Physical spelling of one canonical rail tag (identity for
        unknown tags — never a KeyError)."""
        return self.rails.get(rail, rail)


def _tpu_peak_table() -> list:
    from ..prof import peak

    return peak.PEAK_BF16_TFLOPS


def _gpu_peak_table() -> list:
    from ..prof import peak

    return peak.PEAK_BF16_TFLOPS_GPU


def _tpu_discover(devices):
    from ..topo import model as topo_model

    return topo_model._from_devices(devices)


def _gpu_discover(devices):
    from . import gpu_topo

    return gpu_topo.discover(devices)


BACKENDS: Dict[str, Backend] = {
    "tpu": Backend(
        name="tpu",
        platforms=("tpu", "axon"),
        rails={"ici": "ici", "dcn": "dcn"},
        peak_table=_tpu_peak_table,
        kernels={"quant_ring": "pallas_quant"},
        discover=_tpu_discover,
        default_quant_backend="phase",
    ),
    "gpu": Backend(
        name="gpu",
        platforms=_GPU_PLATFORMS,
        rails={"ici": "nvlink", "dcn": "ib"},
        peak_table=_gpu_peak_table,
        kernels={"quant_ring": "mosaic_quant"},
        discover=_gpu_discover,
        # EQuARX-style fused rings are the GPU default: there is no
        # legacy phase-tuned GPU fleet to stay bitwise with, and the
        # mosaic interpret path proves gpu==phase parity in tier-1.
        default_quant_backend="fused",
    ),
}

_lock = threading.Lock()
_platform_cache: Optional[str] = None


def _device_platform() -> str:
    """``jax.devices()[0].platform``, probed once per process.  Any
    failure (no runtime yet, headless tools) resolves to ``cpu`` — the
    tpu family's safe degenerate."""
    global _platform_cache
    with _lock:
        if _platform_cache is not None:
            return _platform_cache
    try:
        import jax

        from ..runtime import get_runtime_or_none

        rt = get_runtime_or_none()
        devices = rt.devices if rt is not None else jax.devices()
        platform = (devices[0].platform or "cpu").lower()
    except Exception:
        platform = "cpu"
    with _lock:
        if _platform_cache is None:
            _platform_cache = platform
        return _platform_cache


def family(raw: Optional[str] = None) -> str:
    """Resolve the backend family: the ``HVD_TPU_BACKEND`` env override
    (or an explicit ``raw`` spelling) when set, else the first jax
    device's platform.  Unknown spellings raise — a typo'd backend must
    never silently train on the wrong lowering tables."""
    if raw is None:
        raw = env.get_env(env.BACKEND, "auto")
    r = (raw or "auto").strip().lower()
    if r in ("", "auto"):
        return "gpu" if _device_platform() in _GPU_PLATFORMS else "tpu"
    fam = _FAMILY_ALIASES.get(r)
    if fam is None:
        raise HorovodTpuError(
            f"HVD_TPU_BACKEND must be auto|tpu|gpu (got {raw!r})"
        )
    return fam


def get(name: Optional[str] = None) -> Backend:
    """The resolved :class:`Backend` descriptor (or a named one)."""
    return BACKENDS[family(raw=name) if name is not None else family()]


def rail_labels() -> Dict[str, str]:
    """Canonical rail tag → the resolved family's physical label."""
    return dict(get().rails)


def kernel_module_name(op_class: str) -> Optional[str]:
    """Kernel-lowering table lookup for the resolved family (``None``
    for op classes the family has no fused lowering for)."""
    return get().kernels.get(op_class)


def reset() -> None:
    """Drop the platform probe cache (tests flip the env override and
    simulated platforms between cases)."""
    global _platform_cache
    with _lock:
        _platform_cache = None
