"""Cross-replica synchronized BatchNorm.

Reference: ``horovod/torch/sync_batch_norm.py`` (forward: allreduce of
per-rank mean + inverse-count-weighted var, ``:120-160``; hand-written
backward allreducing weight/bias grads) and
``horovod/tensorflow/sync_batch_norm.py`` (sum + sum-of-squares
allreduce).

TPU re-design: the moments collective is traced into the training step
— one fused ``(2F+1)``-element allreduce of
``[sum, sum_of_squares, count]`` over the mesh axis (the TF variant's
algorithm; count participates so arbitrary process sets and future
uneven batches weight correctly).  The backward pass is autodiff
through that collective: differentiating ``psum`` inserts the mirror
``psum``, which is exactly the reference's hand-written backward
(``sync_batch_norm.py:162-218``) — XLA derives it for free.

Unlike pinning flax's ``nn.BatchNorm(axis_name=...)``, this module
syncs over *any* process set (masked/ring lowering, not just XLA
replica-group partitions) and degrades gracefully outside ``shard_map``
(local moments — the single-device test/init path, matching the other
modules' convention).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from .ops import traced
from .parallel.tensor import _axis_present
from .process_sets import ProcessSet
from .runtime import WORLD_AXIS


class SyncBatchNorm(nn.Module):
    """BatchNorm whose batch moments are reduced across the mesh.

    Drop-in for ``nn.BatchNorm`` (same param/stat names: ``scale``,
    ``bias``, ``mean``, ``var``; features on the last axis); initialize
    with ``use_running_average=True`` outside ``shard_map`` (the
    collective needs the mesh axis), train inside
    ``distributed_train_step`` / ``shard_map``.

    Note: before round 3 this was a configured ``nn.BatchNorm``
    factory, so flax variable trees were keyed ``BatchNorm_<i>``;
    checkpoints from that era need the module key renamed to
    ``SyncBatchNorm_<i>`` on restore.
    """

    use_running_average: Optional[bool] = None
    axis_name: Optional[str] = WORLD_AXIS
    process_set: Optional[ProcessSet] = None
    momentum: float = 0.99  # flax nn.BatchNorm drop-in default
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    use_bias: bool = True
    use_scale: bool = True
    scale_init: Any = nn.initializers.ones
    bias_init: Any = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average,
        )
        features = x.shape[-1]
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            reduce_axes = tuple(range(x.ndim - 1))
            local_sum = jnp.sum(xf, axis=reduce_axes)
            local_sq = jnp.sum(xf * xf, axis=reduce_axes)
            local_count = jnp.asarray(
                xf.size // features, jnp.float32
            )
            if self.axis_name and _axis_present(self.axis_name):
                # One fused allreduce of [sum | sum_sq | count] — the
                # reference's two allreduces collapsed into a single
                # (2F+1)-element collective; works on arbitrary process
                # sets through the traced lowering.
                packed = jnp.concatenate(
                    [local_sum, local_sq, local_count[None]]
                )
                packed = traced.allreduce(
                    packed, axis=self.axis_name, op=traced.Sum,
                    process_set=self.process_set,
                )
                total_sum = packed[:features]
                total_sq = packed[features : 2 * features]
                count = packed[-1]
            else:  # outside shard_map: local moments (init/test path)
                total_sum, total_sq, count = local_sum, local_sq, local_count
            mean = total_sum / count
            var = total_sq / count - mean * mean
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value
                    + (1.0 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value
                    + (1.0 - self.momentum) * var
                )

        # Fold the normalization into two (F,)-sized fp32 vectors and
        # apply them in the compute dtype — the activation tensor never
        # round-trips through fp32 (the bf16 BN fast path resnet.py
        # measured at +19%): y = x * mult + shift.
        mult = lax.rsqrt(var + self.epsilon)
        if self.use_scale:
            mult = mult * self.param(
                "scale", self.scale_init, (features,), jnp.float32
            )
        shift = -mean * mult
        if self.use_bias:
            shift = shift + self.param(
                "bias", self.bias_init, (features,), jnp.float32
            )
        out_dtype = self.dtype or x.dtype
        return (
            x.astype(out_dtype) * mult.astype(out_dtype)
            + shift.astype(out_dtype)
        )
