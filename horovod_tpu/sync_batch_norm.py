"""Cross-replica synchronized BatchNorm.

Reference: ``horovod/torch/sync_batch_norm.py`` (218 LoC) and
``horovod/tensorflow/sync_batch_norm.py`` — both allreduce the batch
moments across ranks before normalizing.

On TPU this is a first-class XLA pattern: flax's ``nn.BatchNorm``
already takes ``axis_name``/``axis_index_groups`` and computes moments
with a fused cross-replica mean over the mesh axis.  ``SyncBatchNorm``
is a configured constructor pinning that axis to the world axis (or a
process-set partition), so reference users get the same drop-in name
with the collective compiled into the training step instead of a
hand-written allreduce of sum/sum-of-squares.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn

from .process_sets import ProcessSet
from .runtime import WORLD_AXIS, get_runtime


def SyncBatchNorm(
    *,
    axis_name: Optional[str] = WORLD_AXIS,
    process_set: Optional[ProcessSet] = None,
    **kwargs,
) -> nn.BatchNorm:
    """Build a BatchNorm whose moments are averaged across the mesh.

    Must run inside a ``shard_map``/``distributed_train_step`` context
    (the moments collective needs the mesh axis) — initialize the model
    with ``use_running_average=True`` (eval mode) outside it.
    ``process_set``
    restricts the sync group like the reference's ``process_set``
    argument, lowering to XLA replica groups; it must evenly partition
    the world.
    """
    groups = None
    if process_set is not None and process_set.process_set_id != 0:
        table = get_runtime().process_set_table
        groups = table.partition_groups(process_set)
        if groups is None:
            raise ValueError(
                "SyncBatchNorm process_set must evenly partition the world "
                f"(XLA replica groups); got {list(process_set.ranks)}"
            )
    return nn.BatchNorm(axis_name=axis_name, axis_index_groups=groups, **kwargs)
