"""``horovod_tpu.spark.run`` — run a function on every Spark task.

Reference: ``horovod/spark/runner.py:197`` — ``horovod.spark.run(fn)``
launches a barrier-style Spark job where each task registers with a
driver service, the driver computes the rank layout, and each task then
executes ``fn`` under the distributed env.  Here tasks host TPU worker
processes (or CPU workers in tests); the layout/rendezvous env reuses
the Ray coordinator logic.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from ..ray.runner import Coordinator
from ..utils.logging import get_logger

log = get_logger()


def _pyspark():
    try:
        import pyspark  # noqa: F811

        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires `pyspark`, which is not "
            "installed in this environment."
        ) from e


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    num_proc: Optional[int] = None,
    extra_env: Optional[dict] = None,
    verbose: int = 1,
) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` Spark tasks.

    Returns per-rank results in rank order (reference returns the same).
    Uses Spark's barrier execution mode so all tasks are scheduled
    simultaneously (the reference achieves the same with its driver/task
    registration protocol).
    """
    pyspark = _pyspark()
    spark = pyspark.sql.SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(int(sc.defaultParallelism), 1)
    kwargs = kwargs or {}
    env = dict(extra_env or {})

    def _task(iterator):
        import os
        import socket

        from pyspark import BarrierTaskContext

        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        hostname = socket.gethostname()
        # allgather (hostname, rank) to build the same layout everywhere
        infos = ctx.allGather(f"{hostname}\t{rank}")
        coordinator = Coordinator()
        for line in infos:
            h, r = line.split("\t")
            coordinator.register(h, int(r))
        worker_env = coordinator.finalize_registration()[rank]
        # rank 0's host is the jax.distributed coordinator
        coord_host = None
        for line in infos:
            h, r = line.split("\t")
            if int(r) == 0:
                coord_host = h
        os.environ.update(worker_env)
        os.environ.update(env)
        os.environ.setdefault("HVD_TPU_COORDINATOR_ADDR", f"{coord_host}:29500")
        ctx.barrier()
        yield (rank, fn(*args, **kwargs))

    rdd = sc.parallelize(range(num_proc), num_proc)
    results = rdd.barrier().mapPartitions(_task).collect()
    return [payload for _, payload in sorted(results)]
