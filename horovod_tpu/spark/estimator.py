"""Estimator API: train a JAX/flax model against a DataFrame.

Reference: ``horovod/spark/common/estimator.py:25`` (HorovodEstimator) +
``spark/keras/estimator.py`` / ``spark/torch/estimator.py`` — fit()
materializes the DataFrame to the Store, launches distributed training
via ``horovod.spark.run``, checkpoints through the Store, and returns a
model wrapper usable for inference.

TPU re-design: the model is a flax ``nn.Module`` + optax optimizer; the
training loop is our ``distributed_train_step``; data reaches workers as
numpy shards written by ``_prepare_data`` (the petastorm-parquet
equivalent — columnar npz shards, one per partition).
"""

from __future__ import annotations

import os
import cloudpickle as pickle
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from .store import LocalStore, Store
from ..utils.logging import get_logger

log = get_logger()


def _validate_store_format(store_format: str) -> None:
    """Fail at construction, not inside a Spark executor task."""
    if store_format not in ("npz", "parquet"):
        raise ValueError("store_format must be 'npz' or 'parquet'")
    if store_format == "parquet":
        from .store import parquet_available

        if not parquet_available():
            raise ValueError(
                "store_format='parquet' requires pyarrow "
                "(pip install horovod_tpu[parquet])"
            )


def _fresh_data_dir(path: str) -> None:
    """Create ``path`` and drop shards from any previous fit: a smaller
    partition count would otherwise leave stale part files that
    ``_train_worker``'s glob would mix into this run's data."""
    import glob

    os.makedirs(path, exist_ok=True)
    for pat in ("part-*.npz", "part-*.parquet"):
        for stale in glob.glob(os.path.join(path, pat)):
            os.remove(stale)


def _write_partitions(df, cols, store, fmt: str = "npz") -> str:
    """Materialize the DataFrame to the store as compressed columnar
    shards, one per Spark partition, written by the executors (reference
    ``util.prepare_data``; ``fmt="parquet"`` produces real
    snappy-compressed parquet files — the petastorm-parity format,
    ``spark/common/store.py:89-105``).  The store prefix must be a
    shared filesystem (the reference requires the same of its HDFS/DBFS
    stores)."""
    from .store import write_shard

    path = store.get_train_data_path()
    _fresh_data_dir(path)

    def write_partition(idx, rows_iter):
        rows = list(rows_iter)
        if rows:
            arrays = {c: np.asarray([row[c] for row in rows]) for c in cols}
            write_shard(os.path.join(path, f"part-{idx}"), arrays, fmt)
        yield idx

    df.select(*cols).rdd.mapPartitionsWithIndex(write_partition).count()
    return path


def _write_single_shard(store, named_arrays, fmt: str = "npz") -> str:
    """One-shard write for the Spark-free ``fit_on_arrays`` path (same
    compressed columnar formats as ``_write_partitions``)."""
    from .store import write_shard

    path = store.get_train_data_path()
    _fresh_data_dir(path)
    write_shard(os.path.join(path, "part-0"), named_arrays, fmt)
    return path


def _transform_df(df, predict, feature_col):
    """Shared Spark ``transform``: adds a ``prediction`` column via a
    pandas-free UDF over ``predict`` (reference returns a Transformer)."""
    import pyspark.sql.functions as F
    from pyspark.sql.types import ArrayType, FloatType

    @F.udf(ArrayType(FloatType()))
    def _udf(v):
        return [float(p) for p in predict(np.asarray(v)[None, ...])[0]]

    return df.withColumn("prediction", _udf(df[feature_col]))


class TpuEstimator:
    """Sklearn-style fit/predict over distributed TPU training.

    Parameters mirror the reference estimator's
    (``spark/common/params.py``): model, optimizer (an optax
    GradientTransformation factory), loss, feature/label columns,
    batch_size, epochs, store, backend options.
    """

    def __init__(
        self,
        model=None,
        optimizer=None,
        loss: Optional[Callable] = None,
        feature_cols: Sequence[str] = ("features",),
        label_cols: Sequence[str] = ("label",),
        batch_size: int = 32,
        epochs: int = 1,
        num_proc: Optional[int] = None,
        store: Optional[Store] = None,
        run_id: Optional[str] = None,
        verbose: int = 1,
        extra_env: Optional[dict] = None,
        store_format: str = "npz",
    ):
        _validate_store_format(store_format)
        if model is None:
            raise ValueError("model is required")
        if optimizer is None:
            raise ValueError("optimizer is required")
        if loss is None:
            raise ValueError("loss is required")
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.store = store or LocalStore()
        self.run_id = run_id or "run_default"
        self.verbose = verbose
        self.extra_env = extra_env
        self.store_format = store_format

    # -- checkpoint-resume (reference estimator.py:91 _has_checkpoint) ----

    def _has_checkpoint(self) -> bool:
        return self.store.load_checkpoint(self.run_id) is not None

    # -- data materialization (petastorm-parquet equivalent) --------------

    def _prepare_data(self, df) -> str:
        return _write_partitions(
            df, self.feature_cols + self.label_cols, self.store,
            fmt=self.store_format,
        )

    def fit(self, df) -> "TpuModel":
        """Distributed-train on a Spark DataFrame; returns a TpuModel."""
        data_path = self._prepare_data(df)
        from . import runner as spark_runner

        params = spark_runner.run(
            _train_worker,
            args=(
                pickle.dumps(self.model),
                pickle.dumps(self.optimizer),
                pickle.dumps(self.loss),
                data_path,
                self.feature_cols,
                self.label_cols,
                self.batch_size,
                self.epochs,
                self.store.prefix_path,
                self.run_id,
            ),
            num_proc=self.num_proc,
            extra_env=self.extra_env,
            verbose=self.verbose,
        )
        return TpuModel(model=self.model, params=params[0],
                        feature_cols=self.feature_cols)

    def fit_on_arrays(self, **named_arrays) -> "TpuModel":
        """Spark-free fit over in-memory arrays (single-controller path;
        used by tests and by notebook users without a cluster)."""
        path = _write_single_shard(self.store, named_arrays,
                                   fmt=self.store_format)
        params = _train_worker(
            pickle.dumps(self.model), pickle.dumps(self.optimizer),
            pickle.dumps(self.loss), path, self.feature_cols,
            self.label_cols, self.batch_size, self.epochs,
            self.store.prefix_path, self.run_id,
        )
        return TpuModel(model=self.model, params=params,
                        feature_cols=self.feature_cols)


def _list_parts(data_path, *, partitioned=True):
    """Part files this process should read, in order.

    Partitioned reads (reference: petastorm hands each worker its own
    row-groups, ``spark/common/store.py:89-105``): with multiple
    controller processes, each process opens only its round-robin slice
    of the part files instead of the whole dataset — the read volume
    per worker is O(data/size).  Single-controller worlds read
    everything (the in-process loader shards by index).
    """
    import glob

    import horovod_tpu as hvd

    parts = sorted(
        glob.glob(os.path.join(data_path, "part-*.npz"))
        + glob.glob(os.path.join(data_path, "part-*.parquet"))
    )
    if not parts:
        raise FileNotFoundError(f"no data shards under {data_path}")
    pc = hvd.process_count()
    did_partition = partitioned and pc > 1 and len(parts) >= pc
    if did_partition:
        parts = parts[hvd.process_rank()::pc]
    return parts, did_partition


def _load_columns(data_path, feature_cols, label_cols, *, partitioned=True):
    """Read the columnar shards back into (features, labels) in memory
    (the non-streaming path; see ``_make_loader``)."""
    parts, did_partition = _list_parts(data_path, partitioned=partitioned)
    feats, labs = _read_parts(parts, feature_cols, label_cols)
    return feats, labs, did_partition


def _read_parts(parts, feature_cols, label_cols):
    """Materialize already-listed part files into (features, labels)."""
    from .store import read_shard

    blobs = [read_shard(p) for p in parts]

    def column(c):
        return np.concatenate([b[c] for b in blobs], axis=0)

    if len(label_cols) != 1:
        raise ValueError("exactly one label column is supported")
    # Multiple feature columns are joined along the last axis (the
    # dense-assembler convention the reference's estimators use).
    if len(feature_cols) == 1:
        features = column(feature_cols[0])
    else:
        feats = [np.atleast_2d(column(c).T).T.astype(np.float32)
                 for c in feature_cols]
        features = np.concatenate(feats, axis=-1)
    labels = column(label_cols[0])
    return features, labels


class _FeatureComposingLoader:
    """Adapts a per-column streaming loader to (features, label)
    batches, joining multiple feature columns along the last axis (the
    dense-assembler convention)."""

    def __init__(self, base, n_features: int):
        self._base = base
        self._n = n_features

    def __len__(self) -> int:
        return len(self._base)

    def set_epoch(self, epoch: int) -> None:
        self._base.set_epoch(epoch)

    def __iter__(self):
        for cols in self._base:
            if self._n == 1:
                yield cols[0], cols[-1]
            else:
                feats = [
                    np.atleast_2d(np.asarray(c).T).T.astype(np.float32)
                    for c in cols[:self._n]
                ]
                yield np.concatenate(feats, axis=-1), cols[-1]


def _make_loader(data_path, feature_cols, label_cols, batch_size):
    """Build the epoch loader: streaming row-group reads when this
    process owns disjoint parts (reference petastorm loaders,
    ``spark/data_loaders/pytorch_data_loaders.py`` — epochs never
    materialize a full shard), in-memory + index sharding otherwise.
    ``HVD_TPU_STREAMING_READS=0`` forces the in-memory path.
    """
    import horovod_tpu as hvd

    from ..utils import env as _env

    if len(label_cols) != 1:
        raise ValueError("exactly one label column is supported")
    parts, did_partition = _list_parts(data_path)
    pc = hvd.process_count()
    # Index sharding (the pc>1, unpartitioned case) needs global random
    # access — streaming would feed every process identical batches.
    can_stream = did_partition or pc == 1
    if _env.get_bool("STREAMING_READS", True) and can_stream:
        from ..data import ParquetStreamLoader

        base = ParquetStreamLoader(
            parts, list(feature_cols) + list(label_cols),
            batch_size=batch_size,
            window_rows=_env.get_int("STREAM_WINDOW_ROWS", 4096),
        )
        return _FeatureComposingLoader(base, len(feature_cols)), did_partition
    feats, labs = _read_parts(parts, feature_cols, label_cols)
    from ..data import ArrayDataLoader

    loader = ArrayDataLoader(
        [np.asarray(feats), np.asarray(labs)],
        batch_size=batch_size, shard=not did_partition,
    )
    return loader, did_partition


def _sync_steps_per_epoch(loader, did_partition) -> Optional[int]:
    """Agree on steps/epoch across processes after partitioned reads.

    Returns None when index sharding is in effect (every process sees
    the same global length).  Raises instead of silently training zero
    steps when some rank's partition is smaller than one batch."""
    import horovod_tpu as hvd

    if not did_partition:
        return None
    steps = min(hvd.allgather_object(len(loader)))
    if steps == 0:
        raise ValueError(
            "partitioned data shard smaller than one batch on at least "
            "one worker (steps/epoch = 0); reduce batch_size, repartition "
            "the DataFrame, or use fewer workers"
        )
    return steps


def _train_worker(model_blob, opt_blob, loss_blob, data_path, feature_cols,
                  label_cols, batch_size, epochs, store_prefix, run_id):
    """Per-rank training body (reference ``_torch_fn``/``_keras_fn``)."""
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from .store import FilesystemStore

    model = pickle.loads(model_blob)
    optimizer = pickle.loads(opt_blob)
    loss = pickle.loads(loss_blob)
    store = FilesystemStore(store_prefix)

    hvd.init()
    loader, did_partition = _make_loader(
        data_path, feature_cols, label_cols, batch_size
    )

    # Agree on steps/epoch BEFORE touching data: a rank whose shard is
    # smaller than one batch must hit the collective diagnostic below
    # (and every rank must reach that collective), not a bare
    # StopIteration on the init probe.
    steps_per_epoch = _sync_steps_per_epoch(loader, did_partition)
    if len(loader) == 0:
        raise ValueError(
            "data shard smaller than one batch (steps/epoch = 0); "
            "reduce batch_size or provide more rows"
        )
    x0_batch = next(iter(loader))
    x0 = jnp.asarray(np.asarray(x0_batch[0])[:1], jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x0)
    # resume from a prior run's checkpoint if present
    ckpt = store.load_checkpoint(run_id)
    if ckpt is not None:
        params = jax.tree.map(jnp.asarray, ckpt)
    params = hvd.broadcast_parameters(params, root_rank=0)

    tx = hvd.DistributedOptimizer(optimizer)

    def loss_fn(p, batch):
        x, y = batch
        pred = model.apply(p, x)
        return loss(pred, y)

    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)

    # Partitioned reads already gave this process disjoint rows; index
    # sharding on top would skip data.  Collectives are per-step, so
    # all processes agreed on steps/epoch above (min across ranks).
    for epoch in range(epochs):
        loader.set_epoch(epoch)
        for i, (xb, yb) in enumerate(loader):
            if steps_per_epoch is not None and i >= steps_per_epoch:
                break
            params, opt_state, _ = step(
                params, opt_state,
                (jnp.asarray(xb, jnp.float32), jnp.asarray(yb)),
            )
    params = jax.tree.map(np.asarray, params)
    if hvd.rank() == 0:
        store.save_checkpoint(run_id, params)
    return params


class TpuModel:
    """Trained-model wrapper (reference returns a Spark Transformer;
    here ``transform`` accepts a DataFrame when pyspark is present, and
    ``predict`` always works on arrays)."""

    def __init__(self, model, params, feature_cols):
        self.model = model
        self.params = params
        self.feature_cols = feature_cols

    def predict(self, x) -> np.ndarray:
        import jax.numpy as jnp

        return np.asarray(self.model.apply(
            self.params, jnp.asarray(np.asarray(x), jnp.float32)
        ))

    def transform(self, df):
        return _transform_df(df, self.predict, self.feature_cols[0])
