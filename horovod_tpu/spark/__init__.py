"""Spark cluster integration.

Reference: ``horovod/spark/`` — ``horovod.spark.run()``
(``spark/runner.py:197``) runs a function on every Spark task with the
Horovod env set up, and the Estimator API
(``spark/common/estimator.py:25``) trains a model against a DataFrame
persisted through a ``Store``.

TPU re-design: Spark tasks are host-controllers for TPU slices; the
rank/rendezvous layout is computed exactly as in the Ray coordinator
(``horovod_tpu/ray/runner.py``).  The ``Store`` abstraction and
estimator parameter handling are pure Python (testable without a Spark
cluster); ``run()`` and ``TpuEstimator.fit`` require ``pyspark``.
"""

from .store import FilesystemStore, LocalStore, Store  # noqa: F401
from .estimator import TpuEstimator  # noqa: F401
from .keras import KerasEstimator  # noqa: F401
from .torch import TorchEstimator  # noqa: F401
from .lightning import LightningEstimator  # noqa: F401
from .runner import run  # noqa: F401
from .elastic import run_elastic  # noqa: F401
