"""Keras-style estimator: the full fit loop with validation, metrics,
callbacks and per-epoch checkpointing.

Reference: ``horovod/spark/keras/estimator.py:581`` (KerasEstimator) —
beyond the base estimator it wires metrics, a validation split, Keras
callbacks, and a checkpoint callback storing the best/latest weights in
the Store.  TPU re-design: the model is a flax module trained by
``distributed_train_step``; callbacks are the framework's own
(``horovod_tpu.callbacks``) plus any object with Keras-shaped
``on_epoch_end(epoch, logs)`` hooks; history mirrors
``keras.Model.fit`` output.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Sequence

import cloudpickle as pickle
import numpy as np

from .estimator import TpuEstimator, TpuModel, _load_columns


class KerasEstimator(TpuEstimator):
    """Fit/transform with the Keras-grade loop.

    Extra knobs vs :class:`TpuEstimator` (reference
    ``spark/keras/estimator.py`` params of the same names):

      * ``metrics``: dict name -> fn(pred, label) -> scalar, averaged
        across ranks per epoch (MetricAverageCallback semantics).
      * ``validation``: float in (0, 1) — tail fraction held out; val
        metrics computed per epoch.
      * ``callbacks``: objects with Keras-shaped ``on_epoch_begin`` /
        ``on_epoch_end(epoch, logs)`` (rank 0 only, like the reference
        which runs user callbacks on the coordinator).
      * per-epoch checkpointing to the store; ``fit`` resumes from the
        latest checkpoint when present (``_has_checkpoint``).
    """

    def __init__(self, *args,
                 metrics: Optional[Dict[str, Callable]] = None,
                 validation: Optional[float] = None,
                 callbacks: Optional[Sequence] = None,
                 shuffle: bool = True,
                 **kwargs):
        super().__init__(*args, **kwargs)
        if validation is not None and not (0.0 < validation < 1.0):
            raise ValueError("validation must be a fraction in (0, 1)")
        self.metrics = dict(metrics or {})
        self.validation = validation
        self.callbacks = list(callbacks or [])
        self.shuffle = shuffle

    def _worker_args(self, data_path: str) -> tuple:
        return (
            pickle.dumps(self.model), pickle.dumps(self.optimizer),
            pickle.dumps(self.loss), pickle.dumps(self.metrics),
            pickle.dumps(self.callbacks), data_path, self.feature_cols,
            self.label_cols, self.batch_size, self.epochs,
            self.validation, self.shuffle, self.store.prefix_path,
            self.run_id,
        )

    def fit(self, df) -> "TpuModel":
        data_path = self._prepare_data(df)
        from . import runner as spark_runner

        results = spark_runner.run(
            _keras_worker, args=self._worker_args(data_path),
            num_proc=self.num_proc, extra_env=self.extra_env,
            verbose=self.verbose,
        )
        params, history = results[0]
        model = TpuModel(model=self.model, params=params,
                         feature_cols=self.feature_cols)
        model.history = history
        return model

    def fit_on_arrays(self, **named_arrays) -> "TpuModel":
        from .estimator import _write_single_shard

        path = _write_single_shard(self.store, named_arrays,
                                   fmt=self.store_format)
        params, history = _keras_worker(*self._worker_args(path))
        model = TpuModel(model=self.model, params=params,
                         feature_cols=self.feature_cols)
        model.history = history
        return model


def _keras_worker(model_blob, opt_blob, loss_blob, metrics_blob,
                  callbacks_blob, data_path, feature_cols, label_cols,
                  batch_size, epochs, validation, shuffle, store_prefix,
                  run_id):
    """Per-rank Keras-grade loop (reference ``spark/keras/remote.py``)."""
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from .store import FilesystemStore
    from ..data import ArrayDataLoader

    model = pickle.loads(model_blob)
    optimizer = pickle.loads(opt_blob)
    loss = pickle.loads(loss_blob)
    metrics = pickle.loads(metrics_blob)
    callbacks = pickle.loads(callbacks_blob)
    store = FilesystemStore(store_prefix)

    hvd.init()
    feats, labs, did_partition = _load_columns(
        data_path, feature_cols, label_cols
    )
    feats = np.asarray(feats)
    labs = np.asarray(labs)

    # Validation split: deterministic tail fraction, identical on every
    # rank (the reference splits the parquet row set the same way).
    val = None
    if validation:
        n_val = max(1, int(len(feats) * validation))
        val = (feats[-n_val:], labs[-n_val:])
        feats, labs = feats[:-n_val], labs[:-n_val]

    x0 = jnp.asarray(feats[:1], jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x0)
    # Resume decisions are rank-0's alone: with a non-shared store only
    # the rank-0 host may see the checkpoint, and a per-rank start_epoch
    # would desynchronize the per-epoch collective counts (hang).
    start_epoch = 0
    saved_opt_state = None
    if hvd.rank() == 0:
        ckpt = store.load_checkpoint(run_id)
        if ckpt is not None:
            if isinstance(ckpt, dict) and "params" in ckpt and "epoch" in ckpt:
                params = jax.tree.map(jnp.asarray, ckpt["params"])
                start_epoch = int(ckpt["epoch"]) + 1
                saved_opt_state = ckpt.get("opt_state")
            else:  # plain-params checkpoint from the base estimator
                params = jax.tree.map(jnp.asarray, ckpt)
    start_epoch = int(hvd.broadcast_object(start_epoch, root_rank=0))
    params = hvd.broadcast_parameters(params, root_rank=0)

    tx = hvd.DistributedOptimizer(optimizer)

    def loss_fn(p, batch):
        x, y = batch
        return loss(model.apply(p, x), y)

    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)
    if bool(hvd.broadcast_object(saved_opt_state is not None, root_rank=0)):
        # Resume optimizer moments/schedule counters too — restarting
        # Adam m/v or a warmup schedule mid-run silently changes the
        # trajectory (reference estimators restore the full optimizer).
        # Rank 0 holds the restored values; everyone takes them by
        # broadcast so the moments stay bitwise-identical across ranks.
        if saved_opt_state is not None:
            opt_state = jax.tree.map(jnp.asarray, saved_opt_state)
        opt_state = hvd.broadcast_parameters(opt_state, root_rank=0)

    @jax.jit
    def evaluate(p, x, y):
        pred = model.apply(p, x)
        out = {"loss": loss(pred, y)}
        for name, fn in metrics.items():
            out[name] = fn(pred, y)
        return out

    loader = ArrayDataLoader(
        [feats, labs], batch_size=batch_size, shuffle=shuffle,
        shard=not did_partition,
    )
    from .estimator import _sync_steps_per_epoch

    steps_per_epoch = _sync_steps_per_epoch(loader, did_partition)

    history: dict = {}
    for epoch in range(start_epoch, epochs):
        for cb in callbacks:
            if hvd.rank() == 0 and hasattr(cb, "on_epoch_begin"):
                cb.on_epoch_begin(epoch, {})
        loader.set_epoch(epoch)
        losses = []
        for i, (xb, yb) in enumerate(loader):
            if steps_per_epoch is not None and i >= steps_per_epoch:
                break
            params, opt_state, l = step(
                params, opt_state,
                (jnp.asarray(xb, jnp.float32), jnp.asarray(yb)),
            )
            losses.append(l)
        local_loss = (
            float(np.mean([float(l) for l in losses]))
            if losses else float("nan")
        )
        # cross-rank average: with partitioned reads each rank trains on
        # disjoint rows, so the local mean is not representative
        logs = {"loss": float(hvd.metric_average(local_loss))}
        if val is not None:
            m = evaluate(params, jnp.asarray(val[0], jnp.float32),
                         jnp.asarray(val[1]))
            # cross-rank metric averaging (MetricAverageCallback)
            m = {f"val_{k}": float(v) for k, v in m.items()}
            m = hvd.metric_average(m)  # cross-rank average (pytree)
            logs.update({k: float(v) for k, v in m.items()})
        for k, v in logs.items():
            history.setdefault(k, []).append(v)
        if hvd.rank() == 0:
            store.save_checkpoint(
                run_id, {"params": jax.tree.map(np.asarray, params),
                         "opt_state": jax.tree.map(np.asarray, opt_state),
                         "epoch": epoch},
            )
            for cb in callbacks:
                if hasattr(cb, "on_epoch_end"):
                    cb.on_epoch_end(epoch, logs)
    return jax.tree.map(np.asarray, params), history
