"""Artifact stores for estimator training.

Reference: ``horovod/spark/common/store.py`` — a ``Store`` provides
train-data, checkpoint and logs locations (LocalStore / HDFSStore /
DBFSLocalStore).  Here checkpointing is orbax/npz against a filesystem
path; remote filesystems mount through the same interface.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
from typing import Any, Optional


class Store:
    """Base interface (reference ``store.py:40-130``)."""

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError()

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError()

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError()

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError()

    def exists(self, path: str) -> bool:
        raise NotImplementedError()

    def read(self, path: str) -> bytes:
        raise NotImplementedError()

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError()

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """Pick a store for a path (reference ``store.py:132-147``)."""
        if prefix_path.startswith(("hdfs://", "s3://", "gs://")):
            raise NotImplementedError(
                f"remote store for {prefix_path!r} requires the matching "
                "filesystem package; mount it locally and use LocalStore"
            )
        return LocalStore(prefix_path, *args, **kwargs)


class FilesystemStore(Store):
    """Store over a mounted filesystem prefix."""

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 checkpoint_path: Optional[str] = None,
                 logs_path: Optional[str] = None):
        self.prefix_path = prefix_path
        self._train = train_path or os.path.join(prefix_path, "intermediate_train_data")
        self._val = val_path or os.path.join(prefix_path, "intermediate_val_data")
        self._ckpt = checkpoint_path or os.path.join(prefix_path, "checkpoints")
        self._logs = logs_path or os.path.join(prefix_path, "logs")
        os.makedirs(prefix_path, exist_ok=True)

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        return self._train if idx is None else f"{self._train}.{idx}"

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        return self._val if idx is None else f"{self._val}.{idx}"

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self._ckpt, run_id)

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self._logs, run_id)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as fh:
            return fh.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as fh:
            fh.write(data)

    # -- checkpoint helpers used by the estimator -------------------------

    def save_checkpoint(self, run_id: str, obj: Any) -> str:
        path = os.path.join(self.get_checkpoint_path(run_id), "checkpoint.pkl")
        self.write(path, pickle.dumps(obj))
        return path

    def load_checkpoint(self, run_id: str) -> Optional[Any]:
        path = os.path.join(self.get_checkpoint_path(run_id), "checkpoint.pkl")
        if not self.exists(path):
            return None
        return pickle.loads(self.read(path))


class LocalStore(FilesystemStore):
    """Local-disk store (reference ``LocalStore``, ``store.py:223``)."""

    def __init__(self, prefix_path: Optional[str] = None, **kwargs):
        if prefix_path is None:
            prefix_path = os.path.join(tempfile.gettempdir(), "hvd_tpu_store")
        super().__init__(prefix_path, **kwargs)


# ---- columnar shard formats (reference: petastorm parquet,
# ``spark/common/store.py:89-105``) --------------------------------------
#
# Two interchangeable shard formats under the train-data path:
#   * npz  — compressed numpy archives (no extra deps, fast local path)
#   * parquet — pyarrow tables with snappy compression; N-d columns are
#     stored as FixedSizeList with the trailing shape in the schema
#     metadata, so images/embeddings round-trip exactly.  This is the
#     petastorm-parity format: real parquet files any Spark/pandas
#     reader can open.

def parquet_available() -> bool:
    try:
        import pyarrow  # noqa: F401

        return True
    except ImportError:  # pragma: no cover
        return False


def write_shard(path_no_ext: str, arrays: dict, fmt: str = "npz") -> str:
    """Write one columnar shard; returns the file path written."""
    import numpy as np

    if fmt == "npz":
        path = path_no_ext + ".npz"
        np.savez_compressed(path, **arrays)
        return path
    if fmt != "parquet":
        raise ValueError(f"unknown shard format {fmt!r}")
    import json

    import pyarrow as pa
    import pyarrow.parquet as pq

    fields = {}
    meta = {}
    for c, a in arrays.items():
        a = np.asarray(a)
        if a.ndim <= 1:
            fields[c] = pa.array(a)
        else:
            # explicit trailing product: reshape(-1) is ambiguous for
            # zero-row arrays
            flat = a.reshape(len(a), int(np.prod(a.shape[1:])))
            fields[c] = pa.FixedSizeListArray.from_arrays(
                pa.array(flat.reshape(-1)), flat.shape[1]
            )
            meta[f"shape:{c}"] = json.dumps(list(a.shape[1:]))
    table = pa.table(fields)
    if meta:
        table = table.replace_schema_metadata(
            {**(table.schema.metadata or {}),
             **{k.encode(): v.encode() for k, v in meta.items()}}
        )
    path = path_no_ext + ".parquet"
    pq.write_table(table, path, compression="snappy")
    return path


def read_shard(path: str) -> dict:
    """Read one columnar shard (either format) back to numpy arrays."""
    import numpy as np

    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    import json

    import pyarrow.parquet as pq

    table = pq.read_table(path)
    meta = {
        k.decode(): v.decode()
        for k, v in (table.schema.metadata or {}).items()
    }
    out = {}
    for c in table.column_names:
        col = table[c].combine_chunks()
        shape_key = f"shape:{c}"
        if shape_key in meta:
            trailing = tuple(json.loads(meta[shape_key]))
            flat = np.asarray(col.flatten())
            out[c] = flat.reshape((len(col),) + trailing)
        else:
            out[c] = np.asarray(col)
    return out
