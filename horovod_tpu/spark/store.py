"""Artifact stores for estimator training.

Reference: ``horovod/spark/common/store.py`` — a ``Store`` provides
train-data, checkpoint and logs locations (LocalStore / HDFSStore /
DBFSLocalStore).  Here checkpointing is orbax/npz against a filesystem
path; remote filesystems mount through the same interface.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
from typing import Any, Optional


class Store:
    """Base interface (reference ``store.py:40-130``)."""

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError()

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError()

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError()

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError()

    def exists(self, path: str) -> bool:
        raise NotImplementedError()

    def read(self, path: str) -> bytes:
        raise NotImplementedError()

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError()

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """Pick a store for a path (reference ``store.py:132-147``)."""
        if prefix_path.startswith(("hdfs://", "s3://", "gs://")):
            raise NotImplementedError(
                f"remote store for {prefix_path!r} requires the matching "
                "filesystem package; mount it locally and use LocalStore"
            )
        return LocalStore(prefix_path, *args, **kwargs)


class FilesystemStore(Store):
    """Store over a mounted filesystem prefix."""

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 checkpoint_path: Optional[str] = None,
                 logs_path: Optional[str] = None):
        self.prefix_path = prefix_path
        self._train = train_path or os.path.join(prefix_path, "intermediate_train_data")
        self._val = val_path or os.path.join(prefix_path, "intermediate_val_data")
        self._ckpt = checkpoint_path or os.path.join(prefix_path, "checkpoints")
        self._logs = logs_path or os.path.join(prefix_path, "logs")
        os.makedirs(prefix_path, exist_ok=True)

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        return self._train if idx is None else f"{self._train}.{idx}"

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        return self._val if idx is None else f"{self._val}.{idx}"

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self._ckpt, run_id)

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self._logs, run_id)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as fh:
            return fh.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as fh:
            fh.write(data)

    # -- checkpoint helpers used by the estimator -------------------------

    def save_checkpoint(self, run_id: str, obj: Any) -> str:
        path = os.path.join(self.get_checkpoint_path(run_id), "checkpoint.pkl")
        self.write(path, pickle.dumps(obj))
        return path

    def load_checkpoint(self, run_id: str) -> Optional[Any]:
        path = os.path.join(self.get_checkpoint_path(run_id), "checkpoint.pkl")
        if not self.exists(path):
            return None
        return pickle.loads(self.read(path))


class LocalStore(FilesystemStore):
    """Local-disk store (reference ``LocalStore``, ``store.py:223``)."""

    def __init__(self, prefix_path: Optional[str] = None, **kwargs):
        if prefix_path is None:
            prefix_path = os.path.join(tempfile.gettempdir(), "hvd_tpu_store")
        super().__init__(prefix_path, **kwargs)
