"""Lightning-style estimator: trains modules that implement the
PyTorch-Lightning protocol.

Reference: ``horovod/spark/lightning/estimator.py:619``
(LightningEstimator) + ``spark/lightning/remote.py`` — wraps a
``LightningModule`` in a horovod-strategy Trainer on the executors.
TPU re-design: no dependency on the ``pytorch_lightning`` package —
the estimator drives any object speaking the *protocol* (duck-typed:
``training_step(batch, batch_idx)``, ``configure_optimizers()``, and
optionally ``validation_step``/``on_train_epoch_end``), which real
``LightningModule`` subclasses satisfy when lightning IS installed.
Gradient averaging rides
:class:`horovod_tpu.interop.torch.DistributedOptimizer`, per-epoch
state checkpoints go through the Store (resume like the reference's
``_has_checkpoint``), and per-epoch train/val metrics come back as a
Keras-shaped history dict.
"""

from __future__ import annotations

from typing import Optional, Sequence

import cloudpickle as pickle
import numpy as np

from .estimator import _load_columns
from .store import LocalStore, Store
from .torch import TorchModel

_PROTOCOL = ("training_step", "configure_optimizers")


def _check_protocol(model) -> None:
    missing = [m for m in _PROTOCOL if not callable(getattr(model, m, None))]
    if missing:
        raise TypeError(
            f"model does not implement the lightning protocol: missing "
            f"{missing} (a pytorch_lightning.LightningModule, or any "
            f"torch.nn.Module defining them, works)"
        )


class LightningEstimator:
    """Sklearn-style fit/predict over a lightning-protocol module.

    Unlike :class:`~horovod_tpu.spark.torch.TorchEstimator` there is no
    ``loss``/``optimizer`` argument: the module's own ``training_step``
    computes the loss and ``configure_optimizers`` builds the optimizer,
    exactly as lightning defines them (reference estimator passes the
    module to a lightning Trainer for the same reason).
    """

    def __init__(
        self,
        model=None,
        feature_cols: Sequence[str] = ("features",),
        label_cols: Sequence[str] = ("label",),
        batch_size: int = 32,
        epochs: int = 1,
        validation: Optional[float] = None,
        backward_passes_per_step: int = 1,
        num_proc: Optional[int] = None,
        store: Optional[Store] = None,
        run_id: Optional[str] = None,
        verbose: int = 1,
        extra_env: Optional[dict] = None,
        store_format: str = "npz",
    ):
        from .estimator import _validate_store_format

        _validate_store_format(store_format)
        if model is None:
            raise ValueError("model is required")
        _check_protocol(model)
        if validation is not None and not (0.0 < validation < 1.0):
            raise ValueError("validation must be a fraction in (0, 1)")
        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.validation = validation
        self.backward_passes_per_step = backward_passes_per_step
        self.num_proc = num_proc
        self.store = store or LocalStore()
        self.run_id = run_id or "run_lightning_default"
        self.verbose = verbose
        self.extra_env = extra_env
        self.store_format = store_format

    def _has_checkpoint(self) -> bool:
        return self.store.load_checkpoint(self.run_id) is not None

    def _worker_args(self, data_path: str) -> tuple:
        return (
            pickle.dumps(self.model), data_path, self.feature_cols,
            self.label_cols, self.batch_size, self.epochs,
            self.validation, self.backward_passes_per_step,
            self.store.prefix_path, self.run_id,
        )

    def fit(self, df) -> "TorchModel":
        from .estimator import _write_partitions
        from . import runner as spark_runner

        data_path = _write_partitions(
            df, self.feature_cols + self.label_cols, self.store,
            fmt=self.store_format,
        )
        results = spark_runner.run(
            _lightning_worker, args=self._worker_args(data_path),
            num_proc=self.num_proc, extra_env=self.extra_env,
            verbose=self.verbose,
        )
        return self._wrap(results[0])

    def fit_on_arrays(self, **named_arrays) -> "TorchModel":
        from .estimator import _write_single_shard

        return self._wrap(
            _lightning_worker(
                *self._worker_args(_write_single_shard(
                    self.store, named_arrays, fmt=self.store_format
                ))
            )
        )

    def _wrap(self, result) -> "TorchModel":
        import torch

        state_np, history = result
        model = self.model
        model.load_state_dict(
            {k: torch.as_tensor(v) for k, v in state_np.items()}
        )
        wrapped = TorchModel(model=model, feature_cols=self.feature_cols)
        wrapped.history = history
        return wrapped


def _lightning_worker(model_blob, data_path, feature_cols, label_cols,
                      batch_size, epochs, validation, bpps, store_prefix,
                      run_id):
    """Per-rank lightning loop (reference ``spark/lightning/remote.py``:
    the Trainer body — broadcast, training_step loop with hvd-wrapped
    optimizer, validation_step epoch end, rank-0 checkpoint)."""
    import torch

    import horovod_tpu as hvd
    import horovod_tpu.interop.torch as hvd_torch
    from .store import FilesystemStore
    from ..data import ArrayDataLoader

    model = pickle.loads(model_blob)
    store = FilesystemStore(store_prefix)

    hvd.init()
    feats, labs, did_partition = _load_columns(
        data_path, feature_cols, label_cols
    )
    feats = np.asarray(feats)
    labs = np.asarray(labs)

    val = None
    if validation:
        n_val = max(1, int(len(feats) * validation))
        val = (feats[-n_val:], labs[-n_val:])
        feats, labs = feats[:-n_val], labs[:-n_val]

    # Resume decisions are rank-0's alone: with a non-shared store only
    # the rank-0 host may see the checkpoint, and a per-rank start_epoch
    # would desynchronize the per-epoch collective counts (hang).  The
    # broadcast below distributes both the weights and the epoch.
    start_epoch = 0
    ckpt = None
    if hvd.rank() == 0:
        ckpt = store.load_checkpoint(run_id)
        if ckpt is not None and isinstance(ckpt, dict) and "state" in ckpt:
            model.load_state_dict(
                {k: torch.as_tensor(v) for k, v in ckpt["state"].items()}
            )
            start_epoch = int(ckpt["epoch"]) + 1
    start_epoch = int(hvd.broadcast_object(start_epoch, root_rank=0))
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)

    configured = model.configure_optimizers()
    # lightning allows optimizer | (optimizer_list, scheduler_list) |
    # list | {'optimizer': ..., 'lr_scheduler': ...}.  The
    # (optimizers, schedulers) two-tuple form has BOTH elements as
    # lists per lightning's contract — a bare 2-tuple of optimizers is
    # multiple optimizers, of which this loop drives the first.
    schedulers = []
    if isinstance(configured, dict):
        optimizer = configured["optimizer"]
        sch = configured.get("lr_scheduler")
        if isinstance(sch, dict):  # lightning's scheduler-config dict
            sch = sch.get("scheduler")
        schedulers = [sch] if sch is not None else []
    elif (isinstance(configured, tuple) and len(configured) == 2
          and isinstance(configured[0], (list, tuple))
          and isinstance(configured[1], (list, tuple))):
        optimizers, schedulers = configured
        optimizer = optimizers[0]
    elif isinstance(configured, (list, tuple)):
        optimizer = configured[0]
    else:
        optimizer = configured
    optimizer = hvd_torch.DistributedOptimizer(
        optimizer, backward_passes_per_step=bpps
    )
    schedulers = [s for s in (schedulers if isinstance(
        schedulers, (list, tuple)) else [schedulers]) if s is not None]

    # Resume the optimizer moments and scheduler counters too —
    # restarting Adam m/v or an LR schedule mid-run silently changes
    # the trajectory.  Rank 0 read the checkpoint; everyone receives
    # the same state by object broadcast, keeping ranks identical.
    ckpt_d = ckpt if isinstance(ckpt, dict) else {}
    resume = hvd.broadcast_object(
        {"opt": ckpt_d.get("opt"), "sched": ckpt_d.get("sched")}
        if hvd.rank() == 0 else None,
        root_rank=0,
    )
    if resume.get("opt") is not None:
        optimizer.load_state_dict(resume["opt"])
    for sch, st in zip(schedulers, resume.get("sched") or []):
        sch.load_state_dict(st)

    loader = ArrayDataLoader(
        [feats, labs], batch_size=batch_size, shard=not did_partition,
    )
    from .estimator import _sync_steps_per_epoch

    steps_per_epoch = _sync_steps_per_epoch(loader, did_partition)

    history: dict = {}
    model.train()
    global_calls = 0
    for epoch in range(start_epoch, epochs):
        loader.set_epoch(epoch)
        losses = []
        for i, (xb, yb) in enumerate(loader):
            if steps_per_epoch is not None and i >= steps_per_epoch:
                break
            batch = (
                torch.as_tensor(np.asarray(xb), dtype=torch.float32),
                torch.as_tensor(np.asarray(yb)),
            )
            loss = model.training_step(batch, i)
            if isinstance(loss, dict):  # lightning allows {'loss': ...}
                loss = loss["loss"]
            loss.backward()
            optimizer.step()
            global_calls += 1
            if global_calls % bpps == 0:
                optimizer.zero_grad()
            losses.append(float(loss.detach()))
        for sch in schedulers:
            if hasattr(sch, "step"):
                sch.step()
        local_loss = float(np.mean(losses)) if losses else float("nan")
        logs = {"loss": float(hvd.metric_average(local_loss))}
        if val is not None and callable(getattr(model, "validation_step",
                                                None)):
            model.eval()
            with torch.no_grad():
                out = model.validation_step(
                    (torch.as_tensor(val[0], dtype=torch.float32),
                     torch.as_tensor(val[1])), 0,
                )
            model.train()
            if not isinstance(out, dict):
                out = {"val_loss": out}
            out = {
                (k if k.startswith("val_") else f"val_{k}"):
                float(torch.as_tensor(v).detach())
                for k, v in out.items()
            }
            logs.update(hvd.metric_average(out))
        hook = getattr(model, "on_train_epoch_end", None)
        if callable(hook):
            # Call only the modern zero-arg form; the legacy signature
            # (taking epoch outputs, which this loop does not collect)
            # is skipped by inspection rather than by swallowing
            # TypeErrors the user's own hook body might raise.
            import inspect

            try:
                required = [
                    p for p in inspect.signature(hook).parameters.values()
                    if p.default is p.empty and p.kind in (
                        p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                ]
            except (ValueError, TypeError):
                required = None
            if required == []:
                hook()
        for k, v in logs.items():
            history.setdefault(k, []).append(float(v))
        if hvd.rank() == 0:
            store.save_checkpoint(
                run_id,
                {"state": {k: v.detach().cpu().numpy()
                           for k, v in model.state_dict().items()},
                 "opt": optimizer.state_dict(),
                 "sched": [s.state_dict() for s in schedulers
                           if hasattr(s, "state_dict")],
                 "epoch": epoch},
            )

    state_np = {
        k: v.detach().cpu().numpy() for k, v in model.state_dict().items()
    }
    return state_np, history
