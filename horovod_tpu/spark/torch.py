"""Torch-style estimator: trains a ``torch.nn.Module`` with the
interop collective bridge.

Reference: ``horovod/spark/torch/estimator.py:506`` (TorchEstimator) —
takes a torch model + a torch loss + an optimizer factory, trains it
data-parallel on the executors, checkpoints the ``state_dict`` through
the Store, and returns a transformer.  TPU re-design: the torch model
stays on host CPU (torch has no TPU backend here); gradient averaging
rides the runtime's eager collectives through
``horovod_tpu.interop.torch.DistributedOptimizer``, so multi-process
fits synchronize exactly like the reference's hooks-and-allreduce
loop.
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Sequence

import cloudpickle as pickle
import numpy as np

from .estimator import _load_columns
from .store import LocalStore, Store


class TorchEstimator:
    """Sklearn-style fit/predict over a torch model.

    ``optimizer`` is a factory ``params_iterable -> torch.optim
    .Optimizer`` (the reference passes a torch optimizer instance and
    re-binds it remotely; a factory is the pickle-clean equivalent).
    """

    def __init__(
        self,
        model=None,
        optimizer: Optional[Callable] = None,
        loss: Optional[Callable] = None,
        feature_cols: Sequence[str] = ("features",),
        label_cols: Sequence[str] = ("label",),
        batch_size: int = 32,
        epochs: int = 1,
        backward_passes_per_step: int = 1,
        num_proc: Optional[int] = None,
        store: Optional[Store] = None,
        run_id: Optional[str] = None,
        verbose: int = 1,
        extra_env: Optional[dict] = None,
        store_format: str = "npz",
    ):
        from .estimator import _validate_store_format

        _validate_store_format(store_format)
        self.store_format = store_format
        if model is None or optimizer is None or loss is None:
            raise ValueError("model, optimizer and loss are required")
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.backward_passes_per_step = backward_passes_per_step
        self.num_proc = num_proc
        self.store = store or LocalStore()
        self.run_id = run_id or "run_torch_default"
        self.verbose = verbose
        self.extra_env = extra_env

    def _has_checkpoint(self) -> bool:
        return self.store.load_checkpoint(self.run_id) is not None

    def _worker_args(self, data_path: str) -> tuple:
        return (
            pickle.dumps(self.model), pickle.dumps(self.optimizer),
            pickle.dumps(self.loss), data_path, self.feature_cols,
            self.label_cols, self.batch_size, self.epochs,
            self.backward_passes_per_step, self.store.prefix_path,
            self.run_id,
        )

    def fit(self, df) -> "TorchModel":
        from .estimator import _write_partitions

        data_path = _write_partitions(
            df, self.feature_cols + self.label_cols, self.store,
            fmt=self.store_format,
        )
        from . import runner as spark_runner

        results = spark_runner.run(
            _torch_worker, args=self._worker_args(data_path),
            num_proc=self.num_proc, extra_env=self.extra_env,
            verbose=self.verbose,
        )
        return self._wrap(results[0])

    def fit_on_arrays(self, **named_arrays) -> "TorchModel":
        from .estimator import _write_single_shard

        return self._wrap(
            _torch_worker(
                *self._worker_args(_write_single_shard(
                    self.store, named_arrays, fmt=self.store_format
                ))
            )
        )

    def _wrap(self, state_np) -> "TorchModel":
        import torch

        model = self.model
        state = {k: torch.as_tensor(v) for k, v in state_np.items()}
        model.load_state_dict(state)
        return TorchModel(model=model, feature_cols=self.feature_cols)


def _torch_worker(model_blob, opt_blob, loss_blob, data_path, feature_cols,
                  label_cols, batch_size, epochs, bpps, store_prefix,
                  run_id):
    """Per-rank torch training body (reference ``spark/torch/remote.py``:
    broadcast initial state -> hooks-allreduce loop -> rank-0
    checkpoint)."""
    import torch

    import horovod_tpu as hvd
    import horovod_tpu.interop.torch as hvd_torch
    from .store import FilesystemStore
    from ..data import ArrayDataLoader

    model = pickle.loads(model_blob)
    opt_factory = pickle.loads(opt_blob)
    loss_fn = pickle.loads(loss_blob)
    store = FilesystemStore(store_prefix)

    hvd.init()
    feats, labs, did_partition = _load_columns(
        data_path, feature_cols, label_cols
    )

    ckpt = store.load_checkpoint(run_id)
    if ckpt is not None:
        model.load_state_dict(
            {k: torch.as_tensor(v) for k, v in ckpt.items()}
        )
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)

    optimizer = hvd_torch.DistributedOptimizer(
        opt_factory(model.parameters()),
        backward_passes_per_step=bpps,
    )

    loader = ArrayDataLoader(
        [np.asarray(feats), np.asarray(labs)], batch_size=batch_size,
        shard=not did_partition,
    )
    from .estimator import _sync_steps_per_epoch

    steps_per_epoch = _sync_steps_per_epoch(loader, did_partition)

    model.train()
    # zero_grad must follow the optimizer's own global call counter, not
    # a per-epoch index: when steps/epoch is not a multiple of bpps the
    # two schedules would drift and re-apply stale gradients.
    global_calls = 0
    for epoch in range(epochs):
        loader.set_epoch(epoch)
        for i, (xb, yb) in enumerate(loader):
            if steps_per_epoch is not None and i >= steps_per_epoch:
                break
            x = torch.as_tensor(np.asarray(xb), dtype=torch.float32)
            y = torch.as_tensor(np.asarray(yb))
            loss = loss_fn(model(x), y)
            loss.backward()
            optimizer.step()
            global_calls += 1
            if global_calls % bpps == 0:
                optimizer.zero_grad()

    state_np = {
        k: v.detach().cpu().numpy() for k, v in model.state_dict().items()
    }
    if hvd.rank() == 0:
        store.save_checkpoint(run_id, state_np)
    return state_np


class TorchModel:
    """Trained torch model wrapper (reference returns a Transformer)."""

    def __init__(self, model, feature_cols):
        self.model = model
        self.feature_cols = feature_cols

    def predict(self, x) -> np.ndarray:
        import torch

        self.model.eval()
        with torch.no_grad():
            out = self.model(
                torch.as_tensor(np.asarray(x), dtype=torch.float32)
            )
        return out.numpy()

    def transform(self, df):
        from .estimator import _transform_df

        return _transform_df(df, self.predict, self.feature_cols[0])
