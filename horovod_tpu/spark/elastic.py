"""Elastic Horovod on Spark.

Reference: ``horovod/spark/runner.py:29`` (``run_elastic`` →
``gloo_run_elastic``), the task/driver service protocol
(``horovod/spark/driver/driver_service.py``,
``task_service.py``), and the integration suite
``test/integration/elastic_spark_common.py``.

Architecture (TPU recast): Spark tasks are long-lived HOST AGENTS, not
workers.  Each task runs :func:`task_agent_main`: it heartbeats a
registration into the driver's HMAC KV store and serves exec requests —
spawn this command with this env, report the exit code, honor
termination.  The elastic driver (``runner/elastic_driver.py``) then
runs its membership-round loop exactly as it does over ssh hosts, but
with :class:`SparkWorkerProcess` dispatching round workers THROUGH the
agents.  An executor loss drops its agent out of discovery via
heartbeat expiry (and any in-flight worker reports lost); Spark's task
retry schedules a fresh agent that re-registers — the reference's
task-service re-registration recast onto the KV transport the rest of
this launcher already uses.
"""

from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..elastic.discovery import HostDiscovery, HostManager
from ..runner import controller_py
from ..runner.elastic_driver import ElasticDriver
from ..utils.logging import get_logger

log = get_logger()

AGENT_SCOPE = "__spark_agents__"
CMD_SCOPE = "__spark_cmd__"
RC_SCOPE = "__spark_rc__"
KILL_SCOPE = "__spark_kill__"
STOP_SCOPE = "__spark_stop__"
HEARTBEAT_S = 0.5
AGENT_STALE_S = 5.0


# ---- agent side (runs inside each Spark task) ---------------------------

def _die_with_parent():
    """preexec hook: a worker must not outlive its agent (Spark kills
    the whole executor; the local backend mirrors that via Linux
    PDEATHSIG)."""
    try:
        import ctypes
        import signal as _signal

        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.prctl(1, _signal.SIGKILL)  # PR_SET_PDEATHSIG
    except Exception:
        pass


def task_agent_main(index: int, addr: str, port: int, secret: str,
                    host_label: Optional[str] = None,
                    heartbeat_s: float = HEARTBEAT_S) -> None:
    """Serve exec requests until the driver posts the stop flag
    (reference ``SparkTaskService``: ``run_command`` /
    ``command_exit_code`` / ``terminate`` RPCs, recast as KV polling).

    Each agent incarnation carries a unique ``attempt`` id (a respawned
    Spark task attempt): command/rc/kill keys are attempt-scoped, so a
    fresh attempt can never replay a dead predecessor's commands.
    """
    import secrets as pysecrets

    client = controller_py.make_client(addr, port, secret, rank=index)
    hb_client = controller_py.make_client(addr, port, secret, rank=index)
    host = host_label or socket.gethostname()
    attempt = pysecrets.token_hex(4)
    stop = threading.Event()

    def heartbeat():
        while not stop.is_set():
            try:
                hb_client.put(AGENT_SCOPE, str(index), pickle.dumps({
                    "host": host, "slots": 1, "pid": os.getpid(),
                    "attempt": attempt, "ts": time.time(),
                }))
            except OSError:
                return  # driver gone: Spark will retry or tear us down
            stop.wait(heartbeat_s)

    hb = threading.Thread(target=heartbeat, daemon=True)
    hb.start()
    seq = 0
    try:
        while True:
            if client.get(STOP_SCOPE, "all", timeout_ms=0) is not None:
                return
            key = f"{index}:{attempt}:{seq}"
            blob = client.get(CMD_SCOPE, key, timeout_ms=200)
            if blob is None:
                continue
            argv, env = pickle.loads(blob)
            full_env = dict(os.environ)
            full_env.update(env)
            proc = subprocess.Popen(
                argv, env=full_env, preexec_fn=_die_with_parent
            )
            while proc.poll() is None:
                if client.get(KILL_SCOPE, key, timeout_ms=0) is not None:
                    proc.terminate()
                    try:
                        proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()
                    break
                time.sleep(0.1)
            client.put(RC_SCOPE, key, str(proc.wait()).encode())
            seq += 1
    finally:
        stop.set()
        client.close()
        hb_client.close()


# ---- driver side --------------------------------------------------------

class _AgentTable:
    """Driver-side view of registered agents (heartbeat freshness).

    Lookups are cached briefly: ``_watch_round`` polls every pending
    worker's ``returncode`` at 10 Hz, and an uncached table would issue
    O(np²)·10 KV round-trips per second against the same server the
    heartbeats need (starved heartbeats would then report healthy
    workers as lost)."""

    _CACHE_S = 0.5

    def __init__(self, client, num_agents: int):
        self._client = client
        self._n = num_agents
        self._lock = threading.Lock()
        self._cached: Dict[int, dict] = {}
        self._cached_at = 0.0

    def live_agents(self) -> Dict[int, dict]:
        with self._lock:
            now = time.time()
            if now - self._cached_at <= self._CACHE_S:
                return dict(self._cached)
            out: Dict[int, dict] = {}
            for i in range(self._n):
                blob = self._client.get(AGENT_SCOPE, str(i), timeout_ms=0)
                if blob is None:
                    continue
                info = pickle.loads(blob)
                if now - info["ts"] <= AGENT_STALE_S:
                    out[i] = info
            self._cached, self._cached_at = out, now
            return dict(out)


class SparkTaskDiscovery(HostDiscovery):
    """Hosts = live registered agents, slots aggregated per host label
    (reference: the driver service's registered-task view)."""

    def __init__(self, table: _AgentTable):
        self._table = table

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        hosts: Dict[str, int] = {}
        for info in self._table.live_agents().values():
            hosts[info["host"]] = hosts.get(info["host"], 0) + info["slots"]
        return hosts


class SparkWorkerProcess:
    """WorkerProcess-shaped handle over one agent-dispatched command
    (duck-typed for ``ElasticDriver._watch_round``: ``returncode`` /
    ``terminate`` / ``wait`` / ``rank`` / ``hostname``)."""

    def __init__(self, rank: int, hostname: str, command: List[str],
                 env: Dict[str, str], *, client, table: _AgentTable,
                 agent_index: int, attempt: str, seq: int):
        self.rank = rank
        self.hostname = hostname
        self._client = client
        self._table = table
        self._key = f"{agent_index}:{attempt}:{seq}"
        self._agent = agent_index
        self._attempt = attempt
        self._rc: Optional[int] = None
        client.put(CMD_SCOPE, self._key, pickle.dumps((command, env)))

    @property
    def returncode(self) -> Optional[int]:
        if self._rc is not None:
            return self._rc
        blob = self._client.get(RC_SCOPE, self._key, timeout_ms=0)
        if blob is not None:
            self._rc = int(blob.decode())
            return self._rc
        live = self._table.live_agents().get(self._agent)
        if live is None or live.get("attempt") != self._attempt:
            # executor died with the worker on it (a respawned attempt
            # does NOT own this command): report the loss — the
            # reference sees the same through a dropped task connection
            self._rc = 1
            return self._rc
        return None

    def terminate(self) -> None:
        self._client.put(KILL_SCOPE, self._key, b"1")

    def wait(self, timeout: Optional[float] = None) -> int:
        # timeout=0 is a valid immediate-deadline poll, not "no deadline"
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        while True:
            rc = self.returncode
            if rc is not None:
                return rc
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(f"worker {self.rank} did not exit")
            time.sleep(0.1)


class _AgentWorkerFactory:
    """Maps (hostname, slot) -> a live agent on that host; allocates one
    dispatch sequence number per agent."""

    def __init__(self, client, table: _AgentTable):
        self._client = client
        self._table = table
        self._seq: Dict[tuple, int] = {}
        self._round_claimed: List[int] = []

    def begin_round(self, round_id: int) -> None:
        """run_rounds calls this before each round's spawn loop (the
        worker_factory protocol) — reset the per-round agent claims."""
        self._round_claimed = []

    def __call__(self, rank, hostname, command, env, ssh_port=None,
                 ssh_identity_file=None) -> SparkWorkerProcess:
        live = self._table.live_agents()
        candidates = [
            i for i, info in sorted(live.items())
            if info["host"] == hostname and i not in self._round_claimed
        ]
        if not candidates:
            raise RuntimeError(
                f"no live Spark agent on host {hostname!r} for rank {rank}"
            )
        agent = candidates[0]
        attempt = live[agent]["attempt"]
        self._round_claimed.append(agent)
        seq = self._seq.get((agent, attempt), 0)
        self._seq[(agent, attempt)] = seq + 1
        return SparkWorkerProcess(
            rank, hostname, command, env, client=self._client,
            table=self._table, agent_index=agent, attempt=attempt, seq=seq,
        )


def _driver_addr() -> str:
    """Address remote executors can dial to reach this driver.

    Spark already knows it (``spark.driver.host`` is what executors use
    for the driver RPC); fall back to the default-route NIC (UDP
    connect trick), then the resolver.  Plain
    ``gethostbyname(gethostname())`` is NOT safe here: Debian-style
    /etc/hosts maps the hostname to 127.0.1.1 and remote agents would
    dial their own loopback (cf. ``exec_utils.probe_routable_addr`` —
    the ssh probe itself has no transport to run over on Spark).
    """
    try:
        import pyspark

        spark = pyspark.sql.SparkSession.builder.getOrCreate()
        host = spark.sparkContext.getConf().get("spark.driver.host")
        if host:
            return host
    except Exception:
        pass
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))  # no packet sent: route lookup only
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return socket.gethostbyname(socket.gethostname())


def _launch_spark_agents(num_proc: int, addr: str, port: int,
                         secret: str) -> Callable[[], None]:
    """Start ``num_proc`` long-lived agent tasks as an async Spark job
    (NON-barrier: tasks are independent hosts, and Spark's per-task
    retry is exactly the respawn mechanism elastic wants).  Returns a
    cleanup callable."""
    from .runner import _pyspark

    pyspark = _pyspark()

    spark = pyspark.sql.SparkSession.builder.getOrCreate()
    sc = spark.sparkContext

    def agent_partition(split_index, _it):
        task_agent_main(split_index, addr, port, secret)
        yield split_index

    rdd = sc.parallelize(range(num_proc), num_proc)
    # async action: the driver thread continues into the round loop
    thread = threading.Thread(
        target=lambda: rdd.mapPartitionsWithIndex(agent_partition).collect(),
        daemon=True,
    )
    thread.start()
    return lambda: thread.join(timeout=10)


class LocalAgentBackend:
    """Agent backend for environments without pyspark (and for the
    integration tests): agents are local subprocesses, and a watchdog
    respawns dead ones exactly as Spark task retry would."""

    def __init__(self, num_proc: int, addr: str, port: int, secret: str,
                 host_labels: Optional[List[str]] = None):
        self.num_proc = num_proc
        self._args = (addr, port, secret)
        self._labels = host_labels or [
            f"127.0.0.{i + 1}" for i in range(num_proc)
        ]
        self._procs: Dict[int, subprocess.Popen] = {}
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None

    def _spawn(self, i: int) -> None:
        addr, port, secret = self._args
        code = (
            "import sys; from horovod_tpu.spark.elastic import "
            "task_agent_main; task_agent_main(int(sys.argv[1]), "
            "sys.argv[2], int(sys.argv[3]), sys.argv[4], "
            "host_label=sys.argv[5])"
        )
        env = dict(os.environ)
        env.setdefault("PYTHONPATH", "")
        self._procs[i] = subprocess.Popen(
            [sys.executable, "-c", code, str(i), addr, str(port), secret,
             self._labels[i]],
            env=env,
        )

    def start(self) -> None:
        for i in range(self.num_proc):
            self._spawn(i)

        def watch():
            while not self._stop.is_set():
                for i, p in list(self._procs.items()):
                    if p.poll() is not None and not self._stop.is_set():
                        log.warning(
                            "agent %d died (rc=%s); respawning (the "
                            "Spark-task-retry analog)", i, p.returncode,
                        )
                        self._spawn(i)
                self._stop.wait(0.5)

        self._watchdog = threading.Thread(target=watch, daemon=True)
        self._watchdog.start()

    def kill_agent(self, i: int) -> None:
        """Test hook: simulate an executor loss."""
        self._procs[i].kill()

    def stop(self) -> None:
        self._stop.set()
        if self._watchdog:
            self._watchdog.join(timeout=5)
        for p in self._procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self._procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def run_elastic(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    num_proc: Optional[int] = None,
    min_np: Optional[int] = None,
    max_np: Optional[int] = None,
    extra_env: Optional[Dict[str, str]] = None,
    reset_limit: Optional[int] = None,
    verbose: int = 1,
    _backend: Optional[Any] = None,
) -> List[Any]:
    """Run ``fn`` elastically on Spark (reference
    ``horovod.spark.run_elastic``, ``spark/runner.py:29``): Spark tasks
    host the workers, worker loss blacklists the host and starts a new
    round, Spark task retries re-register fresh hosts, and the job
    completes when a round of workers all exit cleanly.

    Returns the per-rank results of the successful round (rank order).
    ``_backend`` swaps the Spark task layer for another agent
    transport: ``"local"`` builds a :class:`LocalAgentBackend`
    (subprocess agents + respawn watchdog — the pyspark-free test
    harness and single-machine path).
    """
    import cloudpickle
    import secrets as pysecrets

    kwargs = kwargs or {}
    if num_proc is None:
        num_proc = min_np or 1
    min_np = min_np or num_proc
    if _backend is None:
        # Gate BEFORE binding any server socket: a missing pyspark must
        # raise cleanly, not leak the registration server.
        from .runner import _pyspark

        _pyspark()
    secret = pysecrets.token_hex(16)
    # Agent-registration KV server (separate from the per-job rendezvous
    # server run_rounds owns).
    server = controller_py.make_server(secret, num_proc)
    addr = "127.0.0.1" if _backend is not None else _driver_addr()
    client = controller_py.make_client(
        "127.0.0.1", server.port, secret, rank=-1
    )
    table = _AgentTable(client, num_proc)

    backend = _backend
    if backend == "local":
        backend = LocalAgentBackend(
            num_proc, "127.0.0.1", server.port, secret
        )
    cleanup: Optional[Callable] = None
    if backend is None:
        cleanup = _launch_spark_agents(num_proc, addr, server.port, secret)
    elif isinstance(backend, LocalAgentBackend):
        backend.start()

    factory = _AgentWorkerFactory(client, table)
    driver = ElasticDriver(
        HostManager(SparkTaskDiscovery(table)),
        min_np=min_np, max_np=max_np or num_proc, reset_limit=reset_limit,
    )
    results: Dict[int, Any] = {}

    def collect(control, np_: int, round_id: int) -> None:
        for r in range(np_):
            blob = control.get(
                "__results__", f"r{round_id}:{r}", timeout_ms=30_000
            )
            if blob is None:
                raise RuntimeError(f"rank {r} published no result")
            status, payload = pickle.loads(blob)
            if status != "ok":
                raise RuntimeError(f"rank {r} failed:\n{payload}")
            results[r] = payload

    payload = cloudpickle.dumps((fn, args, kwargs))
    try:
        driver.start_discovery()
        rc = driver.run_rounds(
            [sys.executable, "-m", "horovod_tpu.runner.task_runner"],
            extra_env=extra_env,
            publish={("__run__", "func"): payload},
            worker_factory=factory,
            rendezvous_addr=addr,
            result_collector=collect,
        )
        if rc != 0:
            raise RuntimeError(f"elastic Spark job failed with code {rc}")
        return [results[r] for r in sorted(results)]
    finally:
        try:
            client.put(STOP_SCOPE, "all", b"1")
            time.sleep(HEARTBEAT_S)
        except OSError:
            pass
        if isinstance(backend, LocalAgentBackend):
            backend.stop()
        if cleanup is not None:
            cleanup()
        client.close()
        server.stop()
