"""Global runtime: device mesh, rank topology, process sets.

TPU-native replacement for the reference's C++ ``HorovodGlobalState`` +
``BackgroundThreadLoop`` (``horovod/common/global_state.h:39``,
``horovod/common/operations.cc:381``).  The reference spawns a background
thread per process that negotiates tensor readiness over MPI/Gloo; under
XLA SPMD every rank compiles the identical program, so op ordering is
agreed *by construction* and no negotiation service is needed.  What
remains global is: the 1-D device mesh (the "communicator"), rank
topology, the process-set table, and observability (timeline/autotune),
which this module owns.

Rank model (device granularity — one TPU chip == one reference rank):
  * ``size``        — total chips in the mesh (reference ``horovod_size``)
  * ``rank``        — global index of this *process's* first chip; with one
                      chip per process this is exactly the reference rank
  * ``local_rank``  — index of that chip on this host
  * ``local_size``  — chips on this host
  * ``cross_rank``  — host index (reference cross communicator)
Inside traced code the per-device rank is ``jax.lax.axis_index(axis)``.
"""

from __future__ import annotations

import atexit
import threading
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .exceptions import NotInitializedError
from .process_sets import ProcessSet, ProcessSetTable
from .utils import env
from .utils.logging import get_logger

# Canonical axis name of the global 1-D mesh (the "world communicator").
WORLD_AXIS = "hvd"

_runtime_lock = threading.Lock()
_runtime: Optional["Runtime"] = None


class Runtime:
    """Per-process singleton holding the mesh and topology."""

    def __init__(
        self,
        process_sets: Optional[Sequence[ProcessSet]] = None,
        devices: Optional[Sequence[jax.Device]] = None,
    ):
        self._init_distributed()
        if devices is None:
            devices = jax.devices()
        # Stable global order: JAX guarantees jax.devices() is identically
        # ordered on every process (sorted by (process_index, id)).
        self.devices: List[jax.Device] = list(devices)
        self.size: int = len(self.devices)
        self.process_rank: int = jax.process_index()
        self.process_count: int = jax.process_count()
        local = [d for d in self.devices if d.process_index == self.process_rank]
        self.local_devices: List[jax.Device] = local or [self.devices[0]]
        self._resolve_host_topology()
        self.mesh: Mesh = Mesh(
            np.asarray(self.devices, dtype=object).reshape(self.size), (WORLD_AXIS,)
        )
        self.process_set_table = ProcessSetTable(self.size)
        for ps in process_sets or ():
            self.process_set_table.add(ps, dynamic_ok=True)
        # Launcher-declared sets: HVD_TPU_PROCESS_SETS="0,1;2,3"
        # (the env-side mirror of init(process_sets=...), letting hvdrun
        # configure rank subsets without code changes).
        spec = env.get_env(env.PROCESS_SETS)
        if spec:
            for group in spec.split(";"):
                ranks = [int(r) for r in group.split(",") if r.strip()]
                if ranks:
                    self.process_set_table.add(
                        ProcessSet(ranks), dynamic_ok=True
                    )
        self.timeline = None
        timeline_path = env.get_env(env.TIMELINE)
        if timeline_path:
            if self.process_count > 1:
                # One trace per process: a shared filesystem (or local
                # multi-worker) must not clobber; the per-rank files
                # merge with tools/merge_timeline.py.
                timeline_path = f"{timeline_path}.rank{self.rank}"
            from . import native

            if native.available():
                self.timeline = native.NativeTimeline(
                    timeline_path, rank=self.rank
                )
            else:
                from .utils.timeline import Timeline

                self.timeline = Timeline(timeline_path, rank=self.rank)
        # Stall watchdog over blocking waits (reference stall_inspector.cc,
        # warn default 60 s, stall_inspector.h:78). Disabled like the
        # reference via HOROVOD_STALL_CHECK_DISABLE.
        self.stall_watchdog = None
        if not env.get_bool(env.STALL_CHECK_DISABLE):
            from .utils.stall import StallWatchdog

            self.stall_watchdog = StallWatchdog(
                warn_seconds=env.get_float(env.STALL_CHECK_TIME_SECONDS, 60.0),
                shutdown_seconds=env.get_float(
                    env.STALL_SHUTDOWN_TIME_SECONDS, 0.0
                ),
            )
        get_logger().info(
            "initialized: %d device(s), %d process(es), platform=%s",
            self.size,
            self.process_count,
            self.devices[0].platform,
        )

    def _resolve_host_topology(self) -> None:
        """Compute rank / local_rank / cross_rank at reference semantics
        (``MPI_Comm_split_type`` SHARED in ``mpi/mpi_context.cc``):
        processes on the same physical host share a "local" communicator;
        ``cross_rank`` indexes hosts.  Host identity is agreed by
        allgathering hostnames over the mesh (the rendezvous analog of the
        reference's shared-memory split)."""
        self.rank = self.devices.index(self.local_devices[0])
        if self.process_count == 1:
            self.local_rank = 0
            self.local_size = len(self.local_devices)
            self.cross_rank = 0
            self.cross_size = 1
            return
        import hashlib
        import socket

        from jax.experimental import multihost_utils

        # 31-bit hash: jax's default x64-disabled mode truncates gathered
        # integers to int32, so the id must fit in int32 exactly
        digest = hashlib.sha256(socket.gethostname().encode()).digest()[:4]
        my_host = int.from_bytes(digest, "big") & 0x7FFFFFFF
        host_ids = [
            int(h)
            for h in np.asarray(
                multihost_utils.process_allgather(np.int32(my_host))
            ).reshape(-1)
        ]
        # Hosts ordered by first process appearance; processes within a
        # host ordered by process index (matches MPI split key semantics).
        hosts_in_order = list(dict.fromkeys(host_ids))
        self.cross_size = len(hosts_in_order)
        self.cross_rank = hosts_in_order.index(host_ids[self.process_rank])
        peers = [p for p in range(self.process_count) if host_ids[p] == my_host]
        procs_before = peers.index(self.process_rank)
        per_proc = [
            sum(1 for d in self.devices if d.process_index == p) for p in peers
        ]
        self.local_size = sum(per_proc)
        self.local_rank = sum(per_proc[:procs_before])

    def _init_distributed(self) -> None:
        """Multi-host rendezvous: ``jax.distributed.initialize``.

        The TPU-native analog of the reference's Gloo HTTP rendezvous
        (``horovod/common/gloo/gloo_context.cc:216-230``): the launcher
        exports coordinator address + process id/count, and the JAX
        coordination service plays the role of the rendezvous KV store.
        """
        self._owns_distributed = False
        coord = env.get_env(env.COORDINATOR_ADDR)
        nproc = env.get_int(env.CROSS_SIZE, 1)
        pid = env.get_int(env.CROSS_RANK, 0)
        if coord and nproc > 1:
            # Must run before anything initializes the XLA backend — do
            # not query jax.process_count() first.  An already-initialized
            # coordination service (e.g. re-init in elastic mode after the
            # launcher set it up) is fine.
            try:
                # HVD_TPU_START_TIMEOUT / HOROVOD_START_TIMEOUT bounds
                # the rendezvous wait (reference horovod_start_timeout,
                # common.h; its 30 s default is too tight for TPU
                # runtime bring-up, so JAX's 300 s default stands).
                jax.distributed.initialize(
                    coordinator_address=coord, num_processes=nproc,
                    process_id=pid,
                    initialization_timeout=env.get_int(
                        env.START_TIMEOUT, 300
                    ),
                )
                self._owns_distributed = True
            except RuntimeError as e:
                # Tolerate re-init when the coordination service is already
                # up (elastic restart in the same process); anything else
                # is a genuine rendezvous failure.
                if jax.process_count() != nproc:
                    raise
                get_logger().info("jax.distributed already initialized: %s", e)

    def shutdown(self) -> None:
        from .ops import eager
        from .topo import model as topo_model

        # Async exchange service: drain in-flight submissions and stop
        # the background loop before the mesh goes away — its cached
        # executors are compiled against this runtime's mesh and must
        # not survive into a re-init'ed world.
        try:
            from . import svc as _svc

            _svc.drain(timeout_s=5.0)
            _svc.reset_service()
        except Exception as e:  # teardown must never wedge on the svc
            get_logger().warning("exchange service shutdown: %s", e)
        eager.clear_cache()
        # Drop the topology discovery cache: an elastic restart may come
        # back with a different device set (slice count included).
        topo_model.reset()
        if self.stall_watchdog is not None:
            self.stall_watchdog.close()
            self.stall_watchdog = None
        if self.timeline is not None:
            self.timeline.close()
            self.timeline = None
        if self._owns_distributed:
            jax.distributed.shutdown()
            self._owns_distributed = False


def _comm_world_ranks(comm) -> List[int]:
    """Global ranks described by ``comm`` (reference ``basics.py:48``):
    a sequence of world ranks, or an mpi4py(-like) communicator whose
    group is translated into MPI_COMM_WORLD ranks."""
    if isinstance(comm, (list, tuple, range)):
        return [int(r) for r in comm]
    size = int(comm.Get_size())
    group = getattr(comm, "group", None)
    if group is not None:
        # Duck-typed communicators (tests / alternative MPI shims) take
        # priority: their hook must work whether or not mpi4py happens
        # to be installed.
        translate = getattr(group, "translate_ranks", None)
        if callable(translate):
            return [int(r) for r in translate(list(range(size)))]
        try:
            from mpi4py import MPI
        except ImportError:
            MPI = None
        if MPI is not None and isinstance(group, MPI.Group):
            world = MPI.COMM_WORLD.group
            return [
                int(r) for r in
                MPI.Group.Translate_ranks(group, list(range(size)), world)
            ]
    return list(range(size))


def init(
    process_sets=None,
    devices: Optional[Sequence[jax.Device]] = None,
    comm=None,
) -> None:
    """Initialize the runtime (reference ``horovod_init``,
    ``operations.cc:869`` / ``InitializeHorovodOnce`` ``:791``).

    Idempotent like the reference.  ``process_sets`` registers rank
    subsets up front (reference ``horovod_init_multi_comm``,
    ``operations.cc:881``) — or the string ``"dynamic"``, which enables
    ``add_process_set`` later (reference ``basics.py:79-82``).

    ``comm`` accepts a list of global ranks or an mpi4py communicator
    (reference ``basics.py:48``): the world is restricted to the chips
    whose ranks the communicator covers — comm rank i maps onto mesh
    rank ``ranks[i]``.  Mutually exclusive with ``devices``.
    """
    global _runtime
    if isinstance(process_sets, str):
        if process_sets.lower() != "dynamic":
            raise ValueError(
                f"process_sets={process_sets!r}: only 'dynamic' or a "
                "sequence of ProcessSet is accepted"
            )
        env.set_env(env.DYNAMIC_PROCESS_SETS, "1")
        process_sets = None
    if comm is not None:
        if devices is not None:
            raise ValueError("pass either comm= or devices=, not both")
        ranks = _comm_world_ranks(comm)
        world = jax.devices()
        bad = [r for r in ranks if r < 0 or r >= len(world)]
        if bad:
            raise ValueError(
                f"comm ranks {bad} out of range for {len(world)} devices"
            )
        if len(set(ranks)) != len(ranks):
            raise ValueError(f"comm ranks contain duplicates: {ranks}")
        devices = [world[r] for r in ranks]
    with _runtime_lock:
        if _runtime is None:
            _runtime = Runtime(process_sets=process_sets, devices=devices)


def shutdown() -> None:
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None


def is_initialized() -> bool:
    return _runtime is not None


def get_runtime() -> Runtime:
    rt = _runtime
    if rt is None:
        raise NotInitializedError()
    return rt


def get_runtime_or_none() -> Optional[Runtime]:
    return _runtime


atexit.register(shutdown)
