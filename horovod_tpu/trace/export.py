"""Perfetto/Chrome-trace export of span trees, one file per rank.

Same on-disk convention as the PR 2 mergeable timeline
(``utils/timeline.py``): a bare JSON array written one event per line
(salvageable after a crash mid-write), opened by process-metadata
events plus the ``HVD_PROC_META`` instant carrying this process's rank
and wall-clock epoch base — so ``tools/merge_timeline.py`` re-bases N
per-rank trace files onto one shared clock exactly as it does timeline
files, and the two kinds of file merge together into one Perfetto
view.

Spans land on per-phase lanes (``tid`` + ``thread_name`` metadata):
the step lane on top, then exchange/bucket structure, the two rails,
and the service stations — so the Perfetto picture reads top-down the
way the pipeline flows.
"""

from __future__ import annotations

import json
import os
import socket
import threading
from typing import Optional

# Lane layout (Chrome tid + display name).  Unknown phases fall into
# the service lane rather than growing unbounded lanes.
_LANES = (
    ("step", 0, "step"),
    ("exchange", 1, "exchange"),
    ("bucket", 1, "exchange"),
    ("rs_ici", 2, "ici rail"),
    ("ag_ici", 2, "ici rail"),
    ("dcn", 3, "dcn rail"),
    ("queue", 4, "svc"),
    ("negotiate", 4, "svc"),
    ("cache", 4, "svc"),
    ("lower", 4, "svc"),
    ("dispatch", 4, "svc"),
)
_PHASE_TID = {p: tid for p, tid, _ in _LANES}
_TID_NAME = {tid: name for _, tid, name in _LANES}
_DEFAULT_TID = 4


class TraceWriter:
    """Line-buffered Chrome-trace JSON writer for finalized span trees
    (no background thread: trees arrive a handful per step, off the
    device hot path)."""

    def __init__(self, path: str, rank: int, mono0: float,
                 epoch_wall_us: float):
        self.path = path
        self.rank = int(rank)
        self._mono0 = mono0
        self._epoch_wall_us = epoch_wall_us
        self._lock = threading.Lock()
        self._fh = open(path, "w", buffering=1)
        self._fh.write("[\n")
        self._first = True
        self._closed = False
        self._emit_metadata()

    def _emit_metadata(self) -> None:
        pid = os.getpid()
        hostname = socket.gethostname()
        self._write({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": f"rank {self.rank} ({hostname})"}})
        self._write({"name": "process_sort_index", "ph": "M", "pid": pid,
                     "args": {"sort_index": self.rank}})
        for tid in sorted(_TID_NAME):
            self._write({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid,
                         "args": {"name": _TID_NAME[tid]}})
        self._write({
            "name": "HVD_PROC_META", "ph": "i", "ts": 0.0, "s": "p",
            "pid": pid, "tid": 0,
            "args": {
                "rank": self.rank, "hostname": hostname, "pid": pid,
                "epoch_wall_us": self._epoch_wall_us,
                "writer": "trace",
            },
        })

    def _ts_us(self, mono_t: float) -> float:
        return (mono_t - self._mono0) * 1e6

    def write_tree(self, span) -> None:
        """One complete ``X`` event per span in the tree."""
        pid = os.getpid()
        with self._lock:
            if self._closed:
                return
            for s in span.walk():
                args = {
                    "phase": s.phase,
                    "trace_id": s.trace_id,
                    "span_id": s.span_id,
                }
                if s.parent_id:
                    args["parent_id"] = s.parent_id
                if s.producer:
                    args["producer"] = s.producer
                if s.attrs:
                    args.update({
                        k: v for k, v in s.attrs.items()
                        if isinstance(v, (int, float, str, bool))
                    })
                self._write({
                    "name": s.name,
                    "cat": f"TRACE_{s.phase.upper()}",
                    "ph": "X",
                    "ts": self._ts_us(s.t0),
                    "dur": max(s.dur * 1e6, 0.001),
                    "pid": pid,
                    "tid": _PHASE_TID.get(s.phase, _DEFAULT_TID),
                    "args": args,
                })

    def _write(self, event: dict) -> None:
        if not self._first:
            self._fh.write(",\n")
        self._first = False
        self._fh.write(json.dumps(event))

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._fh.write("\n]\n")
                self._fh.close()
            except (OSError, ValueError):  # pragma: no cover
                pass


def dump_to_events(dump: dict) -> list:
    """Render one flight-recorder dump's span trees as Chrome-trace
    events (so a dump merges into ``tools/merge_timeline.py``'s
    postmortem view alongside timeline and full-level trace files)."""
    rank = int(dump.get("rank", 0))
    mono0 = float(dump.get("mono0", 0.0))
    events = [
        {"name": "process_name", "ph": "M", "pid": rank,
         "args": {"name": f"rank {rank} (flight dump)"}},
        # The merge anchor: rank + wall epoch, so a dump re-bases onto
        # the shared clock exactly like a timeline/trace file.
        {"name": "HVD_PROC_META", "ph": "i", "ts": 0.0, "s": "p",
         "pid": rank, "tid": 0,
         "args": {"rank": rank,
                  "epoch_wall_us": float(dump.get("epoch_wall_us", 0.0)),
                  "writer": "flight_dump"}},
    ]

    def _walk(d: dict):
        yield d
        for c in d.get("children", ()):
            yield from _walk(c)

    for rec in list(dump.get("steps", ())) + list(
            dump.get("background", ())):
        tree = rec.get("spans") or {}
        for s in _walk(tree):
            events.append({
                "name": s.get("name", "?"),
                "cat": f"TRACE_{str(s.get('phase', '?')).upper()}",
                "ph": "X",
                "ts": (float(s.get("t0", 0.0)) - mono0) * 1e6,
                "dur": max(float(s.get("dur", 0.0)) * 1e6, 0.001),
                "pid": rank,
                "tid": _PHASE_TID.get(s.get("phase"), _DEFAULT_TID),
                "args": {k: v for k, v in s.items()
                         if k not in ("children",)
                         and isinstance(v, (int, float, str))},
            })
    return events


def write_dump_as_chrome_trace(dump: dict, path: str) -> None:
    """Render one flight-recorder dump as a standalone Chrome trace
    (for loading an anomaly in Perfetto without the full-level
    stream)."""
    with open(path, "w") as fh:
        json.dump(
            {"traceEvents": dump_to_events(dump),
             "displayTimeUnit": "ms"}, fh,
        )
