"""Cross-rank straggler detection from per-rank phase summaries.

The reference's stall check (``stall_inspector.cc``) is the only place
Horovod *names the ranks* a tensor is waiting on; everything else in
its telemetry is rank-local.  This module is that naming power applied
to the whole exchange path: every rank's tracer folds its spans into
``trace.phase_seconds.<phase>`` histograms, the existing heartbeat KV
push ships each rank's metrics snapshot to the elastic driver, and the
driver aggregates them here — per rank, per phase — to answer *which
rank is holding everyone up, and in which phase*.

Detection is a median test: for each phase, take the p50 across ranks;
a rank whose own p50 exceeds ``HVD_TPU_TRACE_STRAGGLER_Z`` x the
median-rank p50 (default 2x, with a 0.1 ms absolute floor so idle-fast
phases cannot flag on jitter) is a straggler.  Results publish as
``trace.straggler{rank=,phase=}`` gauges (value = the ratio) and as
the ``/trace`` endpoint's summary (``runner/telemetry_http.py``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .. import metrics
from ..utils import env

PHASE_PREFIX = "trace.phase_seconds."
TENANT_PREFIX = "trace.tenant_seconds."
DEFAULT_Z = 2.0
# Absolute floor (seconds): a phase whose p50 is under this never
# flags — sub-0.1ms spans are measurement noise, not stragglers.
_MIN_P50_S = 1e-4


def straggler_z() -> float:
    return max(1.0, env.get_float(env.TRACE_STRAGGLER_Z, DEFAULT_Z))


def phase_summary(snapshot: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Per-phase {p50, p99, count, sum} extracted from one rank's
    metrics snapshot (the JSON form workers push over the KV store)."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, hist in (snapshot.get("histograms") or {}).items():
        if not name.startswith(PHASE_PREFIX):
            continue
        phase = name[len(PHASE_PREFIX):]
        count = int(hist.get("count", 0))
        if count <= 0:
            continue
        out[phase] = {
            "p50": metrics.hist_quantile(hist, 0.5),
            "p99": metrics.hist_quantile(hist, 0.99),
            "count": count,
            "sum": float(hist.get("sum", 0.0)),
        }
    return out


def tenant_summary(
    snapshot: Dict[str, Any]
) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """Per-tenant per-phase {p50, p99, count} from one rank's snapshot
    (the ``trace.tenant_seconds.<tenant>.<phase>`` histograms the
    tracer folds tenant-tagged spans into) — the attribution half of
    the multi-tenant arbiter: a slow phase names its tenant, not just
    its rank."""
    out: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for name, hist in (snapshot.get("histograms") or {}).items():
        if not name.startswith(TENANT_PREFIX):
            continue
        tenant, _, phase = name[len(TENANT_PREFIX):].rpartition(".")
        if not tenant:
            continue
        count = int(hist.get("count", 0))
        if count <= 0:
            continue
        out.setdefault(tenant, {})[phase] = {
            "p50": metrics.hist_quantile(hist, 0.5),
            "p99": metrics.hist_quantile(hist, 0.99),
            "count": count,
        }
    return out


def tenant_observed(
    per_rank: Dict[int, Dict[str, Any]]
) -> Dict[str, Dict[str, Any]]:
    """Per-tenant observed SLO inputs aggregated across rank snapshots
    (the SLO watchdog's view, ``runner/slo.py``):

    * ``step_s`` — the tenant's per-step exchange residency: the sum of
      its per-phase p50s, taken from the WORST rank (the rank a
      straggler verdict would name);
    * ``phase_p99_s`` — the worst per-phase p99 across ranks, the
      fallback served-latency signal when no arbiter wait histogram
      exists;
    * ``ranks`` — how many ranks reported the tenant.
    """
    out: Dict[str, Dict[str, Any]] = {}
    for _rank, snap in sorted(per_rank.items()):
        for tenant, phases in tenant_summary(snap).items():
            step = sum(
                (p.get("p50") or 0.0) for p in phases.values()
            )
            p99 = max(
                ((p.get("p99") or 0.0) for p in phases.values()),
                default=0.0,
            )
            agg = out.setdefault(tenant, {
                "step_s": 0.0, "phase_p99_s": 0.0, "ranks": 0,
            })
            agg["ranks"] += 1
            agg["step_s"] = max(agg["step_s"], step)
            agg["phase_p99_s"] = max(agg["phase_p99_s"], p99)
    return out


def _slowest_tenant(snapshot: Dict[str, Any],
                    phase: str) -> Optional[str]:
    """The tenant with the largest p50 for ``phase`` on this rank —
    the per-tenant attribution attached to a straggler verdict."""
    worst, worst_p50 = None, 0.0
    for tenant, phases in tenant_summary(snapshot).items():
        p50 = (phases.get(phase) or {}).get("p50")
        if p50 is not None and p50 > worst_p50:
            worst, worst_p50 = tenant, p50
    return worst


def _counter(snapshot: Dict[str, Any], name: str) -> int:
    return int((snapshot.get("counters") or {}).get(name, 0))


def _gauge(snapshot: Dict[str, Any], name: str) -> Optional[float]:
    for g in snapshot.get("gauges") or ():
        if g.get("name") == name and not g.get("labels"):
            return float(g.get("value"))
    return None


def detect(per_rank: Dict[int, Dict[str, Any]],
           z: Optional[float] = None) -> List[Dict[str, Any]]:
    """Find (rank, phase) stragglers across rank snapshots.  Returns a
    list sorted worst-first: ``{"rank", "phase", "p50",
    "median_p50", "ratio"}``.  Needs >= 2 ranks reporting a phase —
    there is no median to be slower than otherwise."""
    z = straggler_z() if z is None else float(z)
    summaries = {r: phase_summary(s) for r, s in per_rank.items()}
    phases = sorted({p for s in summaries.values() for p in s})
    found: List[Dict[str, Any]] = []
    for phase in phases:
        p50s = {
            r: s[phase]["p50"] for r, s in summaries.items()
            if phase in s and s[phase]["p50"] is not None
        }
        if len(p50s) < 2:
            continue
        # Lower median: with two ranks the baseline must be the OTHER
        # rank, not the straggler itself.
        ordered = sorted(p50s.values())
        median = ordered[(len(ordered) - 1) // 2]
        for rank, p50 in p50s.items():
            if p50 <= _MIN_P50_S:
                continue
            baseline = max(median, _MIN_P50_S)
            if p50 > z * baseline:
                found.append({
                    "rank": rank,
                    "phase": phase,
                    "p50": p50,
                    "median_p50": median,
                    "ratio": p50 / baseline,
                    # Which tenant's traffic dominates the slow phase
                    # on this rank (None in untagged worlds).
                    "tenant": _slowest_tenant(per_rank[rank], phase),
                })
    return sorted(found, key=lambda f: -f["ratio"])


def publish(stragglers: List[Dict[str, Any]]) -> None:
    """Publish ``trace.straggler{rank=,phase=}`` gauges (value = the
    p50 ratio over the median rank).  The family is cleared first so a
    recovered rank's series disappears instead of pinning its last
    ratio."""
    metrics.clear_gauge("trace.straggler")
    metrics.set_gauge("trace.stragglers", len(stragglers))
    for f in stragglers:
        metrics.set_gauge(
            "trace.straggler", f["ratio"],
            {"rank": str(f["rank"]), "phase": f["phase"]},
        )


def trace_payload(per_rank: Dict[int, Dict[str, Any]],
                  z: Optional[float] = None) -> Dict[str, Any]:
    """The ``/trace`` endpoint body: per-rank phase summaries + anomaly
    dump indices (from each rank's own flight-recorder counters) + the
    cross-rank straggler verdicts, one detection pass per scrape."""
    stragglers = detect(per_rank, z=z)
    publish(stragglers)
    ranks = {}
    for rank, snap in sorted(per_rank.items()):
        entry = {
            "phases": phase_summary(snap),
            "anomaly_dumps": _counter(snap, "trace.anomaly_dumps"),
            "last_anomaly_dump": _gauge(snap, "trace.last_anomaly_dump"),
            "steps": _counter(snap, "trace.steps"),
        }
        tenants = tenant_summary(snap)
        if tenants:
            entry["tenants"] = tenants
        ranks[str(rank)] = entry
    return {
        "stragglers": stragglers,
        "straggler_z": straggler_z() if z is None else float(z),
        "ranks": ranks,
    }
