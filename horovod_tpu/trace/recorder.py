"""Flight recorder: the last N steps' span trees, dumped on anomaly.

The reference's stall check tells you a collective is stuck *now*; a
postmortem needs what happened *just before*.  The recorder keeps a
bounded ring of the most recent steps' span trees (plus background
spans from the service loop) per rank, and writes the whole ring to
``HVD_TPU_TRACE_DIR`` when something anomalous happens:

* **slow step** — step time exceeding ``HVD_TPU_TRACE_ANOMALY_Z`` x
  the rolling p50 of recent steps (the z-test a human eyeballing a
  step-time plot runs);
* **fault site** — any armed :mod:`horovod_tpu.faults` injection
  firing (``trace/__init__.on_fault``), so a scripted game-day run
  leaves span evidence of the window around the fault;
* **remesh** — a membership change pausing survivors
  (``elastic/remesh.py``);
* **service death** — the async exchange service degrading to inline
  dispatch (``svc/service.py`` ``_kill``).

Without ``HVD_TPU_TRACE_DIR`` the dump stays in memory (the last one
is queryable — ``last_dump()`` — and counted), so fault-heavy test
suites pay no file IO.  ``trace.anomaly_dumps`` counts dumps;
``trace.last_anomaly_dump`` gauges the latest dump index, which the
driver's ``/trace`` endpoint surfaces per rank.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils import env

DEFAULT_RING = 16
DEFAULT_Z = 3.0
# Rolling window the p50 baseline is computed over, and the minimum
# history before the z-test can fire (a compile-slow first step must
# not dump an empty ring).
_BASELINE_WINDOW = 64
_MIN_HISTORY = 5
# Ignore sub-10ms excursions outright: on a fast CPU loop the p50 can
# be microseconds and z x p50 would flag scheduler jitter.
_MIN_EXCESS_S = 0.010


def ring_size() -> int:
    return max(1, env.get_int(env.TRACE_RING, DEFAULT_RING))


def anomaly_z() -> float:
    return max(1.0, env.get_float(env.TRACE_ANOMALY_Z, DEFAULT_Z))


def trace_dir() -> Optional[str]:
    return env.get_env(env.TRACE_DIR) or None


DEFAULT_DUMP_KEEP = 64


def dump_keep() -> int:
    """On-disk retention: newest N dumps kept per rank (0 = unbounded)."""
    return max(0, env.get_int(env.TRACE_DUMP_KEEP, DEFAULT_DUMP_KEEP))


class FlightRecorder:
    """Per-process ring of recent step span trees + anomaly dumps."""

    def __init__(self, capacity: Optional[int] = None):
        cap = ring_size() if capacity is None else int(capacity)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=cap)
        self._background: deque = deque(maxlen=cap)
        self._durs: deque = deque(maxlen=_BASELINE_WINDOW)
        self._dump_seq = 0
        self._last_dump: Optional[Dict[str, Any]] = None
        self._last_dump_path: Optional[str] = None

    # ------------------------------------------------------- ingestion

    def on_step(self, span) -> None:
        """Record one finished step tree; run the slow-step check
        against the rolling p50 of the steps before it."""
        from .. import metrics

        dur = span.dur
        with self._lock:
            baseline = sorted(self._durs)
            self._ring.append({
                "kind": "step",
                "step": span.attrs.get("step") if span.attrs else None,
                "wall_ts": time.time(),
                "dur_s": dur,
                "spans": span.to_dict(),
            })
            self._durs.append(dur)
        metrics.inc_counter("trace.steps")
        if len(baseline) >= _MIN_HISTORY:
            p50 = baseline[len(baseline) // 2]
            z = anomaly_z()
            if dur > z * p50 and dur - p50 > _MIN_EXCESS_S:
                self.dump(
                    "slow_step",
                    step_seconds=dur, rolling_p50=p50, z=z,
                )

    def on_background(self, span) -> None:
        """Root spans finalized outside any step (the service loop's
        dispatch spans): ring alongside the steps, FIFO like them."""
        with self._lock:
            self._background.append({
                "kind": "background",
                "wall_ts": time.time(),
                "dur_s": span.dur,
                "spans": span.to_dict(),
            })

    # ----------------------------------------------------------- dumps

    def dump(self, reason: str, **detail: Any) -> Optional[str]:
        """Write the ring (steps + background spans) as one JSON dump;
        returns the file path, or None when no ``HVD_TPU_TRACE_DIR`` is
        configured (the dump is still retained in memory and counted).
        Never raises — the recorder must not take down the path it
        observes."""
        from .. import events, metrics
        from .context import _rank

        from .tracer import get_tracer

        tracer = get_tracer()
        with self._lock:
            if not self._ring and not self._background:
                return None
            self._dump_seq += 1
            seq = self._dump_seq
            payload = {
                "reason": reason,
                "detail": detail,
                "rank": _rank(),
                "seq": seq,
                "wall_ts": time.time(),
                # Clock anchor (mono zero <-> wall epoch, the Timeline
                # scheme): lets merge_timeline.py re-base the dump's
                # monotonic span times onto the shared wall clock.
                "mono0": tracer.mono0,
                "epoch_wall_us": tracer.epoch_wall_us,
                "steps": list(self._ring),
                "background": list(self._background),
            }
            self._last_dump = payload
        metrics.inc_counter("trace.anomaly_dumps")
        metrics.inc_counter(f"trace.anomaly_dumps.{reason.split(':')[0]}")
        metrics.set_gauge("trace.last_anomaly_dump", seq)
        path: Optional[str] = None
        d = trace_dir()
        if d:
            try:
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f"flight_rank{payload['rank']}_{seq}.json"
                )
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    json.dump(payload, fh, default=str)
                os.replace(tmp, path)
                self._prune_dumps(d, payload["rank"])
            except OSError as e:
                from ..utils.logging import get_logger

                get_logger().warning("flight-recorder dump failed: %s", e)
                path = None
        with self._lock:
            self._last_dump_path = path
        events.emit(
            events.TRACE_ANOMALY, reason=reason, seq=seq, path=path,
            **{k: v for k, v in detail.items()
               if isinstance(v, (int, float, str))},
        )
        return path

    @staticmethod
    def _prune_dumps(d: str, rank: Any) -> None:
        """Oldest-first retention on this rank's on-disk dumps: a
        long-running chaos-heavy job must not grow ``HVD_TPU_TRACE_DIR``
        without bound.  Keeps the newest ``HVD_TPU_TRACE_DUMP_KEEP``
        (0 = unbounded); pruned files count into
        ``trace.dumps_pruned``.  Never raises."""
        keep = dump_keep()
        if keep <= 0:
            return
        import re

        prefix = f"flight_rank{rank}_"
        found: List[tuple] = []
        try:
            for name in os.listdir(d):
                if not (name.startswith(prefix) and name.endswith(".json")):
                    continue
                m = re.match(re.escape(prefix) + r"(\d+)\.json$", name)
                if m:
                    found.append((int(m.group(1)), name))
        except OSError:
            return
        if len(found) <= keep:
            return
        found.sort()
        pruned = 0
        for _, name in found[:-keep]:
            try:
                os.remove(os.path.join(d, name))
                pruned += 1
            except OSError:
                pass
        if pruned:
            from .. import metrics

            metrics.inc_counter("trace.dumps_pruned", pruned)

    # ------------------------------------------------------ inspection

    def steps(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def last_dump(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._last_dump

    def last_dump_path(self) -> Optional[str]:
        with self._lock:
            return self._last_dump_path

    @property
    def dump_seq(self) -> int:
        with self._lock:
            return self._dump_seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def reset() -> None:
    global _recorder
    with _recorder_lock:
        _recorder = None


def trigger_dump(reason: str, **detail: Any) -> Optional[str]:
    """External anomaly trigger (fault sites, remesh, service death):
    dump the current ring if there is one.  Safe to call from any
    thread, never raises."""
    try:
        if not _has_data():
            return None
        return get_recorder().dump(reason, **detail)
    except Exception:  # pragma: no cover - defensive
        return None


def _has_data() -> bool:
    rec = _recorder
    return rec is not None and (len(rec) > 0 or len(rec._background) > 0)
