"""Span tracer: the host-side clock on every exchange phase.

Horovod's ``HOROVOD_TIMELINE`` records one NEGOTIATE/QUEUE/op phase
span per tensor request (``timeline.cc``) — the artifact that lets an
operator say *where a slow step's time went*.  Our exchange path grew
the same stations one subsystem at a time (queue → negotiation → cache
→ lowering → rail execution, PRs 3–12) but kept only PR 2's inline
timers; this module adds the spans.

Mechanics: spans are **host-side** — they wrap Python work (queue
waits, the lowering pass, trace-time emission of rail phases), never
insert ops into a traced step, and therefore cannot perturb values;
``HVD_TPU_TRACE=off`` reduces every ``span()`` call to one shared
no-op object (zero allocation).  Nesting rides a thread-local stack:
a span opened while another is open on the same thread becomes its
child, so the step span (``TrainStep.__call__``) naturally parents the
exchange/bucket/rail spans emitted while the step traces.  Cross-
thread correlation (producer thread → service loop) uses the
:class:`~horovod_tpu.trace.context.TraceContext` carried by the
submission instead of the stack.

Every finalized root tree is:

* folded into the ``trace.phase_seconds.<phase>`` histograms (the
  per-rank summaries the heartbeat KV push ships to the driver's
  straggler detector — ``trace/straggler.py``);
* handed to the flight recorder (``trace/recorder.py``) for the
  last-N-steps anomaly ring;
* streamed to the per-rank Chrome trace at level ``full``
  (``trace/export.py``).

Step spans additionally derive the measured per-rail utilization
gauges ``topo.rail_busy_frac{rail=ici|dcn}`` from the rail-phase spans
(the pipeliner's overlap claims as a measurement, not a counter).
"""

from __future__ import annotations

import atexit
import itertools
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils import env

LEVELS = ("off", "summary", "full")

# Phases with a rail attribution (the RailChain vocabulary,
# xir/pipeline.py): busy-fraction accounting groups spans by this map.
RAIL_PHASES = {"rs_ici": "ici", "ag_ici": "ici", "dcn": "dcn"}

_level_override: Optional[str] = None
_span_counter = itertools.count(1)


def set_level_override(level: Optional[str]) -> None:
    """Pin the trace level without touching the environment (the sched
    config-override pattern tests use)."""
    global _level_override
    if level is not None and level not in LEVELS:
        raise ValueError(f"trace level must be one of {LEVELS}, got {level!r}")
    _level_override = level


def level() -> str:
    """``HVD_TPU_TRACE`` policy: ``off`` | ``summary`` (default) |
    ``full``.  ``1/true/yes/on`` spell ``full`` (an explicit enable
    means you want the per-rank trace files)."""
    if _level_override is not None:
        return _level_override
    raw = (env.get_env(env.TRACE, "summary") or "summary").strip().lower()
    if raw in ("0", "false", "no", "none", ""):
        return "off"
    if raw in ("1", "true", "yes", "on"):
        return "full"
    if raw not in LEVELS:
        from ..utils.logging import get_logger

        get_logger().warning(
            "HVD_TPU_TRACE=%r is not one of %s; using 'summary'",
            raw, LEVELS,
        )
        return "summary"
    return raw


def enabled() -> bool:
    return level() != "off"


class Span:
    """One timed phase.  Times are ``time.monotonic()`` seconds; the
    wall anchor for cross-rank merging lives on the tracer (sampled
    back to back at startup, the Timeline scheme)."""

    __slots__ = ("name", "phase", "t0", "t1", "trace_id", "span_id",
                 "parent_id", "producer", "tenant", "attrs", "children")

    def __init__(self, name: str, phase: str, t0: float,
                 trace_id: str = "", span_id: str = "",
                 parent_id: str = "", producer: str = "",
                 tenant: str = "",
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.phase = phase
        self.t0 = t0
        self.t1 = t0
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.producer = producer
        self.tenant = tenant
        self.attrs = attrs or {}
        self.children: List["Span"] = []

    @property
    def dur(self) -> float:
        return max(self.t1 - self.t0, 0.0)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name, "phase": self.phase,
            "t0": self.t0, "dur": self.dur,
        }
        if self.trace_id:
            d["trace_id"] = self.trace_id
        if self.span_id:
            d["span_id"] = self.span_id
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.producer:
            d["producer"] = self.producer
        if self.tenant:
            d["tenant"] = self.tenant
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


class _NoopSpan:
    """The shared do-nothing span ``HVD_TPU_TRACE=off`` hands back —
    one module-level instance, so the off path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NOOP = _NoopSpan()


class _ActiveSpan:
    """Context manager around one live span on this thread's stack."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        self._tracer._push(self._span)
        return self._span

    def __exit__(self, *exc):
        self._tracer._pop(self._span)
        return False


class _StepSpan(_ActiveSpan):
    """Step-scoped span: finalization additionally feeds the flight
    recorder's anomaly check and the rail-utilization gauges."""

    def __exit__(self, *exc):
        self._tracer._pop(self._span, step=True)
        return False


class Tracer:
    """Process-wide span collector (one per process, like the metrics
    registry — per-rank attribution happens at merge time)."""

    def __init__(self):
        from .context import _rank

        self._tl = threading.local()
        self._lock = threading.Lock()
        # Two clocks back to back: monotonic anchors span math, wall
        # anchors the cross-rank merge (the Timeline scheme).
        self.mono0 = time.monotonic()
        self.epoch_wall_us = time.time() * 1e6
        self.rank = _rank()
        self._writer = None
        self._writer_failed = False
        self._step_idx = 0

    # ----------------------------------------------------------- stack

    def _stack(self) -> List[Span]:
        st = getattr(self._tl, "stack", None)
        if st is None:
            st = self._tl.stack = []
        return st

    def _push(self, span: Span) -> None:
        st = self._stack()
        if st and not span.parent_id:
            span.parent_id = st[-1].span_id
            if not span.trace_id:
                span.trace_id = st[-1].trace_id
                span.producer = span.producer or st[-1].producer
        if st and not span.tenant:
            span.tenant = st[-1].tenant
        st.append(span)

    def _pop(self, span: Span, step: bool = False) -> None:
        span.t1 = time.monotonic()
        st = self._stack()
        while st and st[-1] is not span:  # tolerate unbalanced exits
            st.pop()
        if st:
            st.pop()
        if st:
            st[-1].children.append(span)
        else:
            self._finalize_root(span, step=step)

    # ------------------------------------------------------------- API

    def span(self, name: str, phase: str, ctx=None, **attrs):
        """Open one span (context manager).  ``ctx`` — a TraceContext —
        pins correlation explicitly (cross-thread); otherwise the
        enclosing span on this thread (or the thread's installed
        context) supplies it."""
        from .context import current

        ctx = ctx if ctx is not None else current()
        sp = Span(
            name, phase, time.monotonic(),
            trace_id=getattr(ctx, "trace_id", ""),
            parent_id=getattr(ctx, "span_id", "") if ctx else "",
            producer=getattr(ctx, "producer", ""),
            tenant=getattr(ctx, "tenant", ""),
            span_id=f"s{next(_span_counter)}",
            attrs=attrs or None,
        )
        return _ActiveSpan(self, sp)

    def step(self, **attrs):
        """Open the per-step root span (``TrainStep.__call__`` wraps
        the whole dispatch in one).  Finalization runs the flight
        recorder's anomaly check and publishes the per-rail busy
        fractions measured from the rail-phase spans underneath."""
        self._step_idx += 1
        sp = Span(
            f"step{self._step_idx}", "step", time.monotonic(),
            span_id=f"s{next(_span_counter)}",
            attrs={"step": self._step_idx, **attrs},
        )
        return _StepSpan(self, sp)

    def record_complete(self, name: str, phase: str, t0: float,
                        t1: Optional[float] = None, ctx=None,
                        **attrs) -> Span:
        """Record an already-elapsed interval as one span (queue waits
        and negotiation windows are only known at their end).  Attaches
        to the calling thread's open span when one exists, else
        finalizes as a root immediately."""
        from .context import current

        ctx = ctx if ctx is not None else current()
        sp = Span(
            name, phase, t0,
            trace_id=getattr(ctx, "trace_id", ""),
            parent_id=getattr(ctx, "span_id", "") if ctx else "",
            producer=getattr(ctx, "producer", ""),
            tenant=getattr(ctx, "tenant", ""),
            span_id=f"s{next(_span_counter)}",
            attrs=attrs or None,
        )
        sp.t1 = time.monotonic() if t1 is None else t1
        st = self._stack()
        if st:
            if not sp.trace_id:
                sp.trace_id = st[-1].trace_id
                sp.parent_id = sp.parent_id or st[-1].span_id
            st[-1].children.append(sp)
        else:
            self._finalize_root(sp)
        return sp

    # ------------------------------------------------------- finalize

    def _finalize_root(self, span: Span, step: bool = False) -> None:
        from .. import metrics

        n = 0
        for s in span.walk():
            n += 1
            metrics.observe(f"trace.phase_seconds.{s.phase}", s.dur)
            # Per-tenant phase attribution (the multi-tenant arbiter's
            # observability half, docs/multitenant.md): tenant-tagged
            # spans additionally fold into trace.tenant_seconds.<tenant>
            # .<phase> so the driver's straggler detector can say WHICH
            # tenant a slow phase belongs to.  Untagged worlds pay
            # nothing.
            if s.tenant:
                metrics.observe(
                    f"trace.tenant_seconds.{s.tenant}.{s.phase}", s.dur
                )
        metrics.inc_counter("trace.spans", n)
        if step:
            self._publish_rail_utilization(span)
            # Device-time profiling plane (prof/): host-gap + MFU +
            # sentinel all derive from the finalized step tree.  The
            # hook never raises and is a no-op at HVD_TPU_PROF=off.
            from .. import prof

            prof.on_step_span(span)
        from . import recorder

        rec = recorder.get_recorder()
        if step:
            rec.on_step(span)
        else:
            rec.on_background(span)
        if level() == "full":
            w = self._ensure_writer()
            if w is not None:
                w.write_tree(span)

    def _publish_rail_utilization(self, step_span: Span) -> None:
        """``topo.rail_busy_frac{rail=}``: the fraction of the step the
        rail-phase spans kept each network busy.  Measured from spans,
        so the pipeliner's overlap is visible as the two fractions'
        sum exceeding what a serialized schedule could reach."""
        from .. import metrics

        busy = {"ici": 0.0, "dcn": 0.0}
        seen = False
        for s in step_span.walk():
            rail = s.attrs.get("rail") if s.attrs else None
            rail = rail or RAIL_PHASES.get(s.phase)
            if rail in busy:
                busy[rail] += s.dur
                seen = True
        if not seen or step_span.dur <= 0:
            return
        for rail, t in busy.items():
            metrics.set_gauge(
                "topo.rail_busy_frac", min(t / step_span.dur, 1.0),
                {"rail": rail},
            )

    # --------------------------------------------------------- export

    def _ensure_writer(self):
        if self._writer is not None or self._writer_failed:
            return self._writer
        path_dir = env.get_env(env.TRACE_DIR)
        if not path_dir:
            self._writer_failed = True
            return None
        try:
            import os

            from .export import TraceWriter

            os.makedirs(path_dir, exist_ok=True)
            self._writer = TraceWriter(
                os.path.join(path_dir, f"trace_rank{self.rank}.json"),
                rank=self.rank, mono0=self.mono0,
                epoch_wall_us=self.epoch_wall_us,
            )
        except OSError as e:
            from ..utils.logging import get_logger

            get_logger().warning("cannot open trace writer: %s", e)
            self._writer_failed = True
        return self._writer

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        self._writer_failed = False


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            _tracer = Tracer()
    return _tracer


def reset() -> None:
    """Drop the tracer (and its writer): test isolation + elastic
    restarts — the next span builds a fresh one against the current
    rank/clock."""
    global _tracer
    with _tracer_lock:
        t, _tracer = _tracer, None
    if t is not None:
        t.close()
    from . import recorder

    recorder.reset()


@atexit.register
def _close_at_exit() -> None:  # pragma: no cover - interpreter teardown
    t = _tracer
    if t is not None:
        t.close()


# Module-level conveniences (the public spelling call sites use).

def span(name: str, phase: str, ctx=None, **attrs):
    if level() == "off":
        return NOOP
    return get_tracer().span(name, phase, ctx=ctx, **attrs)


def step(**attrs):
    if level() == "off":
        return NOOP
    return get_tracer().step(**attrs)


def record_complete(name: str, phase: str, t0: float,
                    t1: Optional[float] = None, ctx=None, **attrs):
    if level() == "off":
        return None
    return get_tracer().record_complete(
        name, phase, t0, t1=t1, ctx=ctx, **attrs
    )
