"""Trace identity: who submitted this exchange, and which request is it.

The reference timeline keys every span by tensor name because a tensor
*is* the unit of work in Horovod's queue.  Our unit of work is a
submission — an :class:`~horovod_tpu.xir.ir.ExchangeProgram` handed to
the async service (or emitted inline by a traced producer) — and one
submission fans out into many spans across threads (producer thread at
enqueue, background loop at negotiation/dispatch, trace thread at rail
emission).  :class:`TraceContext` is the correlation key that survives
the fan-out: a ``(trace_id, span_id, producer, tenant)`` tuple attached
to every ``svc`` Submission and every ExchangeProgram, copied — never
hashed — so it can ride a frozen program without perturbing the
signature the ResponseCache and tune DB key on.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
from typing import Optional

_counter = itertools.count(1)
_tl = threading.local()


def _rank() -> int:
    """Best-effort rank for trace ids and per-rank file names.  The
    launcher env (``HVD_TPU_CROSS_RANK``) wins when set — it is unique
    per *process*, which is what one-trace-file-per-rank needs — with
    the runtime rank as the single-process fallback."""
    raw = os.environ.get("HVD_TPU_CROSS_RANK")
    if raw not in (None, ""):
        try:
            return int(raw)
        except ValueError:
            pass
    try:
        from ..runtime import get_runtime_or_none

        rt = get_runtime_or_none()
        if rt is not None:
            return rt.rank
    except Exception:
        pass
    return 0


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Correlation identity of one traced request.

    ``trace_id`` names the whole request (a submission end to end);
    ``span_id`` names the position in its span tree a child should
    attach under; ``producer`` and ``tenant`` label the submitting
    pipeline (``sched.dense_grad``, ``stale``, a tenant's job name) for
    per-producer attribution in the straggler summary.
    """

    trace_id: str
    span_id: str = "0"
    producer: str = "default"
    tenant: str = ""

    def child(self, span_id: str) -> "TraceContext":
        return dataclasses.replace(self, span_id=span_id)


def default_tenant() -> str:
    """This process's configured tenant (``HVD_TPU_SVC_TENANT``, the
    multi-tenant arbiter's lane key — docs/multitenant.md); "" when the
    process is not tenant-tagged (submission-time derivation from the
    process set then applies, ``svc/arbiter.tenant_of``)."""
    from ..utils import env

    return (env.get_env(env.SVC_TENANT, "") or "").strip()


def new_context(producer: str = "default",
                tenant: str = "") -> TraceContext:
    """Mint a fresh trace id: ``r<rank>-<seq>`` — unique per process,
    attributable to a rank in a merged cross-rank view.  ``tenant``
    defaults to the process's ``HVD_TPU_SVC_TENANT`` tag so every
    producer-minted context is tenant-attributable without call-site
    changes."""
    return TraceContext(
        trace_id=f"r{_rank()}-{next(_counter)}",
        producer=producer, tenant=tenant or default_tenant(),
    )


def current() -> Optional[TraceContext]:
    """The context attached to this thread (or None).  Producers set it
    around a submission so spans emitted downstream — including by
    other modules that never saw the Submission object — correlate."""
    return getattr(_tl, "ctx", None)


def set_current(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install (or clear, with None) this thread's context; returns the
    previous one so callers can restore it."""
    prev = getattr(_tl, "ctx", None)
    _tl.ctx = ctx
    return prev


class use_context:
    """``with use_context(ctx): ...`` — scope a TraceContext to a block
    (the service loop wraps each dispatch in the submission's)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self):
        self._prev = set_current(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        set_current(self._prev)
        return False
