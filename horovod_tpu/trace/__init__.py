"""End-to-end exchange tracing + the cross-rank straggler flight
recorder.

PR 12 made exchange asynchronous — a submission passes through queue →
negotiation → cache → lowering → rail execution, possibly completing k
steps later — and this package is the telemetry that can say *where a
slow step's time went* and *which rank held the bitvector*: the
HOROVOD_TIMELINE per-request phase spans plus the stall check's
rank-naming power (arXiv:1802.05799, PAPER.md L2), rebuilt over the
XIR/svc pipeline.  Four pieces:

* :mod:`~horovod_tpu.trace.context` — :class:`TraceContext`, the
  (trace id, span id, producer/tenant) correlation key attached to
  every ``svc`` Submission and XIR ExchangeProgram;
* :mod:`~horovod_tpu.trace.tracer` — host-side spans at every station
  (queue enqueue/dequeue, negotiation wait with the last-arriving
  participant recorded, cache hit/miss, lowering, the ICI-RS / DCN /
  ICI-AG rail phases at the RailChain boundaries), folded into
  ``trace.phase_seconds.*`` histograms and — at level ``full`` — one
  Chrome-trace file per rank; step spans also derive the measured
  ``topo.rail_busy_frac{rail=ici|dcn}`` gauges;
* :mod:`~horovod_tpu.trace.recorder` — the flight recorder: a bounded
  ring of the last N steps' span trees, dumped to
  ``HVD_TPU_TRACE_DIR`` on anomaly (slow step vs the rolling p50,
  fault-site fire, remesh, service death);
* :mod:`~horovod_tpu.trace.straggler` — the elastic driver aggregates
  per-rank phase summaries from the existing heartbeat KV pushes and
  names stragglers by (rank, phase): ``trace.straggler{rank=,phase=}``
  gauges + the ``/trace`` HTTP endpoint.

``HVD_TPU_TRACE=off`` reduces every instrumentation point to a shared
no-op (zero allocation in the traced path); all levels are bitwise-
neutral to losses — spans wrap host work and never insert ops.  See
docs/tracing.md.
"""

from . import context, export, recorder, straggler, tracer  # noqa: F401
from .context import (  # noqa: F401
    TraceContext,
    current as current_context,
    new_context,
    set_current as set_current_context,
    use_context,
)
from .recorder import (  # noqa: F401
    FlightRecorder,
    get_recorder,
    trigger_dump,
)
from .tracer import (  # noqa: F401
    Span,
    Tracer,
    enabled,
    get_tracer,
    level,
    record_complete,
    reset,
    set_level_override,
    span,
    step,
)


def on_fault(site: str, kind: str) -> None:
    """Fault-site anomaly hook (called by :func:`horovod_tpu.faults.
    inject` whenever an armed fault fires): dump the flight ring so
    the injected failure's surrounding span history survives — before
    a ``crash`` kind hard-exits the process.  Never raises."""
    if level() == "off":
        return
    trigger_dump(f"fault:{site}", site=site, fault_kind=kind)
