"""Checkpoint save/restore for params + optimizer state.

Reference checkpoint/resume mechanisms (SURVEY.md §5): (1) elastic
``State`` in-memory commits (``horovod_tpu/elastic/state.py``), (2)
Spark store checkpoints (``horovod_tpu/spark/store.py``), and (3) Keras
``load_model`` with hvd-wrapped optimizers (``keras/__init__.py:167``)
— a durable on-disk format that round-trips the full training state.
This module is mechanism (3) for the TPU build: orbax when available
(async, sharded, multi-host), msgpack-free npz/pickle fallback
otherwise.

Rank-0-writes / all-read, with a ``broadcast`` on restore so every rank
starts from identical bytes (the reference's
``BroadcastGlobalVariablesCallback``-after-load pattern).

Integrity guarantees (fault-tolerance hardening):

* **Atomic write** — pickle checkpoints are serialized to a temp file
  in the target directory, fsynced, then ``os.replace``d into place: a
  crash mid-save leaves either the old checkpoint or the new one, never
  a torn file under the final name.
* **Content checksum** — a ``checkpoint.meta.json`` sidecar records the
  payload's SHA-256; :func:`load_checkpoint` verifies it and raises
  :class:`~horovod_tpu.exceptions.CheckpointCorruptionError` on
  mismatch (and on undecodable payloads) instead of restoring garbage.
* **Automatic fallback** — :func:`restore_or_init` walks ``step_N``
  directories newest-first and resumes from the newest checkpoint that
  passes verification, counting skips in ``metrics``
  (``checkpoint.corrupt_detected`` / ``checkpoint.fallback``).

The ``checkpoint.write`` fault-injection site (``faults.py``,
kind ``corrupt``) flips bytes after the checksum is recorded — the
deterministic stand-in for bit rot / torn remote writes used by the
integrity tests.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from . import events, faults, functions, runtime
from .exceptions import CheckpointCorruptionError
from .utils.logging import get_logger

log = get_logger()

_CKPT_FILE = "checkpoint.pkl"
_META_FILE = "checkpoint.meta.json"


class _LoadError:
    """Picklable error sentinel broadcast to all ranks so load failures
    raise everywhere instead of deadlocking non-root ranks."""

    def __init__(self, message: str, corrupt: bool = False,
                 missing: Optional[List[str]] = None,
                 available: Optional[List[str]] = None,
                 path: str = ""):
        self.message = message
        self.corrupt = corrupt
        self.missing = missing
        self.available = available
        self.path = path

    def raise_(self) -> None:
        if self.missing is not None:
            from .exceptions import CheckpointMissingKeysError

            raise CheckpointMissingKeysError(
                self.missing, self.available or (), self.path
            )
        if self.corrupt:
            raise CheckpointCorruptionError(self.message)
        raise RuntimeError(self.message)


def _has_orbax() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + rename in the destination directory (same
    filesystem, so the rename is atomic)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _dir_digest(root: str) -> str:
    """Deterministic SHA-256 over a directory tree (sorted relative
    paths + contents) — the integrity fingerprint for orbax
    checkpoints, whose payload is a directory, not one file."""
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            h.update(os.path.relpath(full, root).encode())
            with open(full, "rb") as fh:
                while True:
                    chunk = fh.read(1 << 20)
                    if not chunk:
                        break
                    h.update(chunk)
    return h.hexdigest()


def _corrupt_file(path: str) -> None:
    """Scripted bit rot: damage a payload AFTER its checksum was
    recorded, so verification must catch it."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(max(0, size // 2))
        fh.write(b"\xde\xad\xbe\xef")


def save_checkpoint(
    path: str,
    state: Dict[str, Any],
    step: Optional[int] = None,
    use_orbax: Optional[bool] = None,
) -> str:
    """Write ``state`` (a dict of pytrees: params, opt_state, ...) under
    ``path``; only rank 0 writes (reference: checkpoints saved on rank 0,
    e.g. ``examples/pytorch/pytorch_imagenet_resnet50.py``'s
    ``save_checkpoint``).  Returns the checkpoint directory."""
    import time

    from . import metrics

    target = path if step is None else os.path.join(path, f"step_{step}")
    rt = runtime.get_runtime_or_none()
    if rt is not None and rt.process_rank != 0:
        return target
    t0 = time.perf_counter()
    os.makedirs(target, exist_ok=True)
    if use_orbax is None:
        use_orbax = _has_orbax()
    host_state = jax.device_get(state)
    if use_orbax:
        import orbax.checkpoint as ocp

        orbax_dir = os.path.join(target, "orbax")
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(orbax_dir, host_state, force=True)
        meta = {"format": "orbax", "sha256": _dir_digest(orbax_dir)}
        _atomic_write(
            os.path.join(target, _META_FILE), json.dumps(meta).encode()
        )
        if faults.inject("checkpoint.write", path=target, step=step):
            files = sorted(
                (os.path.getsize(os.path.join(dp, f)),
                 os.path.join(dp, f))
                for dp, _, fs in os.walk(orbax_dir) for f in fs
            )
            if files:
                _corrupt_file(files[-1][1])
    else:
        payload = pickle.dumps(host_state)
        pkl = os.path.join(target, _CKPT_FILE)
        _atomic_write(pkl, payload)
        meta = {
            "format": "pickle",
            "sha256": hashlib.sha256(payload).hexdigest(),
            "size": len(payload),
        }
        _atomic_write(
            os.path.join(target, _META_FILE),
            json.dumps(meta).encode(),
        )
        if faults.inject("checkpoint.write", path=target, step=step):
            _corrupt_file(pkl)
    metrics.inc_counter("checkpoint.saved")
    metrics.observe("checkpoint.write_seconds", time.perf_counter() - t0)
    log.info("checkpoint saved to %s", target)
    return target


def verify_checkpoint(target: str) -> bool:
    """True when ``target`` holds a checkpoint whose SHA-256 matches its
    ``checkpoint.meta.json`` sidecar (payload file for pickle, whole
    directory tree for orbax).  A pre-hardening checkpoint without a
    sidecar passes (nothing to check against); a missing checkpoint or
    checksum mismatch fails."""
    orbax_dir = os.path.join(target, "orbax")
    pkl = os.path.join(target, _CKPT_FILE)
    has_orbax_dir = os.path.isdir(orbax_dir)
    if not has_orbax_dir and not os.path.exists(pkl):
        return False
    meta_path = os.path.join(target, _META_FILE)
    if not os.path.exists(meta_path):
        return True  # legacy checkpoint: no sidecar to verify against
    try:
        with open(meta_path) as fh:
            meta = json.load(fh)
        if meta.get("format") == "orbax" or (
            has_orbax_dir and "size" not in meta
        ):
            return _dir_digest(orbax_dir) == meta["sha256"]
        with open(pkl, "rb") as fh:
            payload = fh.read()
        return (
            len(payload) == int(meta["size"])
            and hashlib.sha256(payload).hexdigest() == meta["sha256"]
        )
    except Exception as e:
        log.warning("checkpoint meta unreadable at %s: %s", target, e)
        return False


def _read_pickle_verified(target: str):
    """Read + integrity-check the pickle payload; returns the state or a
    corruption ``_LoadError`` (broadcastable to non-root ranks)."""
    from . import metrics

    pkl = os.path.join(target, _CKPT_FILE)
    if not verify_checkpoint(target):
        metrics.inc_counter("checkpoint.corrupt_detected")
        return _LoadError(
            f"checkpoint at {target} failed checksum verification "
            "(truncated or corrupted payload)", corrupt=True,
        )
    try:
        with open(pkl, "rb") as fh:
            return pickle.load(fh)
    except Exception as e:
        metrics.inc_counter("checkpoint.corrupt_detected")
        return _LoadError(
            f"checkpoint at {target} is undecodable: {e}", corrupt=True,
        )


def load_checkpoint(
    path: str,
    step: Optional[int] = None,
    broadcast: bool = True,
    _select=None,
) -> Optional[Dict[str, Any]]:
    """Load a checkpoint; returns None if absent.  With ``broadcast``
    (default), only rank 0 touches the filesystem and its bytes are
    broadcast, so all ranks restore identically even when local files
    are divergent, partially written, or missing on non-root ranks.
    Raises :class:`CheckpointCorruptionError` (on every rank) when the
    checkpoint exists but fails integrity verification.

    ``_select`` (internal, see :func:`load_params`) post-processes the
    state on the reading rank *before* the broadcast — either a reduced
    state dict or a :class:`_LoadError` — so non-root ranks only ever
    receive (and materialize) the selected subset."""
    import time

    t0 = time.perf_counter()
    target = path if step is None else os.path.join(path, f"step_{step}")
    rt = runtime.get_runtime_or_none()
    multi = rt is not None and rt.process_count > 1
    state = None
    if not (broadcast and multi and rt.process_rank != 0):
        orbax_dir = os.path.join(target, "orbax")
        pkl = os.path.join(target, _CKPT_FILE)
        if os.path.isdir(orbax_dir):
            if not _has_orbax():
                # Refuse to silently restart from scratch — but in a
                # multi-process world the error must reach every rank
                # through the broadcast below, or non-root ranks hang in
                # the collective waiting for rank 0's payload.
                state = _LoadError(
                    f"checkpoint at {orbax_dir} was written with orbax, "
                    "which is not importable here — install "
                    "orbax-checkpoint to restore it"
                )
            elif not verify_checkpoint(target):
                from . import metrics

                metrics.inc_counter("checkpoint.corrupt_detected")
                state = _LoadError(
                    f"checkpoint at {target} failed checksum "
                    "verification (truncated or corrupted payload)",
                    corrupt=True,
                )
            else:
                import orbax.checkpoint as ocp

                state = ocp.PyTreeCheckpointer().restore(orbax_dir)
        elif os.path.exists(pkl):
            state = _read_pickle_verified(target)
        if _select is not None and state is not None \
                and not isinstance(state, _LoadError):
            state = _select(state)
    if broadcast and multi:
        state = functions.broadcast_object(state, root_rank=0)
    if isinstance(state, _LoadError):
        state.raise_()
    if state is not None:
        from . import metrics

        metrics.observe(
            "checkpoint.restore_seconds", time.perf_counter() - t0
        )
    return state


PARAMS_KEY = "params"


def load_params(
    path: str,
    step: Optional[int] = None,
    broadcast: bool = True,
    keys: tuple = (PARAMS_KEY,),
) -> Optional[Dict[str, Any]]:
    """Params-only restore for serving replicas (``serve/replica.py``).

    A training checkpoint holds the full resumable state — params plus
    optimizer moments, which for Adam-family optimizers are 2x the
    model again.  An inference replica must never materialize that
    optimizer state: the requested ``keys`` (default ``("params",)``)
    are selected on the *reading* rank before the restore broadcast, so
    the dropped entries neither cross the wire nor land on any other
    rank, and the returned dict holds exactly ``keys``.

    Returns None when no checkpoint exists.  A checkpoint that exists
    but lacks a requested key raises
    :class:`~horovod_tpu.exceptions.CheckpointMissingKeysError` on
    every rank — a structured error naming the absent keys (and what
    the checkpoint does hold) instead of a raw ``KeyError``."""
    from . import metrics

    if step is None and _all_steps(path):
        # A training run's root directory: serve from the newest step
        # that passes verification (corrupted newer steps are skipped,
        # same policy as restore_or_init).
        step = latest_good_step(path)
    target = path if step is None else os.path.join(path, f"step_{step}")
    want = tuple(keys)

    def select(state):
        if not isinstance(state, dict):
            return _LoadError(
                f"checkpoint at {target} holds a "
                f"{type(state).__name__}, not a state dict",
            )
        missing = [k for k in want if k not in state]
        if missing:
            return _LoadError(
                "missing keys", missing=sorted(missing),
                available=sorted(map(str, state)), path=target,
            )
        dropped = sorted(k for k in state if k not in want)
        if dropped:
            log.info(
                "params-only restore from %s: dropped %s before "
                "broadcast", target, dropped,
            )
        return {k: state[k] for k in want}

    out = load_checkpoint(path, step=step, broadcast=broadcast,
                          _select=select)
    if out is not None:
        metrics.inc_counter("checkpoint.params_only_restore")
    return out


def _all_steps(path: str) -> List[int]:
    if not os.path.isdir(path):
        return []
    steps = []
    for name in os.listdir(path):
        if name.startswith("step_"):
            try:
                steps.append(int(name[5:]))
            except ValueError:
                pass
    return sorted(steps)


def latest_step(path: str) -> Optional[int]:
    """Highest ``step_N`` subdirectory under ``path`` (resume point)."""
    steps = _all_steps(path)
    return steps[-1] if steps else None


def latest_good_step(path: str) -> Optional[int]:
    """Highest ``step_N`` that passes :func:`verify_checkpoint` —
    corrupted newer steps are skipped (and counted) so resume falls
    back to the last good snapshot instead of dying on bit rot."""
    from . import metrics

    steps = _all_steps(path)
    for i, step in enumerate(reversed(steps)):
        target = os.path.join(path, f"step_{step}")
        if verify_checkpoint(target):
            if i > 0:
                metrics.inc_counter("checkpoint.fallback")
                events.emit(events.CHECKPOINT_FALLBACK, path=path,
                            step=step, skipped=i)
                log.warning(
                    "falling back to checkpoint step %d (%d newer "
                    "step(s) failed verification)", step, i,
                )
            return step
        metrics.inc_counter("checkpoint.corrupt_detected")
        events.emit(events.CHECKPOINT_CORRUPT, path=target, step=step)
        log.warning(
            "checkpoint step %d at %s failed verification; trying "
            "an earlier step", step, target,
        )
    return None


def restore_or_init(
    path: str,
    init_state: Dict[str, Any],
) -> tuple:
    """Resume from the newest *verified* checkpoint under ``path`` or
    fall back to ``init_state`` broadcast from rank 0.  Returns
    (state, step) with step == 0 for a fresh start (the reference's
    resume_from_epoch pattern, ``pytorch_imagenet_resnet50.py``).
    Corrupted newer checkpoints are skipped in favor of the last good
    one (``latest_good_step``).

    The resume-vs-init decision is rank 0's, broadcast to all — ranks
    must take the same branch or their collective sequences diverge
    (checkpoints are written by rank 0, so other ranks' filesystems may
    legitimately not have them).
    """
    rt = runtime.get_runtime_or_none()
    step = latest_good_step(path)
    if rt is not None and rt.process_count > 1:
        step = functions.broadcast_object(step, root_rank=0)
    if step is not None:
        state = load_checkpoint(path, step=step)
        if state is not None:
            return state, step
    if rt is None:
        # usable before hvd.init() like the rest of this module
        return init_state, 0
    return functions.broadcast_parameters(init_state, root_rank=0), 0
