"""Checkpoint save/restore for params + optimizer state.

Reference checkpoint/resume mechanisms (SURVEY.md §5): (1) elastic
``State`` in-memory commits (``horovod_tpu/elastic/state.py``), (2)
Spark store checkpoints (``horovod_tpu/spark/store.py``), and (3) Keras
``load_model`` with hvd-wrapped optimizers (``keras/__init__.py:167``)
— a durable on-disk format that round-trips the full training state.
This module is mechanism (3) for the TPU build: orbax when available
(async, sharded, multi-host), msgpack-free npz/pickle fallback
otherwise.

Rank-0-writes / all-read, with a ``broadcast`` on restore so every rank
starts from identical bytes (the reference's
``BroadcastGlobalVariablesCallback``-after-load pattern).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

import jax
import numpy as np

from . import functions, runtime
from .utils.logging import get_logger

log = get_logger()

_CKPT_FILE = "checkpoint.pkl"


class _LoadError:
    """Picklable error sentinel broadcast to all ranks so load failures
    raise everywhere instead of deadlocking non-root ranks."""

    def __init__(self, message: str):
        self.message = message


def _has_orbax() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


def save_checkpoint(
    path: str,
    state: Dict[str, Any],
    step: Optional[int] = None,
    use_orbax: Optional[bool] = None,
) -> str:
    """Write ``state`` (a dict of pytrees: params, opt_state, ...) under
    ``path``; only rank 0 writes (reference: checkpoints saved on rank 0,
    e.g. ``examples/pytorch/pytorch_imagenet_resnet50.py``'s
    ``save_checkpoint``).  Returns the checkpoint directory."""
    target = path if step is None else os.path.join(path, f"step_{step}")
    rt = runtime.get_runtime_or_none()
    if rt is not None and rt.process_rank != 0:
        return target
    os.makedirs(target, exist_ok=True)
    if use_orbax is None:
        use_orbax = _has_orbax()
    host_state = jax.device_get(state)
    if use_orbax:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(
            os.path.join(target, "orbax"), host_state,
            force=True,
        )
    else:
        with open(os.path.join(target, _CKPT_FILE), "wb") as fh:
            pickle.dump(host_state, fh)
    log.info("checkpoint saved to %s", target)
    return target


def load_checkpoint(
    path: str,
    step: Optional[int] = None,
    broadcast: bool = True,
) -> Optional[Dict[str, Any]]:
    """Load a checkpoint; returns None if absent.  With ``broadcast``
    (default), only rank 0 touches the filesystem and its bytes are
    broadcast, so all ranks restore identically even when local files
    are divergent, partially written, or missing on non-root ranks."""
    target = path if step is None else os.path.join(path, f"step_{step}")
    rt = runtime.get_runtime_or_none()
    multi = rt is not None and rt.process_count > 1
    state = None
    if not (broadcast and multi and rt.process_rank != 0):
        orbax_dir = os.path.join(target, "orbax")
        pkl = os.path.join(target, _CKPT_FILE)
        if os.path.isdir(orbax_dir):
            if not _has_orbax():
                # Refuse to silently restart from scratch — but in a
                # multi-process world the error must reach every rank
                # through the broadcast below, or non-root ranks hang in
                # the collective waiting for rank 0's payload.
                state = _LoadError(
                    f"checkpoint at {orbax_dir} was written with orbax, "
                    "which is not importable here — install "
                    "orbax-checkpoint to restore it"
                )
            else:
                import orbax.checkpoint as ocp

                state = ocp.PyTreeCheckpointer().restore(orbax_dir)
        elif os.path.exists(pkl):
            with open(pkl, "rb") as fh:
                state = pickle.load(fh)
    if broadcast and multi:
        state = functions.broadcast_object(state, root_rank=0)
    if isinstance(state, _LoadError):
        raise RuntimeError(state.message)
    return state


def latest_step(path: str) -> Optional[int]:
    """Highest ``step_N`` subdirectory under ``path`` (resume point)."""
    if not os.path.isdir(path):
        return None
    steps = []
    for name in os.listdir(path):
        if name.startswith("step_"):
            try:
                steps.append(int(name[5:]))
            except ValueError:
                pass
    return max(steps) if steps else None


def restore_or_init(
    path: str,
    init_state: Dict[str, Any],
) -> tuple:
    """Resume from the newest checkpoint under ``path`` or fall back to
    ``init_state`` broadcast from rank 0.  Returns (state, step) with
    step == 0 for a fresh start (the reference's resume_from_epoch
    pattern, ``pytorch_imagenet_resnet50.py``).

    The resume-vs-init decision is rank 0's, broadcast to all — ranks
    must take the same branch or their collective sequences diverge
    (checkpoints are written by rank 0, so other ranks' filesystems may
    legitimately not have them).
    """
    rt = runtime.get_runtime_or_none()
    step = latest_step(path)
    if rt is not None and rt.process_count > 1:
        step = functions.broadcast_object(step, root_rank=0)
    if step is not None:
        state = load_checkpoint(path, step=step)
        if state is not None:
            return state, step
    if rt is None:
        # usable before hvd.init() like the rest of this module
        return init_state, 0
    return functions.broadcast_parameters(init_state, root_rank=0), 0
