"""MNIST models (reference ``examples/pytorch/pytorch_mnist.py:30-50``
``Net``: conv5x5(10) -> pool -> conv5x5(20) -> pool -> fc50 -> fc10)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    """The reference example's LeNet-style net, NHWC for TPU."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: (B, 28, 28, 1)
        x = x.astype(self.dtype)
        x = nn.Conv(10, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(50, dtype=self.dtype)(x))
        x = nn.Dense(10, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class MnistMLP(nn.Module):
    """Small MLP used by unit tests (fast to init/compile)."""

    hidden: int = 128
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        return nn.Dense(10, dtype=self.dtype)(x).astype(jnp.float32)
