"""Inception V3 for TPU (headline benchmark model: 90% scaling
efficiency at 512 GPUs, ``docs/benchmarks.rst:13-14``; the
mixed-branch-width design exercises XLA's conv fusion very differently
from ResNet's uniform bottlenecks).

Faithful V3 topology (stem → 3×InceptionA → grid reduction →
4×InceptionB → grid reduction → 2×InceptionC → global pool); branches
use NHWC, bf16 compute, BatchNorm with fp32 stats.  The auxiliary
classifier is omitted (training-signal trick, not part of the serving
graph the benchmarks time).
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(
            self.features, self.kernel, strides=self.strides,
            padding=self.padding, use_bias=False, dtype=self.dtype,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-3,
            dtype=jnp.float32,
        )(x)
        return nn.relu(x).astype(self.dtype)


class InceptionA(nn.Module):
    pool_features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        d = self.dtype
        b1 = ConvBN(64, (1, 1), dtype=d)(x, train)
        b2 = ConvBN(48, (1, 1), dtype=d)(x, train)
        b2 = ConvBN(64, (5, 5), dtype=d)(b2, train)
        b3 = ConvBN(64, (1, 1), dtype=d)(x, train)
        b3 = ConvBN(96, (3, 3), dtype=d)(b3, train)
        b3 = ConvBN(96, (3, 3), dtype=d)(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = ConvBN(self.pool_features, (1, 1), dtype=d)(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionA(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        d = self.dtype
        b1 = ConvBN(384, (3, 3), strides=(2, 2), padding="VALID", dtype=d)(x, train)
        b2 = ConvBN(64, (1, 1), dtype=d)(x, train)
        b2 = ConvBN(96, (3, 3), dtype=d)(b2, train)
        b2 = ConvBN(96, (3, 3), strides=(2, 2), padding="VALID", dtype=d)(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionB(nn.Module):
    channels_7x7: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        d, c = self.dtype, self.channels_7x7
        b1 = ConvBN(192, (1, 1), dtype=d)(x, train)
        b2 = ConvBN(c, (1, 1), dtype=d)(x, train)
        b2 = ConvBN(c, (1, 7), dtype=d)(b2, train)
        b2 = ConvBN(192, (7, 1), dtype=d)(b2, train)
        b3 = ConvBN(c, (1, 1), dtype=d)(x, train)
        b3 = ConvBN(c, (7, 1), dtype=d)(b3, train)
        b3 = ConvBN(c, (1, 7), dtype=d)(b3, train)
        b3 = ConvBN(c, (7, 1), dtype=d)(b3, train)
        b3 = ConvBN(192, (1, 7), dtype=d)(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = ConvBN(192, (1, 1), dtype=d)(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionB(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        d = self.dtype
        b1 = ConvBN(192, (1, 1), dtype=d)(x, train)
        b1 = ConvBN(320, (3, 3), strides=(2, 2), padding="VALID", dtype=d)(b1, train)
        b2 = ConvBN(192, (1, 1), dtype=d)(x, train)
        b2 = ConvBN(192, (1, 7), dtype=d)(b2, train)
        b2 = ConvBN(192, (7, 1), dtype=d)(b2, train)
        b2 = ConvBN(192, (3, 3), strides=(2, 2), padding="VALID", dtype=d)(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        d = self.dtype
        b1 = ConvBN(320, (1, 1), dtype=d)(x, train)
        b2 = ConvBN(384, (1, 1), dtype=d)(x, train)
        b2a = ConvBN(384, (1, 3), dtype=d)(b2, train)
        b2b = ConvBN(384, (3, 1), dtype=d)(b2, train)
        b2 = jnp.concatenate([b2a, b2b], axis=-1)
        b3 = ConvBN(448, (1, 1), dtype=d)(x, train)
        b3 = ConvBN(384, (3, 3), dtype=d)(b3, train)
        b3a = ConvBN(384, (1, 3), dtype=d)(b3, train)
        b3b = ConvBN(384, (3, 1), dtype=d)(b3, train)
        b3 = jnp.concatenate([b3a, b3b], axis=-1)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = ConvBN(192, (1, 1), dtype=d)(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        d = self.dtype
        x = x.astype(d)
        # stem (299x299 canonical; any size >= ~75 works, pooling is global)
        x = ConvBN(32, (3, 3), strides=(2, 2), padding="VALID", dtype=d)(x, train)
        x = ConvBN(32, (3, 3), padding="VALID", dtype=d)(x, train)
        x = ConvBN(64, (3, 3), dtype=d)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = ConvBN(80, (1, 1), padding="VALID", dtype=d)(x, train)
        x = ConvBN(192, (3, 3), padding="VALID", dtype=d)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = InceptionA(32, dtype=d)(x, train)
        x = InceptionA(64, dtype=d)(x, train)
        x = InceptionA(64, dtype=d)(x, train)
        x = ReductionA(dtype=d)(x, train)
        x = InceptionB(128, dtype=d)(x, train)
        x = InceptionB(160, dtype=d)(x, train)
        x = InceptionB(160, dtype=d)(x, train)
        x = InceptionB(192, dtype=d)(x, train)
        x = ReductionB(dtype=d)(x, train)
        x = InceptionC(dtype=d)(x, train)
        x = InceptionC(dtype=d)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
