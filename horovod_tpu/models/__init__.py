"""Model zoo used by the examples, benchmarks, and tests.

The reference ships models inside its example scripts
(``examples/pytorch/pytorch_mnist.py`` Net, the tf_cnn_benchmarks
ResNet/VGG/Inception configs cited by ``docs/benchmarks.rst``); here they
are first-class flax modules designed for TPU: NHWC layouts, bf16
compute with fp32 params, shapes padded to MXU tiles.
"""

from .mnist import MnistCNN, MnistMLP  # noqa: F401
from .resnet import ResNet, ResNet50, ResNet101, ResNet152  # noqa: F401
from .vgg import VGG, VGG16, VGG19  # noqa: F401
from .inception import InceptionV3  # noqa: F401
from .transformer import (  # noqa: F401
    Transformer,
    TransformerConfig,
    gpt_small,
    gpt_tiny,
)
