"""GPT-style decoder-only transformer, wired for hybrid parallelism.

The reference has no model code (Horovod sits below the model; its
model zoo is the example scripts, SURVEY.md §2 L8) — but the TPU build
must demonstrate long-context and model parallelism as first-class
(SURVEY.md §5, §7 step 9), and that requires a transformer to hang them
on.  TPU-first choices:

* bf16 activations with fp32 LayerNorm/softmax/params (MXU-friendly).
* attention impl selectable per config: "full" (single device),
  "ring" (context parallel over the sp axis — parallel/ring_attention),
  "ulysses" (all_to_all sequence parallel — parallel/ulysses).
* QKV/out projections are column/row tensor-parallel over the tp axis
  (one psum per attention + one per MLP, the Megatron pairing).
* optional MoE FFN sharded over the ep axis (parallel/moe).

All modules degrade gracefully outside shard_map: tp/sp/ep axes absent
⇒ plain dense single-device transformer (the test and entry() path).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.mesh import EP_AXIS, SP_AXIS, TP_AXIS
from ..parallel.moe import MoELayer
from ..parallel.ring_attention import full_attention, ring_attention
from ..parallel.tensor import (
    ColumnParallelDense,
    RowParallelDense,
    TensorParallelMLP,
    _axis_present,
)
from ..parallel.ulysses import ulysses_attention
from ..ops.pallas_kernels import flash_attention

Dtype = Any


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    model_dim: int = 768
    num_heads: int = 12          # GLOBAL head count
    head_dim: int = 64
    ff_dim: int = 3072           # GLOBAL feed-forward width
    max_len: int = 2048
    dtype: Any = jnp.bfloat16
    causal: bool = True
    # Parallelism:
    attn_impl: str = "flash"     # "flash" | "full" | "ring" | "ulysses"
    sp_axis: str = SP_AXIS
    tp_axis: str = TP_AXIS
    remat: bool = False          # jax.checkpoint each block (long-context)
    # MoE (0 ⇒ dense FFN everywhere):
    moe_every: int = 0           # use MoE FFN in every k-th block
    num_experts_local: int = 1
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    ep_axis: str = EP_AXIS


def _tp_degree(axis: str) -> int:
    return lax.axis_size(axis) if _axis_present(axis) else 1


class Attention(nn.Module):
    """Multi-head attention: tp-sharded projections + sp-sharded
    sequence (ring or Ulysses)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array,
                 segment_ids: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        if cfg.attn_impl not in ("flash", "full", "ring", "ulysses"):
            raise ValueError(
                f"unknown attn_impl {cfg.attn_impl!r}; expected "
                "'flash', 'full', 'ring', or 'ulysses'"
            )
        tp = _tp_degree(cfg.tp_axis)
        if cfg.num_heads % tp != 0:
            raise ValueError(
                f"num_heads {cfg.num_heads} not divisible by tp degree {tp}"
            )
        h_local = cfg.num_heads // tp
        b, t, _ = x.shape

        qkv = ColumnParallelDense(
            3 * cfg.num_heads * cfg.head_dim, axis=cfg.tp_axis,
            dtype=cfg.dtype, name="qkv",
        )(x)
        qkv = qkv.reshape(b, t, 3, h_local, cfg.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

        if segment_ids is not None and cfg.attn_impl not in ("flash", "full"):
            raise ValueError(
                "packed sequences (segment_ids) require attn_impl='flash' "
                "or 'full'; sequence-parallel impls do not support packing"
            )
        # With the sp axis absent the sequence is unsharded, so plain
        # full attention is the correct lowering for every impl.
        if cfg.attn_impl == "ring" and _axis_present(cfg.sp_axis):
            out = ring_attention(q, k, v, axis=cfg.sp_axis, causal=cfg.causal)
        elif cfg.attn_impl == "ulysses" and _axis_present(cfg.sp_axis):
            # The post-exchange [B, T_global, H/n, D] attention is the
            # fused Pallas kernel — full sequence, fraction of the heads.
            out = ulysses_attention(
                q, k, v, axis=cfg.sp_axis, causal=cfg.causal,
                attn_fn=flash_attention,
            )
        elif _axis_present(cfg.sp_axis) and lax.axis_size(cfg.sp_axis) > 1:
            # flash/full attend only within the local shard: on a
            # sequence-sharded mesh that silently drops cross-shard
            # attention, so refuse rather than return wrong logits.
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r} is shard-local but the "
                f"sequence axis {cfg.sp_axis!r} is present in the mesh; "
                "use attn_impl='ring' or 'ulysses' for sequence parallelism"
            )
        elif cfg.attn_impl == "flash":
            out = flash_attention(q, k, v, cfg.causal,
                                  segment_ids=segment_ids)
        else:
            out = full_attention(q, k, v, causal=cfg.causal,
                                 segment_ids=segment_ids)

        out = out.reshape(b, t, h_local * cfg.head_dim)
        return RowParallelDense(
            cfg.model_dim, axis=cfg.tp_axis, dtype=cfg.dtype, name="proj"
        )(out)


class Block(nn.Module):
    """Pre-LN transformer block; FFN is dense-TP or MoE."""

    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(
        self, x: jax.Array, segment_ids: Optional[jax.Array] = None
    ) -> Tuple[jax.Array, jax.Array]:
        cfg = self.cfg
        # LayerNorm in fp32 — the numerically load-bearing reductions.
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x)
        x = x + Attention(cfg, name="attn")(h.astype(cfg.dtype),
                                            segment_ids)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x)
        h = h.astype(cfg.dtype)
        aux = jnp.zeros((), jnp.float32)
        if self.use_moe:
            y, aux = MoELayer(
                num_experts_local=cfg.num_experts_local,
                hidden=cfg.ff_dim // max(1, cfg.num_experts_local),
                k=cfg.moe_k,
                capacity_factor=cfg.moe_capacity_factor,
                axis=cfg.ep_axis,
                dtype=cfg.dtype,
                name="moe",
            )(h)
        else:
            y = TensorParallelMLP(
                hidden=cfg.ff_dim,
                features=cfg.model_dim,
                axis=cfg.tp_axis,
                dtype=cfg.dtype,
                name="mlp",
            )(h)
        return x + y.astype(x.dtype), aux


class Transformer(nn.Module):
    """Decoder-only LM.  Input: int32 token ids [B, T_local] (T_local =
    T_global / sp when the sequence is sharded).  Returns (logits
    [B, T_local, vocab], moe_aux_loss scalar)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 segment_ids: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, jax.Array]:
        cfg = self.cfg
        b, t = tokens.shape
        emb = nn.Embed(
            cfg.vocab_size, cfg.model_dim,
            embedding_init=nn.initializers.normal(0.02), name="wte",
        )
        x = emb(tokens)
        # Positional embedding at GLOBAL positions: offset by this
        # device's sequence-block index when sharded over sp.
        pos = jnp.arange(t)
        t_global = t
        if _axis_present(cfg.sp_axis):
            if segment_ids is not None and lax.axis_size(cfg.sp_axis) > 1:
                raise ValueError(
                    "packed sequences cannot be sequence-sharded; drop "
                    "the sp axis or the segment_ids"
                )
            t_global = t * lax.axis_size(cfg.sp_axis)
            pos = pos + lax.axis_index(cfg.sp_axis) * t
        if t_global > cfg.max_len:
            raise ValueError(
                f"sequence length {t_global} exceeds max_len {cfg.max_len}"
            )
        wpe = self.param(
            "wpe", nn.initializers.normal(0.02),
            (cfg.max_len, cfg.model_dim), jnp.float32,
        )
        if segment_ids is not None:
            # Positions restart at each packed document so every
            # document sees the positional embeddings it would see
            # alone in the row.
            idx = jnp.broadcast_to(jnp.arange(t)[None], (b, t))
            is_start = jnp.concatenate(
                [jnp.ones((b, 1), bool),
                 segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1,
            )
            start_idx = lax.cummax(
                jnp.where(is_start, idx, 0), axis=1
            )
            pos2d = idx - start_idx  # [B, T]
            x = (x + jnp.take(wpe, pos2d, axis=0)).astype(cfg.dtype)
        else:
            x = (x + jnp.take(wpe, pos, axis=0)[None]).astype(cfg.dtype)

        aux_total = jnp.zeros((), jnp.float32)
        # remat: recompute block activations in backward instead of
        # storing them (jax.checkpoint) — the standard FLOPs-for-HBM
        # trade that unlocks larger batch/sequence (long-context).
        block_cls = nn.remat(Block) if cfg.remat else Block
        for i in range(cfg.num_layers):
            use_moe = (
                cfg.moe_every > 0 and (i + 1) % cfg.moe_every == 0
            )
            x, aux = block_cls(cfg, use_moe=use_moe, name=f"block_{i}")(
                x, segment_ids
            )
            aux_total = aux_total + aux

        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        # Tied output head (GPT-2 style): logits via embed transpose.
        # The head matmul is ~25% of model FLOPs at T=1024 — run it in
        # the compute dtype (bf16 hits the MXU at full rate; fp32 runs
        # at ~1/8) and cast up for the fp32 softmax/loss downstream.
        logits = (
            x.astype(cfg.dtype) @ emb.embedding.T.astype(cfg.dtype)
        ).astype(jnp.float32)
        return logits, aux_total


def param_shard_axes(params, cfg: TransformerConfig):
    """Pytree (matching ``params``) of space-separated mesh-axis names
    each parameter is sharded over, for ``parallel.sync_gradients``.

    Rules mirror the module structure: attention qkv/proj kernels and
    MLP wi/wo kernels are tp-sharded (column/row); MoE expert weights
    are ep-sharded; embeddings / LayerNorms / psum-side biases / router
    are replicated.
    """

    def classify(path) -> str:
        keys = [getattr(k, "key", str(k)) for k in path]
        joined = "/".join(str(k) for k in keys)
        leaf = keys[-1] if keys else ""
        if "/moe/" in f"/{joined}/":
            return cfg.ep_axis if leaf in ("wi", "wo") else ""
        if "/attn/" in f"/{joined}/":
            if "/qkv/" in f"/{joined}/":
                return cfg.tp_axis  # column shard: kernel and bias
            if "/proj/" in f"/{joined}/" and leaf == "kernel":
                return cfg.tp_axis  # row shard; proj bias is replicated
            return ""
        if "/mlp/" in f"/{joined}/":
            if "/wi/" in f"/{joined}/":
                return cfg.tp_axis
            if "/wo/" in f"/{joined}/" and leaf == "kernel":
                return cfg.tp_axis
            return ""
        return ""

    return jax.tree_util.tree_map_with_path(
        lambda path, _: classify(path), params
    )


def gpt_small(**overrides) -> Transformer:
    """124M-class config (GPT-2 small) — the flagship LM benchmark."""
    cfg = TransformerConfig(
        vocab_size=50304,  # GPT-2 vocab padded to a multiple of 128 (MXU)
        num_layers=12, model_dim=768, num_heads=12, head_dim=64,
        ff_dim=3072, max_len=1024,
    )
    cfg = dataclasses.replace(cfg, **overrides)
    return Transformer(cfg)


def gpt_tiny(**overrides) -> Transformer:
    """Tiny config for tests and the multi-chip dryrun."""
    cfg = TransformerConfig(
        vocab_size=256, num_layers=2, model_dim=64, num_heads=4,
        head_dim=16, ff_dim=128, max_len=256, dtype=jnp.float32,
    )
    cfg = dataclasses.replace(cfg, **overrides)
    return Transformer(cfg)


def packed_token_cross_entropy(
    logits: jax.Array, tokens: jax.Array, segment_ids: jax.Array
) -> jax.Array:
    """Next-token cross-entropy for PACKED rows: position t predicts
    token t+1 only when both live in the same document (no loss across
    document boundaries), and padding (segment id 0) is excluded.
    Mean over valid positions — equal total weight to what the same
    documents would contribute unpacked.
    """
    l32 = logits[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:].astype(jnp.int32)
    valid = jnp.logical_and(
        segment_ids[:, 1:] == segment_ids[:, :-1],
        segment_ids[:, 1:] > 0,
    )
    import optax

    ce = optax.softmax_cross_entropy_with_integer_labels(l32, targets)
    w = valid.astype(jnp.float32)
    return jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1.0)


def token_cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy WITHOUT materializing a
    ``(B, T, vocab)`` one-hot or normalized-probability tensor — the
    standard LM-loss shape for TPU memory bandwidth (on a 50k vocab at
    batch 16 x 1024 tokens the one-hot formulation allocates an extra
    ~3 GB fp32 temporary per step).  Delegates to optax's integer-label
    CE (the same logsumexp-minus-gather form) with fp32 accumulation.
    """
    l32 = logits.astype(jnp.float32)
    import optax

    return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
        l32, targets.astype(jnp.int32)
    ))
