"""VGG for TPU (one of the reference's three headline benchmark models:
``docs/benchmarks.rst:13-14`` reports 68% scaling efficiency for VGG-16
at 512 GPUs — the hardest of the trio because of its 138M mostly-dense
parameters; it stresses the gradient-allreduce path more than compute).

NHWC, bf16 compute/fp32 params.  The default head is the original two
4096-wide FC layers (``classifier_mlp=True``) — those FCs are what made
VGG the allreduce stress test, so parameter-count parity is the
benchmark-faithful default; pass ``classifier_mlp=False`` for a modern
global-average-pool + single-dense head (much smaller, and
image-size-independent).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

_CFG_16 = (2, 2, 3, 3, 3)
_CFG_19 = (2, 2, 4, 4, 4)
_WIDTHS = (64, 128, 256, 512, 512)


class VGG(nn.Module):
    stage_convs: Sequence[int] = _CFG_16
    num_classes: int = 1000
    classifier_mlp: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for stage, (n_convs, width) in enumerate(
            zip(self.stage_convs, _WIDTHS)
        ):
            for i in range(n_convs):
                x = nn.Conv(
                    width, (3, 3), padding="SAME", dtype=self.dtype,
                    name=f"conv{stage}_{i}",
                )(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        if self.classifier_mlp:
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc1")(x))
            x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc2")(x))
        else:
            x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


VGG16 = partial(VGG, stage_convs=_CFG_16)
VGG19 = partial(VGG, stage_convs=_CFG_19)
