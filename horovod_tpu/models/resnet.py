"""ResNet v1.5 for TPU (the north-star benchmark model).

Reference workload: ``examples/pytorch/pytorch_imagenet_resnet50.py``
(torchvision resnet50) and the tf_cnn_benchmarks ResNet-101 numbers in
``docs/benchmarks.rst:32-43``.  TPU-first design choices:

* NHWC layout (XLA:TPU's native conv layout — no transposes).
* bf16 activations/weights compute with fp32 master params and fp32
  batch-norm statistics (the numerically load-bearing part).
* v1.5 stride placement (stride on the 3x3, like the benchmark configs).
* Channel counts are multiples of 128 in the trunk, aligning to the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """NHWC space-to-depth: (N, H, W, C) -> (N, H/b, W/b, C*b*b)."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, c * block * block)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    act: Callable = nn.relu
    # True synchronized BN: moments allreduced across the mesh before
    # normalizing (hvd.SyncBatchNorm) — the per-replica-moments default
    # matches the reference benchmark configs.
    sync_bn: bool = False
    # "conv7" = the canonical 7x7/2 stem; "space_to_depth" folds that
    # conv into a 4x4/1 conv on 2x2-space-to-depth input (the MLPerf
    # TPU trick): a 3-channel 7x7 conv feeds the 128-lane MXU only 3
    # useful input channels, while the folded form feeds 12 on a
    # quarter the spatial positions — mathematically the same function
    # (see tests/test_models.py equivalence proof), much better MXU
    # utilization.
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        # BN compute dtype follows the model (bf16): flax's _compute_stats
        # always promotes to fp32 internally for the moments and keeps
        # batch_stats fp32, so only the normalize/scale multiply runs in
        # bf16 — measured +19% ResNet-50 step throughput on v5e vs
        # forcing the whole BN through fp32.
        if self.sync_bn:
            from ..sync_batch_norm import SyncBatchNorm as norm_cls
        else:
            norm_cls = nn.BatchNorm
        norm = partial(
            norm_cls,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        if self.stem not in ("conv7", "space_to_depth"):
            raise ValueError(
                f"unknown stem {self.stem!r}; expected 'conv7' or "
                "'space_to_depth'"
            )
        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            if (x.shape[1] % 2) or (x.shape[2] % 2):
                raise ValueError(
                    "space_to_depth stem needs even input H/W "
                    f"(got {x.shape[1]}x{x.shape[2]}); use stem='conv7' "
                    "for odd sizes"
                )
            # Equivalent computation to conv7x7/2 pad 3: output i of
            # that conv reads padded rows [2i, 2i+7) — blocks [i, i+4)
            # after 2x2 s2d — so a 4x4 STRIDE-1 conv over the block
            # grid computes the same function (kernel = the 7x7 zero-
            # extended to 8x8 and folded into 4x4x(4C)); same output
            # positions, 4x the MXU input channels.
            x = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
            x = space_to_depth(x, 2)
            x = conv(self.num_filters, (4, 4), (1, 1), padding="VALID",
                     name="conv_init_s2d")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=self.act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])
