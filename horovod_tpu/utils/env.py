"""Environment-variable config layer.

The reference centralizes ~40 ``HOROVOD_*`` env knobs in
``horovod/common/common.h:107-139`` and parses them in
``BackgroundThreadLoop`` (``operations.cc:459-588``).  We keep the same
three-layer config model (env vars < CLI flags < per-call kwargs) with the
``HVD_TPU_*`` prefix, accepting the legacy ``HOROVOD_*`` spelling as a
fallback so reference users can switch without editing their job scripts.
"""

from __future__ import annotations

import os
from typing import Optional

# Knob names (HVD_TPU_ prefix; HOROVOD_ prefix accepted as fallback).
FUSION_THRESHOLD = "FUSION_THRESHOLD"  # bytes; reference default 64MB
CYCLE_TIME = "CYCLE_TIME"  # ms; kept for API parity (no bg thread on TPU)
CACHE_CAPACITY = "CACHE_CAPACITY"
TIMELINE = "TIMELINE"
TIMELINE_MARK_CYCLES = "TIMELINE_MARK_CYCLES"
AUTOTUNE = "AUTOTUNE"
AUTOTUNE_LOG = "AUTOTUNE_LOG"
LOG_LEVEL = "LOG_LEVEL"
# Debug mode: every eager collective cross-checks its wire Request
# (type/dtype/shape/name) across processes before dispatch, erroring on
# mismatch — the reference controller's negotiation-time validation
# (controller.cc ConstructResponse error joining) as an opt-in check.
CONSISTENCY_CHECK = "CONSISTENCY_CHECK"
STALL_CHECK_DISABLE = "STALL_CHECK_DISABLE"
STALL_CHECK_TIME_SECONDS = "STALL_CHECK_TIME_SECONDS"
STALL_SHUTDOWN_TIME_SECONDS = "STALL_SHUTDOWN_TIME_SECONDS"
ELASTIC_ENABLED = "ELASTIC"
ELASTIC_TIMEOUT = "ELASTIC_TIMEOUT"
# Structured JSONL elastic event log path (events.py).
ELASTIC_EVENT_LOG = "ELASTIC_EVENT_LOG"
# Elastic driver HTTP /metrics + /health port (0 = OS-assigned;
# unset = disabled) — runner/telemetry_http.py.
TELEMETRY_PORT = "TELEMETRY_PORT"
START_TIMEOUT = "START_TIMEOUT"
DISABLE_GROUP_FUSION = "DISABLE_GROUP_FUSION"
DYNAMIC_PROCESS_SETS = "DYNAMIC_PROCESS_SETS"
HIERARCHICAL_ALLREDUCE = "HIERARCHICAL_ALLREDUCE"  # reference HOROVOD_HIERARCHICAL_ALLREDUCE
# Payload bytes above which arbitrary (non-partition) process-set
# collectives use member-only ppermute rings/trees instead of masked
# whole-world collectives. No reference analog (MPI communicators always
# touch members only); the knob trades latency vs non-member bandwidth.
SET_RING_THRESHOLD = "SET_RING_THRESHOLD"
PROCESS_SETS = "PROCESS_SETS"
BATCH_D2D_MEMCOPIES = "BATCH_D2D_MEMCOPIES"
NUM_STREAMS = "NUM_STREAMS"
# Bucketed overlap scheduler (sched/): the gradient-exchange pipeline
# behind DistributedOptimizer.  SCHED=off restores the single-fused-
# exchange legacy path; see docs/scheduler.md.
SCHED = "SCHED"  # on (default) | off
SCHED_MODE = "SCHED_MODE"  # allreduce (default) | reduce_scatter
SCHED_BUCKET_BYTES = "SCHED_BUCKET_BYTES"  # default: fusion threshold
SCHED_LOOK_AHEAD = "SCHED_LOOK_AHEAD"  # bucket-close look-ahead, default 3
SCHED_BARRIERS = "SCHED_BARRIERS"  # optimization_barrier sequencing, default on
SCHED_CAPTURE_ORDER = "SCHED_CAPTURE_ORDER"  # backward-order hooks, default on
# Quantized wire v2 (ops/quantized.py + sched/): per-bucket wire format
# for the scheduler's exchange — off (default; dense/compressor wire) |
# bf16 | int8 | fp8.  See docs/quantization.md.
SCHED_WIRE = "SCHED_WIRE"
# Error-feedback residuals for quantized wires (default on): carry
# r <- (g + r) - dequant(quantize(g + r)) in optimizer state.
SCHED_WIRE_EF = "SCHED_WIRE_EF"
# Elements per quantization block (fp32 scale granularity), default 512.
QUANT_BLOCK = "QUANT_BLOCK"
# Accelerator backend family (backend/registry.py): "auto" (default;
# resolved from jax.devices()[0].platform — gpu/cuda/rocm platforms
# pick the gpu family, everything else the tpu family), "tpu", or
# "gpu".  The override exists so CPU test meshes can force either
# family's lowering tables (rail names, fused-ring kernel module, peak
# table, topology discovery) without hardware.  The RESOLVED family
# folds into the tune-DB knob fingerprint (unset ≡ tpu, so existing
# entries keep their keys).  See docs/backends.md.
BACKEND = "BACKEND"
# Quantized-wire backend: "phase" (default; blockwise quantize ->
# all_to_all of wire chunks + scales -> dequant-accumulate as separate
# XLA HLOs) or "fused" (ops/pallas_quant.py Pallas ring kernels:
# quantize / remote-DMA / fp32 dequant-accumulate in one kernel per ICI
# hop, lax.ppermute standing in for the DMA off-TPU).  Same numerics
# contract either way; participates in the tune-DB knob fingerprint so
# fused and phase winners never collide.  See docs/quantization.md.
QUANT_BACKEND = "QUANT_BACKEND"
# Topology-aware hierarchical collectives (topo/): forced topology
# spec — "SxK" / "SxK1xK2" (S slices of an ICI mesh) or a JSON object
# ({"slices":2,"ici_shape":[2,2],...}) — for CPU tests and forced
# shapes; unset = discover from jax.devices().  See docs/topology.md.
TOPO = "TOPO"
# Lowering policy for gradient-exchange collectives over a multi-slice
# axis: auto (default; cost model picks flat vs hier per bucket) |
# flat/off (always today's single-collective path) | hier/on (force
# the ICI reduce_scatter -> DCN all_reduce -> ICI all_gather staging).
TOPO_LOWER = "TOPO_LOWER"
# Cost-model parameters (per-link bandwidth GB/s, per-hop latency us,
# per-collective-phase fixed overhead us).  Defaults model ~10x
# ICI-vs-DCN bandwidth (arXiv:1810.11112's two-level regime).
TOPO_ICI_GBPS = "TOPO_ICI_GBPS"
TOPO_DCN_GBPS = "TOPO_DCN_GBPS"
TOPO_ICI_LAT_US = "TOPO_ICI_LAT_US"
TOPO_DCN_LAT_US = "TOPO_DCN_LAT_US"
TOPO_PHASE_OVERHEAD_US = "TOPO_PHASE_OVERHEAD_US"
# Measured cost model (topo/fit.py): fit effective link parameters
# from the per-collective dispatch histograms and prefer them over the
# static TOPO_* env defaults.  off = static pricing only.
TOPO_FIT = "TOPO_FIT"  # on (default) | off
TOPO_FIT_MIN_OBS = "TOPO_FIT_MIN_OBS"  # observations before first fit
TOPO_FIT_REFIT_EVERY = "TOPO_FIT_REFIT_EVERY"  # new obs between refits
# Unified exchange IR (xir/): route every collective-shaped workload
# (dense DP buckets, MoE all_to_all, Ulysses flips, sparse embedding
# exchange, pipeline ppermute, FSDP RS+AG) through the explicit
# plan->lower->execute pipeline.  off restores the direct-lax call
# paths (bitwise identical).  See docs/exchange_ir.md.
XIR = "XIR"  # on (default) | off
# Wire format non-gradient IR workloads request (default off — an
# explicit numerics opt-in, NOT inherited from HVD_TPU_SCHED_WIRE:
# these ops move activations/embedding rows, not EF-compensated
# gradients).  Shuffle-shaped ops (all_to_all/permute/sparse gather)
# cap at bf16 — int8/fp8 requests downgrade to off for them.
XIR_WIRE = "XIR_WIRE"
# XIR rail pipeliner (xir/pipeline.py): phase-interleave the ICI and
# DCN rails across hier buckets (bucket i's cross-slice DCN hop runs
# concurrently with bucket i+1's ICI reduce-scatter and bucket i-1's
# ICI all-gather, via per-rail optimization_barrier chains).
#   off  = per-bucket chains, PR 10 emission exactly;
#   auto = (default) reorder-only — engage the rail chains when the
#          cost model prices the pipelined order cheaper, never
#          changing the bucket plan;
#   on   = rail chains AND bucket split points chosen from the fitted
#          per-rail bandwidths (plan.build_schedule defers to
#          pipeline.plan_bucket_bytes when no explicit size is set).
# f32 dense losses are bitwise-identical in every mode: the barriers
# are identity on values and reordering never changes summation
# grouping within a bucket.  See docs/exchange_ir.md.
XIR_PIPELINE = "XIR_PIPELINE"
# Whole-step emission (xir/interp.py onestep): fold a step's entire
# exchange schedule — fused buffers, rail-interleaved ordering, AND the
# optimizer-update closure — into ONE compiled dispatch instead of one
# jitted executor per fused buffer / per bucket chain.
#   off  = per-unit dispatch, the PR 18 paths exactly;
#   auto = (default) fold whenever a step has >= 2 dispatch units
#          (like the rail pipeliner, engagement is a scheduling
#          decision, never a numerics one);
#   on   = always fold.
# f32 dense losses are bitwise-identical in every mode: the stitch is
# optimization_barrier ties (identity on values) and the folded units
# emit the same ops in the same per-unit order.  Resolved mode folds
# into the tune-DB knob_fingerprint.  See docs/exchange_ir.md
# ("Whole-step emission").
ONESTEP = "ONESTEP"
# Async exchange service (svc/): the TPU-native BackgroundThreadLoop —
# a persistent executor that accepts XIR programs from concurrent
# producers through a TensorQueue submission API, negotiates readiness
# across producers (the coordinator-bitvector analog), and serves
# repeated program signatures from a ResponseCache without re-lowering.
# off (default) = every exchange dispatches inline exactly as before
# (bitwise identical by construction); on = producers submit plans and
# the service owns the wires.  See docs/exchange_service.md.
SVC = "SVC"  # off (default) | on
# Bounded staleness for the service's dense-gradient pipeline
# (svc/stale.py): 0 (default) = fully synchronous — losses bitwise
# identical to SVC=off; k >= 1 = local SGD / delayed DCN sync — the
# cross-slice hop of step i completes during step i+k's backward
# (DCN-latency hiding across steps, riding the PR 11 rail model).
SVC_STALENESS = "SVC_STALENESS"
# Service-side fusion buffers (svc/fuse.py): bytes one fused wire
# buffer may hold.  The cycle's negotiated submissions coalesce into
# one padded buffer per compatibility class — (op kind, axis/groups,
# wire, lowering, reduce, dtype) — and dispatch as ONE collective (the
# reference FusionBufferManager's 64 MiB staging buffer,
# fusion_buffer_manager.{h,cc}).  0 disables fusion: every submission
# dispatches separately, exactly the PR 12/13 behavior.  Oversize
# programs (> threshold) always pass through unfused.
SVC_FUSION_THRESHOLD = "SVC_FUSION_THRESHOLD"  # bytes; default 64 MiB
# Service cycle time in milliseconds (the reference HOROVOD_CYCLE_TIME,
# common.h:110): after the loop sees a first submission it lingers this
# long before draining the queue, so a burst of producers lands in ONE
# cycle batch (and one fusion pass) instead of one cycle each.  Falls
# back to the legacy CYCLE_TIME knob; default 1.0 ms, 0 = drain
# immediately (the PR 12 behavior).
SVC_CYCLE_TIME = "SVC_CYCLE_TIME"
# Online (cycle_time, fusion_threshold) tuning for the service loop
# (svc/params.py, the reference ParameterManager applied to the two
# service knobs): off (default) = static env values; on = window-score
# candidate pairs from the metrics registry, freeze the winner, pin it
# into the env knobs, and persist it in the tune DB for warm starts.
SVC_TUNE = "SVC_TUNE"  # off (default) | on
# Multi-tenant exchange arbiter (svc/arbiter.py): weighted-fair rail
# scheduling of one cycle's released submissions across tenants.
#   off = (default) FIFO cycle dispatch, the PR 14 behavior exactly;
#   on  = deficit-round-robin across tenant lanes, each batch priced
#         by its ICI/DCN occupancy through the fitted per-rail cost
#         model and charged against the tenant's weighted share.
# Single-tenant worlds are bitwise-identical either way (one lane
# degenerates to seq order).  See docs/multitenant.md.
SVC_ARBITER = "SVC_ARBITER"  # off (default) | on
# This process's tenant name (stamped into every TraceContext and
# Submission).  Unset = derived from the submission's process set
# (``ps:<r0>-<rN>``) when one is attached, else "default".
SVC_TENANT = "SVC_TENANT"
# Per-tenant in-flight cap: how many submissions one tenant may have
# queued/negotiating/dispatching at once before its submit() calls
# block (admission backpressure instead of unbounded queue growth).
# 0 (default) = unbounded, the PR 14 behavior.
SVC_TENANT_INFLIGHT = "SVC_TENANT_INFLIGHT"
# Seconds an admission-throttled submit() waits before being admitted
# anyway (with svc.tenant.admission_timeouts counted) — backpressure
# must slow a producer, never wedge it.  Default 30.
SVC_ADMIT_TIMEOUT = "SVC_ADMIT_TIMEOUT"
# Tenant weights for the deficit-round-robin scheduler:
# "tenantA:2,tenantB:1" (unlisted tenants weigh 1).  A tenant's share
# of the priced rail seconds per scheduling round is proportional to
# its weight.
SVC_TENANT_WEIGHTS = "SVC_TENANT_WEIGHTS"
# DRR quantum in microseconds of priced rail time added to each lane's
# deficit per scheduling round (default 500).  Smaller = finer
# interleaving; any single batch still dispatches once its lane's
# deficit accumulates past its price, so progress is unconditional.
SVC_ARBITER_QUANTUM_US = "SVC_ARBITER_QUANTUM_US"
# Priority preemption bound: when a high-priority tenant requests
# preemption (Arbiter.request_preempt), lower-priority lanes' admission
# stays gated for at most this many service cycles (default 50) even
# if the high-priority backlog never drains — preemption is bounded,
# never a starvation primitive.  Priorities ride the weights knob:
# "tenantA:4" outranks "tenantB:1" (higher weight = higher priority).
SVC_PREEMPT_CYCLES = "SVC_PREEMPT_CYCLES"
# Seconds per service-tuner scoring window (default 0.25).
SVC_TUNE_WINDOW = "SVC_TUNE_WINDOW"
# --- elastic inference serving plane (horovod_tpu/serve/) ----------
# Request-level admission cap: how many accepted-but-unfinished
# requests one replica's batcher may hold before submit() blocks
# (admission backpressure through the arbiter lanes, the request-level
# twin of SVC_TENANT_INFLIGHT).  Default 64; 0 = unbounded.
SERVE_INFLIGHT = "SERVE_INFLIGHT"
# Maximum decode batch: how many active sequences one continuous-
# batching decode step advances together (default 8).
SERVE_BATCH = "SERVE_BATCH"
# KV-cache pool capacity in tokens per replica (default 4096); a full
# pool evicts finished sequences LRU-first and otherwise backpressures
# prefill admission.
SERVE_KV_TOKENS = "SERVE_KV_TOKENS"
# Wire format for the serving plane's tensor-parallel hops
# ("off" | "bf16" | "int8" | "fp8", default off).  EF-free quantized
# wires are exactly right here: inference TP exchanges carry no
# optimizer state to drift.
SERVE_WIRE = "SERVE_WIRE"
# ResponseCache capacity (entries).  Shares the reference's
# HOROVOD_CACHE_CAPACITY knob (common.h:118, response_cache.cc);
# 0 disables the cache (every submission renegotiates + re-lowers).
# CACHE_CAPACITY is declared above with the legacy knob block.
# Persistent schedule autotuning database (sched/store.py): JSON file
# recording converged (bucket_bytes, wire, lowering) per (schedule
# signature, topology, jax version, knob fingerprint); ScheduleTuner
# warm-starts from a hit.  Unset = no persistence (PR 6 behavior).
TUNE_DB = "TUNE_DB"
# A stored schedule is invalidated when the current (fitted) cost
# model's price for it disagrees with the recorded one by more than
# this factor in either direction.
TUNE_STALE_FACTOR = "TUNE_STALE_FACTOR"  # default 4.0
# End-to-end exchange tracing (trace/): span-based host-side tracing of
# the whole submission path (queue -> negotiation -> cache -> lowering
# -> rail phases) plus the per-rank flight recorder.
#   off     = every span call is a shared no-op (zero allocation);
#   summary = (default) spans feed the trace.phase_seconds.* histograms
#             and the flight-recorder ring, no per-span file output;
#   full    = summary + each rank streams its span trees as Chrome-
#             trace JSON (trace_rank<r>.json under HVD_TPU_TRACE_DIR,
#             mergeable by tools/merge_timeline.py).
# Tracing is host-side only: it inserts no ops into a traced step, so
# losses are bitwise identical at every level.  See docs/tracing.md.
TRACE = "TRACE"
# Directory the tracer and flight recorder write to (per-rank Chrome
# traces at level=full; anomaly dump JSON at any non-off level).
# Unset = dumps stay in memory (the last one is queryable), no file IO.
TRACE_DIR = "TRACE_DIR"
# Flight-recorder ring capacity: the last N steps' span trees kept per
# rank for anomaly dumps (default 16).
TRACE_RING = "TRACE_RING"
# Anomaly threshold: a step slower than z x the rolling p50 of recent
# step times dumps the ring (default 3.0).
TRACE_ANOMALY_Z = "TRACE_ANOMALY_Z"
# Cross-rank straggler threshold on the driver: a rank whose per-phase
# p50 exceeds z x the median rank's p50 is flagged in the /trace
# summary and the trace.straggler{rank=,phase=} gauges (default 2.0).
TRACE_STRAGGLER_Z = "TRACE_STRAGGLER_Z"
# On-disk flight-dump retention: keep only the newest N
# flight_rank<r>_*.json anomaly dumps per rank under HVD_TPU_TRACE_DIR,
# deleting oldest-first after each dump (default 64; 0 = unbounded).
# Pruned files bump the trace.dumps_pruned counter.
TRACE_DUMP_KEEP = "TRACE_DUMP_KEEP"
# Async-service negotiation stall timeout (seconds, default 60): a
# submission stuck in negotiation past this emits a svc.stall warning
# naming the missing participants (the PR 2 stall inspector extended to
# the service's producer-level bitvector).
STALL_TIMEOUT = "STALL_TIMEOUT"
# Stall escalation: after this many CONSECUTIVE stalled check intervals
# the negotiator abandons the entry and every posted participant's
# future resolves through the inline-fallback path (counter
# svc.stall_abandoned + a svc_stall_abandon event) — a permanently
# missing participant can never wedge multi-participant producers.
# 0 (default) = warn forever, never abandon (the pre-PR 16 behavior).
STALL_ABANDON = "STALL_ABANDON"
# Per-tenant SLO specs for the driver-side watchdog (runner/slo.py):
#   "tenantA:step=0.5,p99=0.05;tenantB:p99=0.1"
# step = target per-step exchange seconds (sum of the tenant's
# per-phase p50s from trace.tenant_seconds); p99 = target served-
# latency p99 (the arbiter's svc.tenant.wait_seconds histogram).
# Unset/empty = no watchdog, no remediation.  See docs/multitenant.md.
SLO_SPEC = "SLO_SPEC"
# Breach hysteresis: a tenant must breach the same target for this many
# CONSECUTIVE evaluation windows before the watchdog confirms it
# (default 3) — one noisy sample never triggers a remediation.
SLO_WINDOWS = "SLO_WINDOWS"
# Seconds between driver-side SLO evaluations (default 5).
SLO_CHECK_INTERVAL = "SLO_CHECK_INTERVAL"
# Seconds a tenant's remediation ladder holds at a rung before a
# still-confirmed breach escalates to the next rung (default 30) —
# every rung gets time to take effect before a costlier one fires.
SLO_COOLDOWN = "SLO_COOLDOWN"
# Remediation execution bounds (elastic/remediate.py): per-phase
# attempt timeout in seconds (default 30) and attempts per phase
# (default 2) for the RetryPolicy every escalation rung runs under.
REMEDIATE_TIMEOUT = "REMEDIATE_TIMEOUT"
REMEDIATE_RETRIES = "REMEDIATE_RETRIES"
# Device-time profiling plane (prof/): compiled-step introspection (XLA
# cost/memory analysis per program signature), the per-step host-gap
# profiler, online MFU gauges, and the perf-regression sentinel.
#   on  = (default) everything above; host-side only — profiling
#         inserts no ops into any compiled program, so losses are
#         bitwise identical on vs off.
#   off = every prof call is a no-op; executors are returned unwrapped
#         (exactly the pre-profiling code path).
PROF = "PROF"
# Persistent perf-baseline database (prof/baseline.py): JSON file
# (ScheduleStore machinery, entry kind "prof_baseline") recording
# step-time p50 / MFU / rail-busy per (workload signature, topology,
# knob fingerprint).  Unset = sentinel observes but never persists or
# compares ("no_baseline" verdicts).
PROF_DB = "PROF_DB"
# Regression threshold factor (default 1.5): the sentinel flags a
# regression when observed step p50 exceeds baseline x factor, or
# observed MFU falls below baseline / factor.
PROF_REGRESS_FACTOR = "PROF_REGRESS_FACTOR"
# Sentinel check cadence in steps (default 20); 0 = never auto-check
# (explicit Sentinel.check() only, e.g. from tests or the smoke).
PROF_CHECK_EVERY = "PROF_CHECK_EVERY"
# Directory for jax.profiler capture windows triggered by a confirmed
# perf regression or SLO breach.  Unset (default) = capture hooks are
# inert — no profiler trace is ever started.
PROF_CAPTURE_DIR = "PROF_CAPTURE_DIR"
# Capture-window length in seconds (default 5) and the maximum number
# of capture windows per process (default 2) — a flapping sentinel can
# never fill the disk with profiler traces.
PROF_CAPTURE_SECS = "PROF_CAPTURE_SECS"
PROF_CAPTURE_MAX = "PROF_CAPTURE_MAX"

# Launcher-provided rendezvous env (analog of reference gloo_run.py:65-103).
RANK = "RANK"
SIZE = "SIZE"
LOCAL_RANK = "LOCAL_RANK"
LOCAL_SIZE = "LOCAL_SIZE"
CROSS_RANK = "CROSS_RANK"
CROSS_SIZE = "CROSS_SIZE"
HOSTNAME = "HOSTNAME"
RENDEZVOUS_ADDR = "RENDEZVOUS_ADDR"
RENDEZVOUS_PORT = "RENDEZVOUS_PORT"
COORDINATOR_ADDR = "COORDINATOR_ADDR"  # jax.distributed coordinator

DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024
# Fusion buffers are padded to this many bytes (reference common.h:146
# FUSION_BUFFER_ATOMIC_UNIT = 64); on TPU we align to the fp32 lane tile.
FUSION_BUFFER_ATOMIC_UNIT = 512


def _names(name: str) -> tuple[str, str]:
    return "HVD_TPU_" + name, "HOROVOD_" + name


def get_env(name: str, default: Optional[str] = None) -> Optional[str]:
    """Read a knob, preferring HVD_TPU_<name>, falling back to HOROVOD_<name>."""
    new, legacy = _names(name)
    val = os.environ.get(new)
    if val is None:
        val = os.environ.get(legacy)
    return default if val is None else val


def get_int(name: str, default: int) -> int:
    val = get_env(name)
    if val is None or val == "":
        return default
    try:
        return int(val)
    except ValueError:
        return default


def get_float(name: str, default: float) -> float:
    val = get_env(name)
    if val is None or val == "":
        return default
    try:
        return float(val)
    except ValueError:
        return default


def get_bool(name: str, default: bool = False) -> bool:
    val = get_env(name)
    if val is None or val == "":
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def set_env(name: str, value: str) -> None:
    os.environ["HVD_TPU_" + name] = value
