"""Stall detection for blocking collective waits.

Reference: ``horovod/common/stall_inspector.{h,cc}`` — the background
loop checks tensors pending longer than ``HOROVOD_STALL_CHECK_TIME_SECONDS``
(default 60, ``stall_inspector.h:78``), warns with the offending names,
and optionally aborts after ``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``.

On TPU the collective itself executes inside a compiled XLA program, so
the observable stall point is the *host-side wait* (``block_until_ready``
/ a device->host transfer that never completes because a peer died or a
DCN link hung).  ``StallWatchdog`` tracks named waits via the native
``StallInspector`` (cpp/src/stall.cc) when built, or the pure-Python
fallback below, and a daemon thread reports stalls periodically.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional, Tuple

from .logging import get_logger


class PyStallInspector:
    """Pure-Python mirror of the native StallInspector ABI."""

    def __init__(self, warn_seconds: float = 60.0, shutdown_seconds: float = 0.0):
        self.warn = warn_seconds
        self.shutdown_after = shutdown_seconds
        self._pending: dict = {}
        self._lock = threading.Lock()

    def begin(self, name: str) -> None:
        with self._lock:
            self._pending[name] = time.monotonic()

    def end(self, name: str) -> None:
        with self._lock:
            self._pending.pop(name, None)

    def report(self) -> Tuple[List[str], bool]:
        now = time.monotonic()
        stalled, shutdown = [], False
        with self._lock:
            for name, t0 in self._pending.items():
                age = now - t0
                if age >= self.warn:
                    stalled.append(name)
                if self.shutdown_after > 0 and age >= self.shutdown_after:
                    shutdown = True
        return stalled, shutdown

    def close(self) -> None:
        with self._lock:
            self._pending.clear()


class StallWatchdog:
    """Daemon poll thread over a (native or Python) stall inspector.

    ``wait(value, name)`` is the guarded replacement for
    ``jax.block_until_ready`` on any cross-process-dependent wait: the
    op is registered before blocking and cleared after, so the poll
    thread can warn — the reference's background-loop check
    (``operations.cc`` BackgroundThreadLoop -> CheckForStalledTensors)
    recast for the host-wait world.
    """

    def __init__(
        self,
        warn_seconds: float = 60.0,
        shutdown_seconds: float = 0.0,
        on_stall: Optional[Callable[[List[str]], None]] = None,
        poll_seconds: Optional[float] = None,
    ):
        from .. import native

        if native.available():
            self.inspector = native.StallInspector(warn_seconds, shutdown_seconds)
        else:
            self.inspector = PyStallInspector(warn_seconds, shutdown_seconds)
        self.warn_seconds = warn_seconds
        self.shutdown_seconds = shutdown_seconds
        self._on_stall = on_stall
        self._warned: set = set()
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._stop = threading.Event()
        self._poll = poll_seconds or max(0.05, min(warn_seconds / 2.0, 10.0))
        self._thread = threading.Thread(
            target=self._loop, name="hvd_tpu_stall_watchdog", daemon=True
        )
        self._thread.start()

    def wait(self, value, name: str):
        import jax

        # Concurrent or repeated waits may share a user-facing name
        # (eager handles default to "collective"); key each wait
        # uniquely so one finishing cannot clear another's pending
        # entry.  The suffix is stripped for display in _loop.
        with self._seq_lock:
            self._seq += 1
            key = f"{name}#{self._seq}"
        self.inspector.begin(key)
        try:
            jax.block_until_ready(value)
        finally:
            self.inspector.end(key)
            self._warned.discard(key)
        return value

    def begin(self, name: str) -> None:
        self.inspector.begin(name)

    def end(self, name: str) -> None:
        self.inspector.end(name)
        self._warned.discard(name)

    def _loop(self) -> None:
        from .. import metrics

        while not self._stop.wait(self._poll):
            try:
                stalled, shutdown = self.inspector.report()
            except Exception:
                return  # inspector closed under us during shutdown
            # Export the report through the registry so stalls reach
            # /metrics, not just stderr: a count gauge plus one labeled
            # series per currently-stalled op name.
            current = sorted({s.split("#", 1)[0] for s in stalled})
            metrics.set_gauge("stall.current_stalled", len(current))
            metrics.clear_gauge("stall.stalled")
            for op in current:
                metrics.set_gauge("stall.stalled", 1, labels={"op": op})
            fresh = [s for s in stalled if s not in self._warned]
            if fresh:
                self._warned.update(fresh)
                metrics.inc_counter("stall.warnings", len(fresh))
                display = sorted({s.split("#", 1)[0] for s in fresh})
                get_logger().warning(
                    "One or more collectives stalled for over %.0fs. "
                    "A peer process may have died or a network link hung. "
                    "Stalled ops: %s",
                    self.warn_seconds, ", ".join(display),
                )
                if self._on_stall is not None:
                    self._on_stall(display)
            if shutdown:
                get_logger().critical(
                    "Stall exceeded shutdown threshold (%.0fs); aborting "
                    "(reference HOROVOD_STALL_SHUTDOWN_TIME_SECONDS semantics).",
                    self.shutdown_seconds,
                )
                os._exit(134)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self.inspector.close()
