"""Online autotuning of the fusion threshold.

Reference: ``ParameterManager`` (``horovod/common/parameter_manager.{h,cc}``)
scores each tuning window by observed bytes/sec and drives a Bayesian
optimizer (``optim/bayesian_optimization.cc``) over knobs like the
fusion threshold and cycle time, then broadcasts the winner.

On TPU the fusion threshold is a trace-time constant, so a "window" is a
compiled step function: the tuner suggests a threshold, the caller
rebuilds/recompiles its step with it, reports the measured score, and
after ``warmup_windows`` the tuner freezes the best value (the reference
also freezes after convergence).  The GP/EI search runs in the native
core (cpp/src/autotune.cc); a hill-climbing fallback covers builds
without the native library.
"""

from __future__ import annotations

import math
from typing import Optional

from . import env
from .logging import get_logger


class FusionAutotuner:
    """Suggest/observe loop for the fusion threshold knob.

    Usage::

        tuner = FusionAutotuner()
        while training:
            thr = tuner.threshold_bytes()
            step = build_step(fusion_threshold_bytes=thr)   # recompiles
            score = run_window(step)                        # bytes/sec
            tuner.observe(score)
    """

    def __init__(
        self,
        low_bytes: int = 1 << 16,
        high_bytes: int = 1 << 28,
        warmup_windows: Optional[int] = None,
        log_path: Optional[str] = None,
    ):
        self.low = math.log2(low_bytes)
        self.high = math.log2(high_bytes)
        if warmup_windows is None:
            # Reference sub-knob (parameter_manager.h:42-105):
            # AUTOTUNE_BAYES_OPT_MAX_SAMPLES caps total GP samples —
            # here the explore budget before freezing.
            warmup_windows = env.get_int(
                "AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 10
            )
        self.warmup_windows = max(1, warmup_windows)
        # Reference AUTOTUNE_WARMUP_SAMPLES: number of leading samples
        # DISCARDED before scoring (its default 3 covers cold caches);
        # ours defaults to 0 because each window already fences out its
        # compile step.
        self._discard_left = max(0, env.get_int("AUTOTUNE_WARMUP_SAMPLES", 0))
        self._windows = 0
        self._frozen: Optional[int] = None
        self._current: Optional[float] = None
        self._log_path = log_path or env.get_env(env.AUTOTUNE_LOG)
        self._native = None
        self._history: list[tuple[float, float]] = []
        from .. import native

        if native.available():
            self._native = native.Autotune(self.low, self.high)

    def threshold_bytes(self) -> int:
        if self._frozen is not None:
            return self._frozen
        if self._native is not None:
            self._current = self._native.suggest()
        else:
            # fallback: coarse grid sweep
            grid = [self.low + (self.high - self.low) * i / max(1, self.warmup_windows - 1)
                    for i in range(self.warmup_windows)]
            self._current = grid[min(self._windows, len(grid) - 1)]
        return int(2 ** self._current)

    def observe(self, score: float) -> None:
        """Report the window score (bytes/sec or images/sec)."""
        if self._frozen is not None or self._current is None:
            return
        if self._discard_left > 0:
            self._discard_left -= 1  # reference warmup sample: dropped
            return
        self._history.append((self._current, score))
        if self._native is not None:
            self._native.observe(self._current, score)
        self._windows += 1
        if self._log_path:
            with open(self._log_path, "a") as fh:
                fh.write(f"{self._windows},{2**self._current:.0f},{score}\n")
        if self._windows >= self.warmup_windows:
            self._freeze()

    def _freeze(self) -> None:
        if self._native is not None:
            best_x, best_score = self._native.best()
        else:
            best_x, best_score = max(self._history, key=lambda p: p[1])
        self._frozen = int(2 ** best_x)
        get_logger().info(
            "autotune converged: fusion threshold %d bytes (score %.3g)",
            self._frozen, best_score,
        )

    def freeze(self, threshold_bytes: int) -> None:
        """Pin the knob to a known-good value without exploration — the
        warm-start entry point for a persisted schedule
        (``sched/store.py``): ``converged`` is True immediately and no
        window is ever burned re-learning it."""
        self._frozen = int(threshold_bytes)

    @property
    def converged(self) -> bool:
        return self._frozen is not None


class AutotuneDriver:
    """Transparent window loop over :class:`FusionAutotuner`.

    The reference tunes *online*: ``ParameterManager::Update`` counts
    reduced bytes per cycle, scores a window, and flips knobs without
    user involvement (``parameter_manager.h:42-105``, ``.cc:118-170``).
    This driver gives ``TrainStep`` the same hands-off behavior: it
    owns the window bookkeeping (steps per window, wall-clock scoring
    with a sync at each boundary, compile-step exclusion) and yields the
    fusion threshold each step should trace with.

    Protocol::

        thr = driver.threshold_bytes()        # before building/running step
        out = step(...)                       # possibly a recompile
        driver.after_step(out)                # scores windows, advances

    Scores are steps/sec over the window excluding its first step (which
    pays the recompile for a new threshold — the reference excludes
    warmup samples the same way).
    """

    def __init__(self, window_steps: Optional[int] = None,
                 quant_eligible: bool = False, **tuner_kwargs):
        import time as _time

        self._time = _time
        self.tuner = FusionAutotuner(**tuner_kwargs)
        self.window_steps = window_steps or env.get_int(
            "AUTOTUNE_WINDOW",
            env.get_int("AUTOTUNE_STEPS_PER_SAMPLE", 16),
        )
        self._steps_in_window = 0
        self._t0: Optional[float] = None
        # Second knob (the reference tunes several parameters jointly,
        # parameter_manager.h:42-105): after the threshold freezes, the
        # hierarchical-allreduce lowering is probed at the winning
        # threshold and kept only if it scores better.  Categorical,
        # numerics-neutral — exactly the class of knob the reference
        # explores.  Skipped when the user pinned the env knob or the
        # world has a single host (the lowering would no-op).
        self._hier_state = "pending"   # pending -> probing -> frozen
        self._hier_value: Optional[bool] = None
        self._hier_scores: list = []
        self._hier_windows = max(1, env.get_int("AUTOTUNE_HIER_WINDOWS", 2))
        self._flat_scores: list = []
        # Third knob: int8 quantized wire on/off, probed at the frozen
        # (threshold, hierarchical) winner.  UNLIKE the first two this
        # changes numerics (lossy wire), so exploration requires the
        # explicit opt-in HVD_TPU_AUTOTUNE_EXPLORE_QUANTIZED=1 *and* a
        # build-side eligibility flag (op/compression/set support —
        # TrainStep passes it; a probe variant whose trace still raises
        # is rejected via reject_quantized()).
        self._quant_state = "pending"  # pending -> probing -> frozen
        self._quant_value: Optional[bool] = None
        self._quant_eligible = bool(quant_eligible) and env.get_bool(
            "AUTOTUNE_EXPLORE_QUANTIZED", False
        )
        self._quant_base: list = []
        self._quant_scores: list = []
        # Joint refinement (the reference explores knobs JOINTLY via one
        # Bayesian surface; sequential freezing can miss interaction
        # effects): after the quantized knob lands and CHANGED the
        # config, the hierarchical knob is re-probed once at the final
        # quantized setting and flipped if the flip scores better.
        self._refine_state = "pending"  # pending->baseline->probing->done
        self._hier_flip: Optional[bool] = None
        self._refine_base: list = []
        self._refine_scores: list = []

    def threshold_bytes(self) -> int:
        return self.tuner.threshold_bytes()

    def hierarchical(self) -> Optional[bool]:
        """Current hierarchical-lowering suggestion for the step build
        (None until the threshold knob has converged)."""
        if self._hier_state == "probing":
            return True
        if self._hier_state == "frozen":
            if self._refine_state == "probing":
                return self._hier_flip
            return self._hier_value
        return None

    def quantized(self) -> Optional[bool]:
        """Current quantized-wire suggestion for the step build (None
        until its turn in the schedule; None when frozen-off so the
        baseline compiled variant is reused, mirroring the hierarchical
        freeze contract)."""
        if self._quant_state == "probing":
            return True
        if self._quant_state == "frozen":
            return self._quant_value
        return None

    def reject_quantized(self) -> None:
        """Called by the step builder when tracing the quantized probe
        variant raises (sparse grads, unsupported op discovered at
        trace time): freeze the knob off and skip refinement."""
        self._quant_state = "frozen"
        self._quant_value = None
        self._quant_eligible = False
        if self._refine_state != "done":
            self._refine_state = "done"
        get_logger().info(
            "autotune: quantized wire rejected by the step build"
        )

    def _hier_explorable(self) -> bool:
        # empty string == unset (get_bool's semantics everywhere else)
        if env.get_env(env.HIERARCHICAL_ALLREDUCE) not in (None, ""):
            return False  # user pinned the knob: honor it
        try:
            from ..runtime import get_runtime

            rt = get_runtime()
            return rt.cross_size > 1 and rt.local_size > 1
        except Exception:
            return False

    def _collapse_static(self) -> None:
        """Freeze knobs whose exploration is statically pointless the
        moment their turn arrives — no window may be burned discovering
        a knob that cannot move (quant without the opt-in/eligibility,
        refinement without a kept quant)."""
        if (self._hier_state == "frozen"
                and self._quant_state == "pending"
                and not self._quant_eligible):
            self._quant_state = "frozen"
            self._quant_value = None
        if (self._quant_state == "frozen"
                and self._refine_state == "pending"
                and (self._quant_value is not True
                     or not self._hier_explorable())):
            self._refine_state = "done"

    def _advance_hier(self, score: float) -> None:
        """Feed a closed window's score to the hierarchical knob state
        machine (runs only after the threshold tuner froze)."""
        try:
            self._advance_hier_inner(score)
        finally:
            self._collapse_static()

    def _advance_hier_inner(self, score: float) -> None:
        if self._hier_state == "pending":
            if not self._hier_explorable():
                self._hier_state = "frozen"
                self._hier_value = None
                return
            # frozen-flat baseline: same window count as the probe so
            # the comparison is noise-symmetric (mean vs mean)
            self._flat_scores.append(score)
            if len(self._flat_scores) >= self._hier_windows:
                self._hier_state = "probing"
            return
        if self._hier_state == "probing":
            self._hier_scores.append(score)
            if len(self._hier_scores) >= self._hier_windows:
                flat = sum(self._flat_scores) / len(self._flat_scores)
                hier = sum(self._hier_scores) / len(self._hier_scores)
                kept = hier > flat
                # A rejected probe freezes to None, NOT False: the flat
                # baseline's compiled variant is keyed on None, and the
                # eviction must keep it rather than force a redundant
                # recompile of an identical program.
                self._hier_value = True if kept else None
                self._hier_state = "frozen"
                get_logger().info(
                    "autotune: hierarchical allreduce %s (flat %.3g vs "
                    "hierarchical %.3g steps/s, %d windows each)",
                    "kept" if kept else "rejected", flat, hier,
                    self._hier_windows,
                )

    def _advance_quant(self, score: float) -> None:
        """Quantized-wire knob state machine (runs after the
        hierarchical knob froze)."""
        try:
            self._advance_quant_inner(score)
        finally:
            self._collapse_static()

    def _advance_quant_inner(self, score: float) -> None:
        if self._quant_state == "pending":
            if not self._quant_eligible:
                self._quant_state = "frozen"
                self._quant_value = None
                return
            self._quant_base.append(score)
            if len(self._quant_base) >= self._hier_windows:
                self._quant_state = "probing"
            return
        if self._quant_state == "probing":
            self._quant_scores.append(score)
            if len(self._quant_scores) >= self._hier_windows:
                base = sum(self._quant_base) / len(self._quant_base)
                quant = sum(self._quant_scores) / len(self._quant_scores)
                kept = quant > base
                self._quant_value = True if kept else None
                self._quant_state = "frozen"
                get_logger().info(
                    "autotune: quantized wire %s (fp %.3g vs int8 %.3g "
                    "steps/s, %d windows each)",
                    "kept" if kept else "rejected", base, quant,
                    self._hier_windows,
                )

    def _advance_refine(self, score: float) -> None:
        """One joint-refinement round-trip: re-probe the hierarchical
        knob at the FINAL quantized setting (sequential freezing probed
        it before the quantized knob existed, which misses interaction
        effects — the reference's joint Bayesian surface would not)."""
        if self._refine_state == "pending":
            # only worth a probe when the quantized knob changed the
            # config and the hierarchical knob is actually explorable
            if self._quant_value is not True or not self._hier_explorable():
                self._refine_state = "done"
                return
            self._hier_flip = None if self._hier_value else True
            self._refine_state = "baseline"
            # fall through: this window already ran the current config
        if self._refine_state == "baseline":
            self._refine_base.append(score)
            if len(self._refine_base) >= self._hier_windows:
                self._refine_state = "probing"
            return
        if self._refine_state == "probing":
            self._refine_scores.append(score)
            if len(self._refine_scores) >= self._hier_windows:
                base = sum(self._refine_base) / len(self._refine_base)
                flip = sum(self._refine_scores) / len(self._refine_scores)
                if flip > base:
                    get_logger().info(
                        "autotune: joint refinement flipped hierarchical "
                        "to %s at the quantized winner (%.3g vs %.3g "
                        "steps/s)", self._hier_flip, flip, base,
                    )
                    self._hier_value = self._hier_flip
                self._refine_state = "done"

    @property
    def converged(self) -> bool:
        return (
            self.tuner.converged
            and self._hier_state == "frozen"
            and self._quant_state == "frozen"
            and self._refine_state == "done"
        )

    @staticmethod
    def _sync(out) -> None:
        """Watchdog-guarded sync: the window fence blocks on a step
        output whose collectives depend on every peer — the most likely
        place to hang on a dead process, so it must be visible to the
        stall inspector (reference ``stall_inspector.h:78``), never a
        bare ``block_until_ready``.
        """
        try:
            from ..runtime import get_runtime

            wd = get_runtime().stall_watchdog
        except Exception:
            wd = None
        if wd is not None:
            wd.wait(out, "TrainStep")
        else:
            import jax

            jax.block_until_ready(out)

    def after_step(self, out) -> None:
        """Advance the window; ``out`` is any step output to sync on."""
        if self.converged:
            return
        self._steps_in_window += 1
        if self._steps_in_window == 1:
            # First step of a window pays tracing+compile for the new
            # threshold; fence it out of the timed region.
            self._sync(out)
            self._t0 = self._time.perf_counter()
            return
        if self._steps_in_window >= self.window_steps:
            self._sync(out)
            dt = self._time.perf_counter() - self._t0
            timed_steps = self._steps_in_window - 1
            score = timed_steps / max(dt, 1e-9)
            self._observe_window(score)
            self._steps_in_window = 0
            self._t0 = None

    def _observe_window(self, score: float) -> None:
        """Feed one closed window's score to the knob schedule:
        threshold -> hierarchical -> quantized -> joint refinement.
        Factored out of :meth:`after_step` so the schedule is testable
        on synthetic score surfaces."""
        threshold = self.tuner.threshold_bytes()
        hier = self.hierarchical()
        quant = self.quantized()
        if not self.tuner.converged:
            self.tuner.observe(score)
            if self.tuner.converged and not self._hier_explorable():
                # static check: don't burn a window discovering it
                self._hier_state = "frozen"
                self._hier_value = None
            self._collapse_static()
        elif self._hier_state != "frozen":
            self._advance_hier(score)
        elif self._quant_state != "frozen":
            self._advance_quant(score)
        elif self._refine_state != "done":
            self._advance_refine(score)
        self._record_window(threshold, score, hier, quant)

    @staticmethod
    def _record_window(threshold: int, score: float,
                       hier: Optional[bool] = None,
                       quant: Optional[bool] = None) -> None:
        """Window records land on the timeline (reference
        ParameterManager's cycle records): one event per closed window
        with the explored threshold, lowering choice, and steps/s
        score — flat-baseline vs hier-probe windows must be tellable
        apart in the trace."""
        try:
            from ..runtime import get_runtime_or_none

            rt = get_runtime_or_none()
            tl = rt.timeline if rt is not None else None
        except Exception:
            tl = None
        if tl is not None:
            lowering = "hier" if hier else "flat"
            wire = "int8" if quant else "fp"
            tl.record_op(
                f"autotune threshold={threshold} lowering={lowering} "
                f"wire={wire} score={score:.2f}steps/s",
                "AUTOTUNE_WINDOW", threshold,
            )
