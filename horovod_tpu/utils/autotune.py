"""Online autotuning of the fusion threshold.

Reference: ``ParameterManager`` (``horovod/common/parameter_manager.{h,cc}``)
scores each tuning window by observed bytes/sec and drives a Bayesian
optimizer (``optim/bayesian_optimization.cc``) over knobs like the
fusion threshold and cycle time, then broadcasts the winner.

On TPU the fusion threshold is a trace-time constant, so a "window" is a
compiled step function: the tuner suggests a threshold, the caller
rebuilds/recompiles its step with it, reports the measured score, and
after ``warmup_windows`` the tuner freezes the best value (the reference
also freezes after convergence).  The GP/EI search runs in the native
core (cpp/src/autotune.cc); a hill-climbing fallback covers builds
without the native library.
"""

from __future__ import annotations

import math
from typing import Optional

from . import env
from .logging import get_logger


class FusionAutotuner:
    """Suggest/observe loop for the fusion threshold knob.

    Usage::

        tuner = FusionAutotuner()
        while training:
            thr = tuner.threshold_bytes()
            step = build_step(fusion_threshold_bytes=thr)   # recompiles
            score = run_window(step)                        # bytes/sec
            tuner.observe(score)
    """

    def __init__(
        self,
        low_bytes: int = 1 << 16,
        high_bytes: int = 1 << 28,
        warmup_windows: int = 10,
        log_path: Optional[str] = None,
    ):
        self.low = math.log2(low_bytes)
        self.high = math.log2(high_bytes)
        self.warmup_windows = warmup_windows
        self._windows = 0
        self._frozen: Optional[int] = None
        self._current: Optional[float] = None
        self._log_path = log_path or env.get_env(env.AUTOTUNE_LOG)
        self._native = None
        self._history: list[tuple[float, float]] = []
        from .. import native

        if native.available():
            self._native = native.Autotune(self.low, self.high)

    def threshold_bytes(self) -> int:
        if self._frozen is not None:
            return self._frozen
        if self._native is not None:
            self._current = self._native.suggest()
        else:
            # fallback: coarse grid sweep
            grid = [self.low + (self.high - self.low) * i / max(1, self.warmup_windows - 1)
                    for i in range(self.warmup_windows)]
            self._current = grid[min(self._windows, len(grid) - 1)]
        return int(2 ** self._current)

    def observe(self, score: float) -> None:
        """Report the window score (bytes/sec or images/sec)."""
        if self._frozen is not None or self._current is None:
            return
        self._history.append((self._current, score))
        if self._native is not None:
            self._native.observe(self._current, score)
        self._windows += 1
        if self._log_path:
            with open(self._log_path, "a") as fh:
                fh.write(f"{self._windows},{2**self._current:.0f},{score}\n")
        if self._windows >= self.warmup_windows:
            self._freeze()

    def _freeze(self) -> None:
        if self._native is not None:
            best_x, best_score = self._native.best()
        else:
            best_x, best_score = max(self._history, key=lambda p: p[1])
        self._frozen = int(2 ** best_x)
        get_logger().info(
            "autotune converged: fusion threshold %d bytes (score %.3g)",
            self._frozen, best_score,
        )

    @property
    def converged(self) -> bool:
        return self._frozen is not None
