"""Shared benchmark harness: the canonical DP training step.

One implementation of the fwd+bwd+allreduce+update setup used by the
root ``bench.py``, ``examples/synthetic_benchmark.py``, and
``tools/scaling_bench.py`` — the reference's tf_cnn_benchmarks-style
methodology (``docs/benchmarks.rst:67-80``) — so the step protocol
lives in one place.
"""

from __future__ import annotations

from typing import Optional, Tuple


def build_dp_step(hvd, model, image_size: int, *,
                  compression=None,
                  lr: float = 0.01,
                  momentum: Optional[float] = 0.9) -> Tuple:
    """Build the data-parallel training step for an image model.

    Returns ``(step, params, batch_stats, opt_state)``; ``batch_stats``
    is None for models without BatchNorm (e.g. VGG) and the step then
    takes/returns no stats.  Initial parameters are broadcast from
    rank 0 like every reference benchmark script.
    """
    import jax
    import jax.numpy as jnp
    import optax

    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, image_size, image_size, 3)), train=True,
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats")
    params = hvd.broadcast_parameters(params, root_rank=0)

    tx = hvd.DistributedOptimizer(
        optax.sgd(lr, momentum=momentum),
        compression=compression if compression is not None
        else hvd.Compression.none,
    )

    if batch_stats is not None:
        def loss_fn(p, stats, batch):
            x, y = batch
            logits, updated = model.apply(
                {"params": p, "batch_stats": stats}, x, train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            return loss, updated["batch_stats"]

        step = hvd.distributed_train_step(loss_fn, tx, stateful=True)
    else:
        def loss_fn(p, batch):
            x, y = batch
            logits = model.apply({"params": p}, x, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)
    return step, params, batch_stats, opt_state


def timed_throughput(step, params, batch_stats, opt_state, batch,
                     iters: int, warmup: int = 3) -> Tuple[float, Tuple]:
    """Run ``warmup`` + ``iters`` steps; return (seconds, final state).

    A scalar host transfer fences each phase: ``block_until_ready`` is
    not a reliable fence on every PJRT transport (observed on the axon
    relay), but a device->host read is.
    """
    import time

    def one():
        nonlocal params, batch_stats, opt_state
        if batch_stats is not None:
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, batch
            )
        else:
            params, opt_state, loss = step(params, opt_state, batch)
        return loss

    loss = None
    for _ in range(warmup):
        loss = one()
    if loss is not None:
        float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = one()
    float(loss)
    return time.perf_counter() - t0, (params, batch_stats, opt_state)
