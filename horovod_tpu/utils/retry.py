"""Reusable retry policy: bounded attempts, exponential backoff with
deterministic jitter, optional per-attempt timeout.

The reference scatters ad-hoc retry loops through its runner (ssh
probes, rendezvous polls, discovery hiccups swallowed by the driver
loop).  Centralizing the policy buys three things the fault-tolerance
path needs: (1) every retry is counted in :mod:`horovod_tpu.metrics`
(``retry.<name>.attempts`` / ``.retries`` / ``.exhausted``) so flaky
infrastructure is visible, not silent; (2) jitter is drawn from a
seedable RNG so tests assert exact backoff sequences; (3) a per-attempt
timeout turns a *hung* call (the failure mode heartbeats exist for)
into a retryable error instead of a wedged driver.

Used by ``elastic/discovery.py`` (flaky discovery scripts),
``runner/elastic_driver.py`` (worker spawn), and
``runner/elastic_worker.py`` (rendezvous KV connect).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Tuple, Type

from ..exceptions import RetryTimeoutError


def _run_with_timeout(fn: Callable, args, kwargs, timeout_s: float):
    """Run ``fn`` in a daemon thread with a deadline.  On timeout the
    thread is abandoned (Python offers no safe kill) and
    :class:`RetryTimeoutError` is raised — callers pick attempt timeouts
    long enough that an abandoned attempt is rare and harmless
    (subprocess-backed work is additionally bounded by its own timeout).
    """
    result: list = []
    error: list = []

    def runner():
        try:
            result.append(fn(*args, **kwargs))
        except BaseException as e:  # delivered to the waiting caller
            error.append(e)

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise RetryTimeoutError(
            f"attempt exceeded per-attempt timeout of {timeout_s}s"
        )
    if error:
        raise error[0]
    return result[0]


@dataclass
class RetryPolicy:
    """``call(fn, ...)`` runs ``fn`` up to ``max_attempts`` times.

    Delay before retry K (1-based) is
    ``min(base_delay_s * multiplier**(K-1), max_delay_s)`` scaled by a
    jitter factor uniform in ``[1 - jitter, 1 + jitter]`` from the
    seeded RNG.  ``retry_on`` bounds which exceptions are retryable
    (others propagate immediately); :class:`RetryTimeoutError` from
    ``attempt_timeout_s`` is always retryable.  After the last attempt
    the final exception propagates unchanged.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.1
    multiplier: float = 2.0
    max_delay_s: float = 5.0
    jitter: float = 0.1
    attempt_timeout_s: Optional[float] = None
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None
    name: str = "retry"
    seed: Optional[int] = None
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self._rng = random.Random(self.seed)

    def delay_s(self, retry_index: int) -> float:
        """Backoff before the ``retry_index``-th retry (1-based), jitter
        included.  Consumes one RNG draw — with a fixed ``seed`` the
        sequence of delays is reproducible."""
        base = min(
            self.base_delay_s * (self.multiplier ** (retry_index - 1)),
            self.max_delay_s,
        )
        if self.jitter <= 0:
            return base
        return base * self._rng.uniform(1.0 - self.jitter, 1.0 + self.jitter)

    def call(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        from .. import metrics

        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            metrics.inc_counter(f"retry.{self.name}.attempts")
            t0 = time.perf_counter()
            try:
                if self.attempt_timeout_s is not None:
                    result = _run_with_timeout(
                        fn, args, kwargs, self.attempt_timeout_s
                    )
                else:
                    result = fn(*args, **kwargs)
                metrics.observe(
                    f"retry.{self.name}.attempt_seconds",
                    time.perf_counter() - t0,
                )
                return result
            except self.retry_on + (RetryTimeoutError,) as e:
                metrics.observe(
                    f"retry.{self.name}.attempt_seconds",
                    time.perf_counter() - t0,
                )
                last = e
                if attempt == self.max_attempts:
                    break
                delay = self.delay_s(attempt)
                metrics.inc_counter(f"retry.{self.name}.retries")
                if self.on_retry is not None:
                    self.on_retry(attempt, e, delay)
                from .logging import get_logger

                get_logger().warning(
                    "%s: attempt %d/%d failed (%s); retrying in %.2fs",
                    self.name, attempt, self.max_attempts, e, delay,
                )
                if delay > 0:
                    self.sleep(delay)
        metrics.inc_counter(f"retry.{self.name}.exhausted")
        assert last is not None
        raise last

    def wrap(self, fn: Callable) -> Callable:
        """Decorator form of :meth:`call`."""
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        return wrapped
