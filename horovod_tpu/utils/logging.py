"""Rank-aware logging (analog of reference ``common/logging.{h,cc}``).

Level comes from ``HVD_TPU_LOG_LEVEL`` / ``HOROVOD_LOG_LEVEL``
(trace/debug/info/warning/error/fatal); messages are prefixed with the
process rank once the runtime is initialized.
"""

from __future__ import annotations

import logging
import sys

from . import env

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_logger: logging.Logger | None = None


class _RankFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        from .. import runtime

        rt = runtime.get_runtime_or_none()
        record.hvd_rank = rt.process_rank if rt is not None else "-"
        return True


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("horovod_tpu")
        level_name = (env.get_env(env.LOG_LEVEL) or "warning").lower()
        logger.setLevel(_LEVELS.get(level_name, logging.WARNING))
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("[%(asctime)s %(hvd_rank)s %(levelname)s] %(message)s")
        )
        handler.addFilter(_RankFilter())
        logger.addHandler(handler)
        logger.propagate = False
        _logger = logger
    return _logger
