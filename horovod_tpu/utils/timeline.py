"""Chrome-tracing timeline (reference ``horovod/common/timeline.{h,cc}``).

The reference feeds a lock-free SPSC queue drained by a dedicated writer
thread producing chrome://tracing JSON with per-tensor NEGOTIATE/QUEUE/op
phases.  On TPU there is no negotiation phase; we record the eager
dispatch lifecycle (ENQUEUE -> compiled-op) per named collective, with
the same JSON format so the file opens in chrome://tracing / Perfetto.
Deep device-level profiling is delegated to ``jax.profiler`` (the
``start_profile``/``stop_profile`` helpers), the TPU-native analog of the
reference's NVTX ranges (``nvtx_op_range.h``).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional


class Timeline:
    """Background-thread JSON writer, mirroring ``TimelineWriter``."""

    def __init__(self, path: str):
        self.path = path
        self._queue: "queue.Queue" = queue.Queue(maxsize=1_000_000)
        self._start = time.perf_counter()
        self._closed = threading.Event()
        self._fh = open(path, "w")
        self._fh.write("[\n")
        self._first = True
        self._thread = threading.Thread(
            target=self._drain, name="hvd_tpu_timeline", daemon=True
        )
        self._thread.start()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._start) * 1e6

    def record_op(self, name: str, activity: str, nbytes: int) -> None:
        """One complete event per collective dispatch."""
        self._put(
            {
                "name": name,
                "cat": activity,
                "ph": "X",
                "ts": self._now_us(),
                "dur": 1,
                "pid": os.getpid(),
                "tid": 0,
                "args": {"bytes": int(nbytes), "activity": activity},
            }
        )

    def begin(self, name: str, activity: str) -> None:
        self._put(
            {"name": name, "cat": activity, "ph": "B", "ts": self._now_us(),
             "pid": os.getpid(), "tid": 0}
        )

    def end(self, name: str, activity: str) -> None:
        self._put(
            {"name": name, "cat": activity, "ph": "E", "ts": self._now_us(),
             "pid": os.getpid(), "tid": 0}
        )

    def mark_cycle(self) -> None:
        """Reference ``HOROVOD_TIMELINE_MARK_CYCLES`` instant events."""
        self._put(
            {"name": "CYCLE", "ph": "i", "ts": self._now_us(), "s": "g",
             "pid": os.getpid(), "tid": 0}
        )

    def _put(self, event: dict) -> None:
        if self._closed.is_set():
            return
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            pass  # drop like the reference's bounded lockfree queue

    def _drain(self) -> None:
        # The writer thread owns the file handle end to end: it drains the
        # backlog after close() signals, writes the epilogue, and closes —
        # so no event can land after the closing bracket.
        while not (self._closed.is_set() and self._queue.empty()):
            try:
                ev = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if not self._first:
                self._fh.write(",\n")
            self._first = False
            self._fh.write(json.dumps(ev))
        self._fh.write("\n]\n")
        self._fh.close()

    def close(self) -> None:
        self._closed.set()
        self._thread.join()


def start_timeline(path: str) -> None:
    """Attach a timeline writer to the running runtime (reference
    ``horovod_start_timeline``, ``operations.cc:1011`` — runtime
    activation without the env var).  Replaces any active timeline."""
    from .. import native
    from ..runtime import get_runtime

    rt = get_runtime()
    if rt.timeline is not None:
        rt.timeline.close()
    if native.available():
        rt.timeline = native.NativeTimeline(path)
    else:
        rt.timeline = Timeline(path)


def stop_timeline() -> None:
    """Flush and detach the active timeline (reference
    ``horovod_stop_timeline``)."""
    from ..runtime import get_runtime

    rt = get_runtime()
    if rt.timeline is not None:
        rt.timeline.close()
        rt.timeline = None


# jax.profiler passthroughs (NVTX-range analog).
_profiler_active = False


def start_profile(logdir: str) -> None:
    global _profiler_active
    import jax

    jax.profiler.start_trace(logdir)
    _profiler_active = True


def stop_profile() -> None:
    global _profiler_active
    import jax

    if _profiler_active:
        jax.profiler.stop_trace()
        _profiler_active = False
