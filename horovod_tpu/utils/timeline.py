"""Chrome-tracing timeline (reference ``horovod/common/timeline.{h,cc}``).

The reference feeds a lock-free SPSC queue drained by a dedicated writer
thread producing chrome://tracing JSON with per-tensor NEGOTIATE/QUEUE/op
phases.  On TPU there is no negotiation phase; we record the eager
dispatch lifecycle (ENQUEUE -> compiled-op) per named collective, with
the same JSON format so the file opens in chrome://tracing / Perfetto.
Deep device-level profiling is delegated to ``jax.profiler`` (the
``start_profile``/``stop_profile`` helpers), the TPU-native analog of the
reference's NVTX ranges (``nvtx_op_range.h``).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional


def _resolve_rank() -> int:
    """Best-effort rank for the process-metadata lane: the runtime's
    when initialized, the launcher env otherwise (timelines can start
    before ``hvd.init()``)."""
    try:
        from ..runtime import get_runtime_or_none

        rt = get_runtime_or_none()
        if rt is not None:
            return rt.rank
    except Exception:
        pass
    return int(os.environ.get("HVD_TPU_CROSS_RANK", "0") or 0)


class Timeline:
    """Background-thread JSON writer, mirroring ``TimelineWriter``.

    Mergeable across ranks: the first events are Chrome-trace metadata
    (process/thread names, sort index) plus one ``HVD_PROC_META``
    instant carrying this process's **wall-clock epoch base** in
    microseconds — ``ts`` values stay relative (cheap perf_counter
    deltas on the hot path) and ``tools/merge_timeline.py`` re-bases N
    per-rank traces onto the shared wall clock using that epoch.
    """

    def __init__(self, path: str, rank: Optional[int] = None,
                 queue_size: int = 1_000_000):
        self.path = path
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        # Two clocks sampled back to back: perf_counter anchors relative
        # ts, time.time() anchors the merge across processes.
        self._start = time.perf_counter()
        self._epoch_wall_us = time.time() * 1e6
        self.rank = _resolve_rank() if rank is None else int(rank)
        self._drop_logged = False
        self._closed = threading.Event()
        # Line-buffered: a worker killed mid-round (crash, driver
        # terminate) leaves every completed event on disk, so the trace
        # is salvageable for the postmortem merge.
        self._fh = open(path, "w", buffering=1)
        self._fh.write("[\n")
        self._first = True
        self._thread = threading.Thread(
            target=self._drain, name="hvd_tpu_timeline", daemon=True
        )
        self._thread.start()
        self._emit_process_metadata()

    def _emit_process_metadata(self) -> None:
        import socket

        pid = os.getpid()
        hostname = socket.gethostname()
        self._put({"name": "process_name", "ph": "M", "pid": pid,
                   "args": {"name": f"rank {self.rank} ({hostname})"}})
        self._put({"name": "process_sort_index", "ph": "M", "pid": pid,
                   "args": {"sort_index": self.rank}})
        for tid, lane in ((0, "dispatch"), (1, "measured")):
            self._put({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": lane}})
        self._put({
            "name": "HVD_PROC_META", "ph": "i", "ts": 0.0, "s": "p",
            "pid": pid, "tid": 0,
            "args": {
                "rank": self.rank, "hostname": hostname, "pid": pid,
                "epoch_wall_us": self._epoch_wall_us,
            },
        })

    def _now_us(self) -> float:
        return (time.perf_counter() - self._start) * 1e6

    def record_op(self, name: str, activity: str, nbytes: int) -> None:
        """One complete event per collective dispatch."""
        self._put(
            {
                "name": name,
                "cat": activity,
                "ph": "X",
                "ts": self._now_us(),
                "dur": 1,
                "pid": os.getpid(),
                "tid": 0,
                "args": {"bytes": int(nbytes), "activity": activity},
            }
        )

    def begin(self, name: str, activity: str) -> None:
        self._put(
            {"name": name, "cat": activity, "ph": "B", "ts": self._now_us(),
             "pid": os.getpid(), "tid": 0}
        )

    def end(self, name: str, activity: str) -> None:
        self._put(
            {"name": name, "cat": activity, "ph": "E", "ts": self._now_us(),
             "pid": os.getpid(), "tid": 0}
        )

    def record_span(self, name: str, activity: str, ts_us: float,
                    dur_us: float, args: Optional[dict] = None) -> None:
        """A MEASURED duration event (reference per-tensor activity
        begin/end records, ``common/timeline.cc``): unlike
        ``record_op``'s dispatch ticks, ``ts``/``dur`` here are real
        device-execution times (profiler-extracted)."""
        self._put(
            {
                "name": name,
                "cat": activity,
                "ph": "X",
                "ts": float(ts_us),
                "dur": max(float(dur_us), 0.001),
                "pid": os.getpid(),
                "tid": 1,  # measured lane, separate from dispatch lane 0
                "args": {"activity": activity, **(args or {})},
            }
        )

    def mark_cycle(self) -> None:
        """Reference ``HOROVOD_TIMELINE_MARK_CYCLES`` instant events."""
        self._put(
            {"name": "CYCLE", "ph": "i", "ts": self._now_us(), "s": "g",
             "pid": os.getpid(), "tid": 0}
        )

    def _put(self, event: dict) -> None:
        if self._closed.is_set():
            return
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            # Drop like the reference's bounded lockfree queue — but
            # visibly: a truncated trace must be diagnosable.
            from .. import metrics

            metrics.inc_counter("timeline.dropped_events")
            if not self._drop_logged:
                self._drop_logged = True
                from .logging import get_logger

                get_logger().warning(
                    "timeline writer backlog full; dropping events "
                    "(see the timeline.dropped_events counter for the "
                    "total — the trace at %s is incomplete)", self.path,
                )

    def _drain(self) -> None:
        # The writer thread owns the file handle end to end: it drains the
        # backlog after close() signals, writes the epilogue, and closes —
        # so no event can land after the closing bracket.
        while not (self._closed.is_set() and self._queue.empty()):
            try:
                ev = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if not self._first:
                self._fh.write(",\n")
            self._first = False
            self._fh.write(json.dumps(ev))
        self._fh.write("\n]\n")
        self._fh.close()

    def close(self) -> None:
        self._closed.set()
        self._thread.join()


def start_timeline(path: str) -> None:
    """Attach a timeline writer to the running runtime (reference
    ``horovod_start_timeline``, ``operations.cc:1011`` — runtime
    activation without the env var).  Replaces any active timeline."""
    from .. import native
    from ..runtime import get_runtime

    rt = get_runtime()
    if rt.timeline is not None:
        rt.timeline.close()
    if native.available():
        rt.timeline = native.NativeTimeline(path)
    else:
        rt.timeline = Timeline(path)


def stop_timeline() -> None:
    """Flush and detach the active timeline (reference
    ``horovod_stop_timeline``)."""
    from ..runtime import get_runtime

    rt = get_runtime()
    if rt.timeline is not None:
        rt.timeline.close()
        rt.timeline = None


# ---- cross-rank merge (tools/merge_timeline.py CLI) ----------------------


def _load_trace_events(path: str, status: Optional[dict] = None) -> list:
    """Read one trace file: a bare JSON array (this writer's and the
    trace exporter's format), a ``{"traceEvents": [...]}`` object
    (Chrome's), or a flight-recorder dump (``{"steps": [...]}`` —
    rendered to events via ``trace/export.py``).

    A trace whose writer died mid-job (worker crash, driver terminate)
    has no closing bracket; the Chrome trace format itself permits that
    for exactly this reason, so fall back to salvaging the complete
    events line by line (this writer emits one event per line).

    ``status`` (a dict, mutated in place) reports how the file parsed:
    ``ok`` | ``salvaged`` (line-by-line recovery) | ``empty`` (parsed
    but no events) | ``error`` (unreadable / zero events recovered) —
    the per-file parse report ``tools/merge_timeline.py`` prints
    instead of silently dropping a rank."""
    status = status if status is not None else {}
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError as e:
        status.update(status="error", detail=str(e), events=0)
        return []
    try:
        data = json.loads(text)
    except json.JSONDecodeError as e:
        events = []
        for line in text.splitlines():
            line = line.strip().rstrip(",").strip()
            if line in ("[", "]", ""):
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # the torn tail of the last write
        if events:
            status.update(status="salvaged", detail=str(e),
                          events=len(events))
        else:
            status.update(status="error",
                          detail=f"no events salvageable: {e}", events=0)
        return events
    if isinstance(data, dict):
        if "traceEvents" in data:
            events = list(data["traceEvents"])
        elif "steps" in data or "background" in data:
            # A flight-recorder dump: render its span trees as events
            # so an anomaly dump merges into the postmortem view.
            from ..trace.export import dump_to_events

            events = dump_to_events(data)
        else:
            events = []
    else:
        events = list(data)
    status.update(
        status="ok" if events else "empty",
        detail="", events=len(events),
    )
    return events


# Categories that get their own named lane in the merged view: the
# scheduler's per-bucket dispatch lane, the async service's submission
# lane, the hierarchical phase lane, and every per-workload
# <KIND>_EXCHANGE lane the XIR interpreter emits.  TRACE_* categories
# (the trace exporter) already carry their own thread_name metadata.
_LANE_CATS = ("SCHED_EXCHANGE", "SVC_EXCHANGE", "TOPO_PHASE")


def _lane_cat(cat: Optional[str]) -> Optional[str]:
    if not cat:
        return None
    if cat in _LANE_CATS or cat.endswith("_EXCHANGE"):
        return cat
    return None


def merge_timeline_files(paths, report: Optional[list] = None) -> dict:
    """Align N per-rank traces into one Chrome trace with per-rank
    lanes.

    Each file's ``HVD_PROC_META`` event supplies its rank and
    wall-clock epoch base; every ``ts`` is re-based to the earliest
    epoch across files so concurrent collectives line up even when the
    per-process ``perf_counter`` zeros (and wall clocks) are skewed.
    Lanes: ``pid`` is rewritten to the rank (with matching
    ``process_sort_index``), so Perfetto orders lanes rank 0..N-1
    top-down; events in the known activity lanes (SCHED_EXCHANGE /
    SVC_EXCHANGE / TOPO_PHASE / <KIND>_EXCHANGE) get a named thread
    lane per rank instead of piling onto the dispatch thread.  Files
    without metadata (pre-merge traces) fall back to their position in
    ``paths`` with a zero epoch, and merge with a warning rather than
    failing the whole postmortem.

    ``report`` (a list, appended in ``paths`` order) collects one
    per-file parse record: ``{"path", "status", "events", "rank",
    "detail"}`` with status ``ok``/``salvaged``/``empty``/``error`` —
    the CLI's per-file report, so an unparseable rank is named, not
    silently dropped.
    """
    from .logging import get_logger

    loaded = []  # (rank, epoch_wall_us, events, source_index)
    for i, path in enumerate(paths):
        status: dict = {}
        events = _load_trace_events(path, status)
        meta = next(
            (e for e in events if e.get("name") == "HVD_PROC_META"), None
        )
        if meta is not None:
            args = meta["args"]
        else:
            # Native-core traces (and the trace exporter's sidecar-less
            # crashed writers) carry the merge metadata in a JSON
            # sidecar (the C writer's event ABI has no args payload).
            args = None
            try:
                with open(path + ".hvdmeta.json") as fh:
                    args = json.load(fh)
            except (OSError, ValueError):
                pass
        if args is None:
            if events:
                get_logger().warning(
                    "%s has no HVD_PROC_META event or .hvdmeta.json "
                    "sidecar; assuming rank %d with epoch 0 (timestamps "
                    "will not align across files)", path, i,
                )
                if status.get("status") == "ok":
                    status["status"] = "no_meta"
            rank, epoch = i, 0.0
        else:
            rank = int(args.get("rank", i))
            epoch = float(args.get("epoch_wall_us", 0.0))
        if report is not None:
            report.append({
                "path": path, "rank": rank,
                "status": status.get("status", "error"),
                "events": status.get("events", len(events)),
                "detail": status.get("detail", ""),
            })
        loaded.append((rank, epoch, events, i))

    base = min((epoch for _, epoch, _, _ in loaded), default=0.0)
    merged: list = []
    lane_tids: dict = {}  # (rank, cat) -> tid
    files_per_rank: dict = {}  # rank -> files merged so far
    for rank, epoch, events, _src in sorted(
            loaded, key=lambda t: (t[0], t[3])):
        # Multiple files may legitimately share a rank (a timeline AND
        # a trace export): offset the later files' thread ids so their
        # lanes coexist instead of interleaving on tid 0.
        tid_off = 100 * files_per_rank.get(rank, 0)
        files_per_rank[rank] = files_per_rank.get(rank, 0) + 1
        offset = epoch - base
        for e in events:
            e = dict(e)
            e["pid"] = rank
            if tid_off and "tid" in e:
                e["tid"] = int(e.get("tid", 0)) + tid_off
            if e.get("ph") == "M":
                if e.get("name") == "process_sort_index":
                    e["args"] = {"sort_index": rank}
            elif "ts" in e:
                e["ts"] = float(e["ts"]) + offset
            cat = _lane_cat(e.get("cat"))
            if cat is not None and e.get("ph") != "M":
                key = (rank, cat)
                tid = lane_tids.get(key)
                if tid is None:
                    tid = 10 + len([k for k in lane_tids if k[0] == rank])
                    lane_tids[key] = tid
                    merged.append({
                        "name": "thread_name", "ph": "M", "pid": rank,
                        "tid": tid, "args": {"name": cat},
                    })
                e["tid"] = tid
            merged.append(e)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


# ---- measured per-bucket durations (reference timeline.cc activity
# records, activities common.h:73-105) ------------------------------------

_BUCKET_RE = None


def _bucket_re():
    global _BUCKET_RE
    if _BUCKET_RE is None:
        import re

        # both engines' scopes: hvd_bucket* (legacy HVD_TPU_SCHED=off)
        # and hvd_sched_bucket* (the bucketed overlap scheduler)
        _BUCKET_RE = re.compile(r"hvd_(?:sched_)?bucket(\d+)_(\d+)B")
    return _BUCKET_RE


def extract_bucket_spans(logdir: str, hlo_text: Optional[str] = None):
    """Extract ``hvd_bucket*`` execution spans from a ``jax.profiler``
    trace directory.

    Two join paths cover both backends: TPU traces carry the scoped op
    name directly in the event name/args; CPU traces carry only the HLO
    instruction name (``args.hlo_op``), which joins through the
    compiled module's ``op_name`` metadata (``hlo_text``).  Returns a
    list of ``(bucket_label, ts_us, dur_us)``.
    """
    import glob
    import gzip
    import json as _json

    op_to_bucket = {}
    if hlo_text:
        import re

        for m in re.finditer(
            r"(\S+)\s*=\s*[^\n]*op_name=\"([^\"]*hvd_(?:sched_)?bucket"
            r"(\d+)_(\d+)B[^\"]*)\"",
            hlo_text,
        ):
            op_to_bucket[m.group(1).lstrip("%")] = (
                f"bucket{m.group(3)}[{m.group(4)}B]"
            )
    spans = []
    pattern = os.path.join(logdir, "**", "*.trace.json.gz")
    for fp in glob.glob(pattern, recursive=True):
        with gzip.open(fp) as fh:
            events = _json.loads(fh.read()).get("traceEvents", [])
        for e in events:
            if e.get("ph") != "X":
                continue
            dur = float(e.get("dur", 0) or 0)
            if dur <= 0:
                continue
            args = e.get("args") or {}
            hay = f"{e.get('name', '')} {args.get('long_name', '')}"
            m = _bucket_re().search(hay)
            if m:
                label = f"bucket{m.group(1)}[{m.group(2)}B]"
            else:
                label = op_to_bucket.get(str(args.get("hlo_op", "")))
            if label is not None:
                spans.append((label, float(e.get("ts", 0) or 0), dur))
    return spans


def profile_bucket_step(fn, *args, logdir: Optional[str] = None, **kwargs):
    """Run ``fn(*args)`` ONCE under the device profiler and extract the
    MEASURED per-bucket execution durations (reference: the timeline's
    per-tensor activity begin/end records let a user see which fusion
    bucket is slow; here the ``hvd_bucket*`` named scopes planted by
    ``DistributedOptimizer`` are joined against the profiler trace).

    Emits one ``BUCKET_EXEC`` duration event per bucket into the active
    timeline (measured lane, real ``ts``/``dur``) and returns
    ``({bucket_label: total_duration_us}, step_output)``.  The step
    output MUST replace the caller's inputs: compiled train steps
    donate (params, state, opt_state) buffers, so the arguments passed
    in are consumed by the profiled step exactly as by a normal step.
    One profiler session is paid for the single diagnostic step — the
    hot path stays uninstrumented — and the HLO-metadata join (needed
    only on backends whose traces lack scoped op names, e.g. CPU) is
    built lazily so no second compile is paid where the name join
    succeeds.
    """
    import shutil
    import tempfile

    import jax

    created = None
    if logdir is None:
        logdir = created = tempfile.mkdtemp(prefix="hvd_bucket_prof_")
    try:
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        with jax.profiler.trace(logdir):
            out = jitted(*args, **kwargs)
            jax.block_until_ready(out)
        spans = extract_bucket_spans(logdir, None)
        if not spans:
            # Trace lacks scoped names (CPU backend): join through the
            # compiled module's op_name metadata instead.  Only this
            # fallback pays the AOT lower/compile for the text; TPU
            # traces carry scoped names and never reach here.
            try:
                hlo_text = (
                    jitted.lower(*args, **kwargs).compile().as_text()
                )
            except Exception:
                hlo_text = None
            if hlo_text:
                spans = extract_bucket_spans(logdir, hlo_text)
        totals: dict = {}
        starts: dict = {}
        for label, ts, dur in spans:
            totals[label] = totals.get(label, 0.0) + dur
            starts[label] = min(starts.get(label, ts), ts)
        from ..runtime import get_runtime_or_none

        rt = get_runtime_or_none()
        tl = rt.timeline if rt is not None else None
        if tl is not None and hasattr(tl, "record_span"):
            for label in sorted(totals):
                tl.record_span(
                    label, "BUCKET_EXEC", starts[label], totals[label],
                    args={"measured": True},
                )
        return totals, out
    finally:
        if created is not None:
            shutil.rmtree(created, ignore_errors=True)


# jax.profiler passthroughs (NVTX-range analog).
_profiler_active = False


def start_profile(logdir: str) -> None:
    global _profiler_active
    import jax

    jax.profiler.start_trace(logdir)
    _profiler_active = True


def stop_profile() -> None:
    global _profiler_active
    import jax

    if _profiler_active:
        jax.profiler.stop_trace()
        _profiler_active = False
