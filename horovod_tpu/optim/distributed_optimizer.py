"""DistributedOptimizer: gradient reduction fused into an optax transform.

TPU-native re-design of the reference's per-framework optimizers
(``horovod/torch/optimizer.py:506`` ``DistributedOptimizer``,
``horovod/tensorflow/__init__.py:627`` + ``DistributedGradientTape``
``:759``).  The reference hooks each parameter's grad-accumulator,
fires async allreduces as gradients become ready, and blocks in
``optimizer.step()``.  Under XLA the whole training step is one compiled
program, so "overlap" is the compiler's latency-hiding job; what this
wrapper keeps from the reference is the *semantics and knobs*:

  * op: Average / Sum / Adasum              (optimizer.py:72, :335)
  * compression (fp16/bf16 wire)            (torch/compression.py)
  * backward_passes_per_step local gradient
    aggregation                              (optimizer.py:72,
                                             tensorflow/gradient_aggregation.py)
  * gradient_predivide_factor split into
    pre/postscale                            (optimizer.py:194-205)
  * tensor fusion bucketing                  (fusion_buffer_manager +
                                             FuseResponses)
  * process sets                             (optimizer.py process_set arg)

The returned ``optax.GradientTransformation``'s ``update`` must run in an
SPMD context (inside ``shard_map`` over the world axis) — use
``distributed_train_step`` to build the full jitted step, or embed the
transform in your own shard_map.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..compression import Compression, Compressor
from ..exceptions import QuantizedWireError
from ..ops import fusion, traced
from ..ops.traced import Adasum, Average, Sum
from ..process_sets import ProcessSet
from ..runtime import WORLD_AXIS, get_runtime


# Trace-time override forcing the quantized wire ON for the autotune
# probe variant (the fusion-threshold / hierarchical override pattern).
_quantized_override: Optional[bool] = None


def set_quantized_override(value: Optional[bool]) -> None:
    global _quantized_override
    _quantized_override = value


class DistributedOptimizerState(NamedTuple):
    """State wrapper; ``acc`` holds per-rank gradient accumulators (local
    values, varying over the world axis) and is None when
    backward_passes_per_step == 1.  ``residual`` carries the
    error-feedback residuals of the quantized wire (per-rank local, one
    fp32 leaf per parameter; None unless a quantized wire with EF was
    active at init — see docs/quantization.md)."""

    counter: jax.Array
    acc: Any
    inner: Any
    residual: Any = None


def remesh_optimizer_state(
    state: "DistributedOptimizerState", *, joined: bool = False
) -> "DistributedOptimizerState":
    """Carry a :class:`DistributedOptimizerState` across an in-process
    remesh (``elastic/remesh.py``).

    Every leaf is either replicated (``inner``, ``counter``) or
    param-shaped and rank-local (``acc`` gradient accumulators, EF
    ``residual``) — unlike ZeRO-1 bucket shards, nothing here needs a
    shard exchange; the state is valid under any world size.  A JOINER
    (``joined=True``) zeroes the rank-local leaves: it has no local
    accumulation/quantization history, and zeros are the documented
    safe cold-start for both (a partial accumulation window restarts;
    EF degrades to plain quantization until feedback refills).
    """
    if not joined:
        return state
    zero = lambda t: None if t is None else jax.tree.map(
        jnp.zeros_like, t
    )
    return state._replace(
        acc=zero(state.acc), residual=zero(state.residual)
    )


def _adasum_hier_eligible(axis, process_set) -> bool:
    """Whether ``op=Adasum`` can take the hierarchical ``hier_adasum``
    lowering: one named present axis that factors across slices, and
    the global set — plain sum over ICI, adaptive summation only on
    the DCN hop (docs/adasum.md).  Single-slice topologies, process
    subsets, and multi-axis reductions stay on the flat VHDD tree."""
    from ..parallel.tensor import _axis_present
    from ..topo import model as topo_model

    if not (isinstance(axis, str) and _axis_present(axis)):
        return False
    if process_set is not None and process_set.process_set_id != 0:
        return False
    topo = topo_model.current()
    if not topo.multi_slice:
        return False
    return topo.factor_axis(jax.lax.axis_size(axis))[0] > 1


def _reduce_gradients(
    grads: Any,
    *,
    axis,
    op: int,
    compression: type[Compressor],
    prescale_factor: float,
    postscale_factor: float,
    process_set: Optional[ProcessSet],
    fusion_threshold_bytes: Optional[int],
    groups: Optional[Sequence[Sequence[int]]] = None,
    sparse_as_dense: bool = False,
    residuals: Any = None,
    lowering: Optional[str] = None,
    update: Optional[Callable[[Any], Any]] = None,
) -> Any:
    """Bucket, compress, and allreduce a gradient pytree as few fused
    collectives (the FuseResponses + fusion-buffer path, compiled).

    ``IndexedSlices`` leaves take the sparse path — allgather of slices
    (reference ``tensorflow/__init__.py:95-162``) — then densify locally
    for the inner optimizer; ``sparse_as_dense=True`` densifies *before*
    the reduction instead (reference ``torch/optimizer.py``
    ``sparse_as_dense``), trading wire bytes for one fused collective.

    ``residuals`` (pytree matching ``grads``, fp32 leaves) engages
    error feedback on quantized-wire buckets; the call then returns
    ``(reduced, new_residuals)`` instead of just the reduced tree.

    ``lowering`` pins the per-bucket exchange lowering for this
    reduction (``None`` defers to ``HVD_TPU_TOPO_LOWER`` /
    ``SchedConfig.lowering``) — the Adasum optimizer preset passes
    ``"hier_adasum"``.

    ``update`` (a closure over the *reduced* gradient tree) engages
    whole-step emission (``HVD_TPU_ONESTEP``): on the scheduler path
    the decompress+update epilogue is handed to
    :func:`~horovod_tpu.sched.execute.exchange` and — when the fold is
    engaged — stitched into the exchange emission itself, so XLA
    compiles reduce + update as one program.  The call then returns
    ``update(reduced_tree)`` (with residuals:
    ``(update_result, new_residuals)``) instead of the reduced tree.
    Paths the fold does not cover (legacy single-pass, sparse leaves,
    ``HVD_TPU_ONESTEP=off``) apply ``update`` after the reduction —
    value-identical, the fold is ordering-only.
    """
    from ..ops.sparse import IndexedSlices, densify, sparse_allreduce

    # Quantized wire (Compression.int8/fp8 or a HVD_TPU_SCHED_WIRE
    # request) validation happens up front so it also covers all-sparse
    # trees and sparse leaves (which would otherwise silently ship fp32
    # through the identity compressor).  The autotune probe can force
    # the quantized wire on at trace time (third explored knob,
    # utils/autotune.py) — only ever on, never off: an explicit
    # Compression.int8 is a user numerics choice.
    quantized = getattr(compression, "quantized_wire", False)
    if _quantized_override:
        quantized = True
    if quantized:
        if op not in (Average, Sum):
            from .. import sched as _sched_mod

            # Narrowed raise (PR 10): hierarchical Adasum quantizes only
            # the DCN hop (the intra-slice sum stays dense), so a
            # cross-slice topology serves Compression.int8/fp8 + Adasum
            # through the hier_adasum lowering.  Flat Adasum (single
            # slice, process subsets, multi-axis) still raises — the
            # VHDD tree has no quantized form.
            if not (op == Adasum and _sched_mod.current_config().enabled
                    and _adasum_hier_eligible(axis, process_set)):
                raise QuantizedWireError(
                    "the quantized wire requires op=Average/Sum "
                    "(ops/quantized.py); flat Adasum has no quantized "
                    "lowering — on a cross-slice topology hier_adasum "
                    "quantizes just the DCN hop (docs/adasum.md)"
                )
        if process_set is not None and process_set.process_set_id != 0:
            # v2 serves sets that tile the axis into equal replica
            # groups (the phase collectives ride replica_groups);
            # anything else raises rather than silently going dense.
            from ..runtime import get_runtime

            table = get_runtime().process_set_table
            if table.partition_groups(process_set) is None and \
                    len(process_set.ranks) != table.world_size:
                raise QuantizedWireError(
                    f"the quantized wire serves the global set or sets "
                    f"that tile the axis into equal replica groups; "
                    f"{process_set!r} does neither — use the dense "
                    "path for arbitrary subsets"
                )

    is_sparse = lambda x: isinstance(x, IndexedSlices)
    if sparse_as_dense:
        grads = jax.tree.map(
            lambda g: densify(g) if is_sparse(g) else g, grads,
            is_leaf=is_sparse,
        )
    leaves, treedef = jax.tree.flatten(grads, is_leaf=is_sparse)
    if not leaves:
        return update(grads) if update is not None else grads
    sparse_idx = [i for i, g in enumerate(leaves) if is_sparse(g)]
    if sparse_idx:
        if quantized:
            raise QuantizedWireError(
                "Compression.int8 does not support IndexedSlices "
                "gradients (the quantizer lives inside the dense "
                "two-phase reduction); use sparse_as_dense=True or a "
                "cast compressor (bf16/fp16)"
            )
        if op not in (Average, Sum):
            raise ValueError(
                "IndexedSlices gradients support op=Average or Sum only "
                "(the reference's sparse path is allgather-based and has "
                "no Adasum variant); pass sparse_as_dense=True to adasum "
                "embedding gradients as dense tensors"
            )

        def reduce_sparse(s: IndexedSlices) -> jax.Array:
            # Same wire semantics as the dense path: compress the
            # payload, prescale before the collective, postscale after.
            wire, ctx = compression.compress(s.values)
            if prescale_factor != 1.0:
                wire = wire * jnp.asarray(prescale_factor, wire.dtype)
            out = sparse_allreduce(
                IndexedSlices(s.indices, wire, s.dense_shape),
                axis=axis, op=op, process_set=process_set,
            )
            vals = compression.decompress(out.values, ctx)
            if postscale_factor != 1.0:
                vals = vals * jnp.asarray(postscale_factor, vals.dtype)
            reduced = densify(
                IndexedSlices(out.indices, vals, s.dense_shape)
            )
            if process_set is not None:
                # Non-members keep their own local gradient (the dense
                # path's jnp.where(mask, y, x) pass-through,
                # traced.py:236); allgather hands them zeros or foreign
                # slices instead, so mask at the densified level.
                from ..ops.traced import _set_info

                _, mask, _, _ = _set_info(axis, process_set)
                if mask is not None:
                    reduced = jnp.where(mask, reduced, densify(s))
            return reduced

        sparse_set = set(sparse_idx)
        dense_pos = [i for i in range(len(leaves)) if i not in sparse_set]
        if groups is not None:
            # Remap explicit group indices onto the dense-only leaf list.
            old_to_new = {old: new for new, old in enumerate(dense_pos)}
            bad = [i for g in groups for i in g if i in sparse_set]
            if bad:
                raise ValueError(
                    f"groups reference IndexedSlices leaves {bad}; sparse "
                    "gradients cannot join fusion groups (they reduce as "
                    "allgather-of-slices, not fused allreduce)"
                )
            groups = [[old_to_new[i] for i in g] for g in groups]
        reduced_sparse = {i: reduce_sparse(leaves[i]) for i in sparse_idx}
        dense_reduced = _reduce_gradients(
            [leaves[i] for i in dense_pos],
            axis=axis, op=op, compression=compression,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set=process_set,
            fusion_threshold_bytes=fusion_threshold_bytes, groups=groups,
            lowering=lowering,
        )
        out = list(leaves)
        for i, t in zip(dense_pos, dense_reduced):
            out[i] = t
        for i, t in reduced_sparse.items():
            out[i] = t
        tree = jax.tree.unflatten(treedef, out)
        # Sparse leaves never fold (allgather-of-slices has no fused
        # emission); the update applies after, value-identical.
        return update(tree) if update is not None else tree

    compressed = [compression.compress(g) for g in leaves]
    wire = [c[0] for c in compressed]
    ctxs = [c[1] for c in compressed]

    # Wire bytes per element: 1 on the int8 path (the in-memory
    # tensors stay fp32 there — compress() is identity), so buckets
    # fill to the intended wire-size threshold.
    wire_itemsize = (
        (lambda t: 1) if quantized else (lambda t: t.dtype.itemsize)
    )
    sizes = [w.size * wire_itemsize(w) for w in wire]
    wire_dtypes = [str(w.dtype) for w in wire]
    if groups is not None:
        # Explicit tensor groups (reference optimizer.py:128-162 `groups`):
        # each listed group fuses atomically; ungrouped tensors bucket by
        # threshold.
        grouped_idx = set(i for g in groups for i in g)
        pinned = [list(g) for g in groups]
        rest = [i for i in range(len(wire)) if i not in grouped_idx]
    else:
        pinned = []
        rest = list(range(len(wire)))

    # Quantized wire (Compression.int8/fp8): the quantization lives
    # inside the two-phase reduction, so the bucket dispatches to the
    # quantized primitives instead of cast-allreduce-cast.  Pre/postscale
    # fold into the fp32 accumulation outside the quantizer.
    def reduce_flat(f):
        if quantized:
            from ..ops.quantized import quantized_allreduce

            if not jnp.issubdtype(f.dtype, jnp.floating):
                return traced.allreduce(
                    f, axis=axis, op=op,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    process_set=process_set,
                )
            g = f if prescale_factor == 1.0 else f * prescale_factor
            g = quantized_allreduce(
                g, axis=axis, op=op, process_set=process_set,
                wire=getattr(compression, "wire_format", "int8"),
            )
            return g if postscale_factor == 1.0 else g * postscale_factor
        return traced.allreduce(
            f, axis=axis, op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            process_set=process_set,
        )

    # Per-bucket hot-path lanes (reference per-tensor activity lanes,
    # common.h:73-105): a named_scope per bucket lands in the compiled
    # program's op metadata — the device profiler attributes each fused
    # collective to its bucket — and, when a timeline is active, the
    # plan records one event per bucket at trace time so a slow bucket
    # is identifiable without a full profiler trace.
    from ..runtime import get_runtime_or_none

    _rt = get_runtime_or_none()
    tl = _rt.timeline if _rt is not None else None

    from .. import sched as _sched

    cfg = _sched.current_config()
    if cfg.enabled:
        # Bucketed overlap scheduler (sched/, default engine): plan in
        # reverse-backward order (observed by the grad-boundary taps
        # when TrainStep armed them), emit barrier-sequenced per-bucket
        # collectives XLA can overlap with the remaining backward.
        import dataclasses as _dc

        if cfg.bucket_bytes is None and fusion_threshold_bytes is not None:
            cfg = _dc.replace(cfg, bucket_bytes=fusion_threshold_bytes)
        # Per-bucket wire request: an explicit quantized compressor
        # wins; otherwise the HVD_TPU_SCHED_WIRE / tuner choice rides.
        wire_req = (
            getattr(compression, "wire_format", "int8") if quantized
            else cfg.wire
        )
        if wire_req in ("int8", "fp8"):
            # Satellite contract: the quantized wire raises instead of
            # silently degrading when the reduction shape cannot carry
            # it (non-Sum/Average ops, multi-axis reductions; process
            # sets were validated above, non-tiling ones at trace
            # time).  Adasum is the narrowed exception: on a
            # cross-slice topology the hier_adasum lowering quantizes
            # just the DCN hop, so only *flat* Adasum still raises.
            if op not in (Average, Sum) and not (
                op == Adasum
                and _adasum_hier_eligible(axis, process_set)
            ):
                raise QuantizedWireError(
                    f"quantized wire {wire_req!r} requires op=Average/"
                    "Sum; flat Adasum and min/max reductions have no "
                    "quantized lowering — unset HVD_TPU_SCHED_WIRE or "
                    "use a cast compressor (cross-slice topologies "
                    "quantize Adasum's DCN hop via hier_adasum)"
                )
            if not isinstance(axis, str):
                raise QuantizedWireError(
                    f"quantized wire {wire_req!r} needs one named mesh "
                    f"axis (got {axis!r}); the all_to_all phase has no "
                    "multi-axis form"
                )
        # Hierarchical (ICI/DCN) lowering eligibility: one named axis,
        # plain sum/average, the global set (topology groups factor the
        # whole axis).  The plan stamps the cost model's per-bucket
        # choice; ineligible shapes stay flat.
        from ..parallel.tensor import _axis_present

        hier_ok = (
            op in (Average, Sum)
            and isinstance(axis, str)
            and _axis_present(axis)
            and (process_set is None or process_set.process_set_id == 0)
        )
        # op=Adasum rides the hierarchical machinery too (ROADMAP 5a):
        # eligible buckets lower hier_adasum — the reference's
        # AdasumGpuAllreduceOp schedule (sum inside the slice, adaptive
        # summation across) — unless the lowering is forced flat, in
        # which case (and on single-slice topologies, where the plan
        # resolves flat anyway) the flat VHDD tree serves the bucket.
        adasum_ok = (
            op == Adasum
            and isinstance(axis, str)
            and _axis_present(axis)
            and (process_set is None or process_set.process_set_id == 0)
        )
        req_lowering = cfg.lowering if lowering is None else lowering
        if hier_ok:
            lower_req = req_lowering
        elif adasum_ok:
            lower_req = "flat" if req_lowering == "flat" \
                else "hier_adasum"
        else:
            lower_req = "flat"
        schedule = _sched.build_schedule(
            sizes, wire_dtypes, cfg,
            order=_sched.hooks.consume_order(len(wire)),
            pinned=pinned,
            wire=wire_req,
            lowering=lower_req,
            axis_size=(
                jax.lax.axis_size(axis) if (hier_ok or adasum_ok)
                else None
            ),
        )
        # reduce_scatter+all_gather exchange (arXiv:2004.13336) needs a
        # plain sum/average over one whole-world axis; anything else
        # (Adasum, process sets, multi-axis) keeps the allreduce
        # lowering per dense bucket.  Quantized buckets have their own
        # RS+AG lowering below (for them the decomposition IS the
        # allreduce), so both sched modes run quantized end-to-end.
        rs_ok = (
            cfg.mode == "reduce_scatter"
            and op in (Average, Sum)
            and (process_set is None or process_set.process_set_id == 0)
            and isinstance(axis, str)
        )

        def dense_flat(f):
            if rs_ok and jnp.issubdtype(f.dtype, jnp.floating):
                return _sched.execute.reduce_scatter_flat(
                    f, axis=axis, average=(op == Average),
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                )
            return reduce_flat(f)

        res_out = None
        if residuals is not None:
            res_out = list(jax.tree.flatten(residuals)[0])
            if len(res_out) != len(wire):
                raise ValueError(
                    "residuals structure does not match gradients"
                )

        def reduce_bucket_flat(f, bucket):
            if bucket.lowering == "hier_adasum" and (hier_ok or adasum_ok):
                # Hierarchical Adasum (both sched modes — the staged
                # allreduce IS the RS+AG composition): intra-slice sum,
                # adaptive combination on the 1/k DCN shard, ICI
                # gather.  The bucket's wire compresses only the DCN
                # leg; EF does not apply (hier semantics).
                return _sched.execute.hier_adasum_flat(
                    f, axis=axis, average=(op != Sum),
                    wire=bucket.wire,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                )
            if bucket.lowering == "hier" and hier_ok:
                # Two-level ICI/DCN staging (topo/): the bucket's wire
                # compresses only the cross-slice hop.  EF residuals
                # don't apply on this lowering (the quantization error
                # lives on the slice-summed shard, not the gradient) —
                # hier quantized buckets run EF-free.
                if rs_ok and jnp.issubdtype(f.dtype, jnp.floating):
                    return _sched.execute.hier_reduce_scatter_flat(
                        f, axis=axis, average=(op == Average),
                        wire=bucket.wire,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor,
                    )
                return _sched.execute.hier_allreduce_flat(
                    f, axis=axis, average=(op == Average),
                    wire=bucket.wire,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                )
            if bucket.wire in ("int8", "fp8"):
                res_flat, rmeta = None, None
                if res_out is not None:
                    rf, rmeta = fusion.flatten_group(
                        [res_out[i] for i in bucket.indices]
                    )
                    res_flat = rf[0]
                red, r_new = _sched.execute.quantized_exchange_flat(
                    f, axis=axis, average=(op == Average),
                    wire=bucket.wire,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    residual=res_flat, process_set=process_set,
                )
                if r_new is not None:
                    for i, r in zip(
                        bucket.indices,
                        fusion.unflatten_group([r_new], rmeta),
                    ):
                        res_out[i] = r.astype(res_out[i].dtype)
                return red
            if bucket.wire == "bf16":
                return _sched.execute.bf16_wire(dense_flat)(f)
            return dense_flat(f)

        # Rail pipeliner (xir/pipeline.py): hier buckets may emit as
        # per-rail phase chains — the factory mirrors the serialized
        # hier reducers above op for op, so pipeline on/off/auto is
        # bitwise-identical on the f32 dense wire.
        phase_factory = (
            _sched.execute.hier_phase_factory(
                axis=axis, average=(op == Average), rs_mode=rs_ok,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
            )
            if hier_ok else None
        )
        if update is None:
            reduced = _sched.exchange(
                wire, schedule, reduce_bucket_flat,
                barriers=cfg.barriers, timeline=tl, axis=axis,
                phases=phase_factory,
            )
            out = [
                compression.decompress(t, c)
                for t, c in zip(reduced, ctxs)
            ]
            tree = jax.tree.unflatten(treedef, out)
            if residuals is not None:
                return tree, jax.tree.unflatten(treedef, res_out)
            return tree

        # Whole-step emission (HVD_TPU_ONESTEP): hand the decompress +
        # optimizer-update closure to the exchange so an engaged fold
        # stitches it INTO the traced emission (one dispatch unit for
        # reduce + update).  A None result means the fold did not
        # engage — the epilogue then applies right here, on the exact
        # jaxpr the epilogue-free path would have built.
        def _epilogue(red_leaves):
            out_ = [
                compression.decompress(t, c)
                for t, c in zip(red_leaves, ctxs)
            ]
            return update(jax.tree.unflatten(treedef, out_))

        reduced, update_result = _sched.exchange(
            wire, schedule, reduce_bucket_flat,
            barriers=cfg.barriers, timeline=tl, axis=axis,
            phases=phase_factory, epilogue=_epilogue,
        )
        if update_result is None:
            update_result = _epilogue(reduced)
        if residuals is not None:
            return update_result, jax.tree.unflatten(treedef, res_out)
        return update_result

    # Legacy single-pass path (HVD_TPU_SCHED=off): in-order buckets, no
    # sequencing barriers — one monolithic fused exchange per dtype run.
    buckets = list(pinned)
    if rest:
        for b in fusion.bucket_plan(
            [sizes[i] for i in rest], [wire_dtypes[i] for i in rest],
            fusion_threshold_bytes,
        ):
            buckets.append([rest[i] for i in b])
    reduced = list(wire)
    for bi, bucket in enumerate(buckets):
        nbytes = sum(sizes[i] for i in bucket)
        if tl is not None:
            tl.record_op(
                f"bucket{bi}[n={len(bucket)}]", "FUSION_PLAN", nbytes
            )
        with jax.named_scope(f"hvd_bucket{bi}_{nbytes}B"):
            flats, meta = fusion.flatten_group([wire[i] for i in bucket])
            out_flats = [reduce_flat(f) for f in flats]
        for i, t in zip(bucket, fusion.unflatten_group(out_flats, meta)):
            reduced[i] = t

    out = [compression.decompress(t, c) for t, c in zip(reduced, ctxs)]
    tree = jax.tree.unflatten(treedef, out)
    if update is not None:
        # Legacy single-pass engine: no fold (the path has no program
        # emission to stitch into); the update applies after.
        tree = update(tree)
    if residuals is not None:
        # Legacy engine: EF rides the scheduler; residuals pass through
        # untouched (zeros behave as plain quantization).
        return tree, residuals
    return tree


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    op: int = Average,
    compression: type[Compressor] = Compression.none,
    backward_passes_per_step: int = 1,
    average_aggregated_gradients: bool = True,
    gradient_predivide_factor: float = 1.0,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
    fusion_threshold_bytes: Optional[int] = None,
    groups: Optional[Sequence[Sequence[int]]] = None,
    sparse_as_dense: bool = False,
    axis=WORLD_AXIS,
    lowering: Optional[str] = None,
) -> optax.GradientTransformation:
    """Wrap an optax transform with distributed gradient reduction.

    Mirrors ``hvd.DistributedOptimizer`` keyword-for-keyword where the
    concept survives on TPU (no ``named_parameters``: JAX gradients are
    a named pytree by construction).  Gradient pytrees may carry
    :class:`~horovod_tpu.ops.sparse.IndexedSlices` leaves (from
    ``dense_grad_to_indexed_slices``); those reduce as allgather-of-
    slices unless ``sparse_as_dense=True`` densifies them first
    (reference ``torch/optimizer.py`` knob of the same name).

    ``lowering`` pins this optimizer's per-bucket exchange lowering
    (``flat``/``hier``/``hier_adasum``/``auto``; ``None`` defers to
    ``HVD_TPU_TOPO_LOWER``) — the ``DistributedAdasumOptimizer``
    preset pins ``hier_adasum``.  Ineligible buckets (non-float,
    single-slice topologies, process subsets) still resolve flat.
    """
    if gradient_predivide_factor != 1.0:
        if op != Average:
            raise ValueError(
                "gradient_predivide_factor requires op=Average "
                "(reference torch/optimizer.py:194)"
            )
        # Reference split (optimizer.py:194-205): prescale by 1/f before
        # the sum, postscale by f/size after.
        prescale_factor = prescale_factor / gradient_predivide_factor
        postscale_factor = postscale_factor * gradient_predivide_factor
    k = int(backward_passes_per_step)
    if k < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    def reduce_fn(grads, residuals=None, update=None):
        return _reduce_gradients(
            grads,
            axis=axis,
            op=op,
            compression=compression,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            process_set=process_set,
            fusion_threshold_bytes=fusion_threshold_bytes,
            groups=groups,
            sparse_as_dense=sparse_as_dense,
            residuals=residuals,
            lowering=lowering,
            update=update,
        )

    def _ef_active() -> bool:
        # Error-feedback residuals ride the scheduler engine with a
        # quantized wire — either an explicit Compression.int8/fp8 or a
        # HVD_TPU_SCHED_WIRE=int8/fp8 request at init time (the state
        # must exist before the first trace).
        from .. import sched as _sched

        cfg = _sched.current_config()
        if not (cfg.enabled and cfg.wire_ef):
            return False
        if getattr(compression, "quantized_wire", False):
            return True
        return cfg.wire in ("int8", "fp8")

    def init_fn(params):
        acc = None
        if k > 1:
            acc = jax.tree.map(jnp.zeros_like, params)
        residual = None
        if _ef_active():
            residual = jax.tree.map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params
            )
        return DistributedOptimizerState(
            counter=jnp.zeros((), jnp.int32),
            acc=acc,
            inner=optimizer.init(params),
            residual=residual,
        )

    def update_fn(grads, state: DistributedOptimizerState, params=None):
        residual = getattr(state, "residual", None)
        if k == 1:
            from ..xir import interp as _xinterp

            if _xinterp.onestep_mode() != "off":
                # Whole-step emission (HVD_TPU_ONESTEP): the inner
                # update rides into the reduction as a closure, so an
                # engaged fold compiles exchange + update as ONE
                # dispatch unit.  Identical math in identical order —
                # the closure body is the exact two lines below.
                def _apply(reduced_tree):
                    return optimizer.update(
                        reduced_tree, state.inner, params
                    )

                if residual is not None:
                    (updates, inner), residual = reduce_fn(
                        grads, residual, update=_apply
                    )
                else:
                    updates, inner = reduce_fn(grads, update=_apply)
                return updates, DistributedOptimizerState(
                    counter=state.counter + 1, acc=None, inner=inner,
                    residual=residual,
                )
            if residual is not None:
                reduced, residual = reduce_fn(grads, residual)
            else:
                reduced = reduce_fn(grads)
            updates, inner = optimizer.update(reduced, state.inner, params)
            return updates, DistributedOptimizerState(
                counter=state.counter + 1, acc=None, inner=inner,
                residual=residual,
            )

        # Local gradient aggregation (reference
        # LocalGradientAggregationHelper / optimizer.py
        # backward_passes_per_step): accumulate locally, reduce + step
        # every k-th call, zero updates in between.  Sparse leaves
        # densify into the (dense) accumulator, like the reference's
        # aggregation helper which only handles dense buffers.
        from ..ops.sparse import IndexedSlices as _IS, densify as _densify

        grads = jax.tree.map(
            lambda g: _densify(g) if isinstance(g, _IS) else g, grads,
            is_leaf=lambda x: isinstance(x, _IS),
        )
        acc = jax.tree.map(lambda a, g: a + g, state.acc, grads)
        counter = state.counter + 1
        boundary = (counter % k) == 0

        def do_step(operand):
            acc_, inner_, res_ = operand
            scale = 1.0 / k if average_aggregated_gradients else 1.0
            scaled = jax.tree.map(lambda a: a * scale, acc_)
            if res_ is not None:
                reduced, res_ = reduce_fn(scaled, res_)
            else:
                reduced = reduce_fn(scaled)
            updates, new_inner = optimizer.update(reduced, inner_, params)
            zeroed = jax.tree.map(jnp.zeros_like, acc_)
            return updates, zeroed, new_inner, res_

        def no_step(operand):
            acc_, inner_, res_ = operand
            updates = jax.tree.map(jnp.zeros_like, acc_)
            return updates, acc_, inner_, res_

        updates, acc, inner, residual = lax.cond(
            boundary, do_step, no_step, (acc, state.inner, residual)
        )
        return updates, DistributedOptimizerState(
            counter=counter, acc=acc, inner=inner, residual=residual
        )

    # Autotune eligibility marker: with an explicit threshold the trace-
    # time override in fusion.bucket_plan is never consulted, so TrainStep
    # must not burn recompiles exploring candidates that change nothing.
    update_fn._hvd_fusion_threshold = fusion_threshold_bytes
    # Quantized-wire exploration eligibility (third autotune knob): the
    # probe only makes sense when int8 isn't already the user's wire and
    # the reduction shape supports it; sparse leaves are discovered at
    # trace time and rejected there.
    update_fn._hvd_quant_eligible = (
        not getattr(compression, "quantized_wire", False)
        and op in (Average, Sum)
        and (process_set is None or process_set.process_set_id == 0)
    )
    # Exchange-service markers (svc/): the wrapped inner transform so
    # the bounded-staleness pipeline (HVD_TPU_SVC_STALENESS>=1) can
    # drive it directly — its exchange splits into a synchronous ICI
    # leg and a service-submitted DCN leg, replacing the inline global
    # reduction above — and the eligibility gate (plain averaged DP
    # over the whole world; anything else stays synchronous).
    update_fn._hvd_inner = optimizer
    update_fn._hvd_stale_eligible = (
        op == Average
        and not getattr(compression, "quantized_wire", False)
        and (process_set is None or process_set.process_set_id == 0)
        and prescale_factor == 1.0 and postscale_factor == 1.0
        and k == 1
    )
    return optax.GradientTransformation(init_fn, update_fn)


class TrainStep:
    """Compiled SPMD training step (the DistributedGradientTape-equivalent
    end-to-end path, reference ``tensorflow/__init__.py:355-455``).

    ``init(params)`` builds properly-sharded optimizer state;
    ``__call__(params, opt_state, batch)`` runs one fused step: local
    grads on each chip's batch shard -> fused allreduce -> optimizer
    update -> loss pmean.

    Stateful models (flax mutable collections like BatchNorm
    ``batch_stats``): pass ``stateful=True`` with
    ``loss_fn(params, model_state, batch) -> (loss, new_model_state)``;
    the step becomes ``(params, model_state, opt_state, batch) ->
    (params, model_state, opt_state, loss)``.  The returned model state
    is cross-replica averaged so running statistics stay identical on
    every rank — note this is *running-stats* averaging only:
    normalization inside the step still uses each replica's local batch
    moments.  For true synchronized BatchNorm (moments allreduced before
    normalizing, reference ``torch/sync_batch_norm.py``) build the model
    with ``horovod_tpu.SyncBatchNorm``.
    """

    def __init__(
        self, loss_fn, optimizer, *, axis=WORLD_AXIS, has_aux=False,
        stateful=False, donate=True,
    ):
        self._donate = bool(donate)
        if stateful and has_aux:
            raise ValueError(
                "stateful=True and has_aux=True are mutually exclusive: a "
                "stateful loss_fn's aux slot carries the new model state "
                "(return extra metrics inside the model state pytree)"
            )
        rt = get_runtime()
        self.mesh = rt.mesh
        self.axis = axis
        self.has_aux = has_aux
        self.stateful = stateful
        self._optimizer = optimizer

        param_spec = P()  # replicated
        batch_spec = P(axis)  # sharded along leading dim

        def state_specs(state):
            # acc and EF-residual leaves vary per rank -> stacked over
            # the axis; the rest of the state is replicated.
            if isinstance(state, DistributedOptimizerState) and (
                state.acc is not None or state.residual is not None
            ):
                def vary(t):
                    return jax.tree.map(lambda _: P(axis), t)

                return DistributedOptimizerState(
                    counter=P(),
                    acc=None if state.acc is None else vary(state.acc),
                    inner=jax.tree.map(lambda _: P(), state.inner),
                    residual=(
                        None if state.residual is None
                        else vary(state.residual)
                    ),
                )
            return jax.tree.map(lambda _: P(), state)

        def _stack_local(st, unstack=False):
            """[None]-stack (or unstack) the per-rank-varying leaves so
            the P(axis) spec carries them as one global array."""
            f = (lambda a: a[0]) if unstack else (lambda a: a[None])
            if isinstance(st, DistributedOptimizerState):
                if st.acc is not None:
                    st = st._replace(acc=jax.tree.map(f, st.acc))
                if st.residual is not None:
                    st = st._replace(residual=jax.tree.map(f, st.residual))
            return st

        def init_body(params):
            return _stack_local(optimizer.init(params))

        # Grad-boundary taps (sched/hooks.py): when the overlap
        # scheduler drives a DistributedOptimizer (marker present), the
        # backward trace records per-leaf readiness order so the plan
        # stage buckets in true reverse-backward order.  Gated on the
        # marker — a plain optax transform never consumes the capture.
        _is_hvd_opt = hasattr(optimizer.update, "_hvd_fusion_threshold")

        def _loss_for_trace():
            from .. import sched as _sched

            _cfg = _sched.current_config()
            if _is_hvd_opt and _cfg.enabled and _cfg.capture_order:
                return _sched.hooks.capturing_loss(loss_fn)
            return loss_fn

        def compute_grads(params, model_state, batch):
            loss_fn = _loss_for_trace()
            if stateful:
                (loss, out_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    params, model_state, batch
                )
                # Cross-replica average of model state (SyncBN semantics).
                out_state = lax.pmean(out_state, axis)
                return loss, out_state, None, grads
            if has_aux:
                (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    params, batch
                )
                return loss, None, lax.pmean(aux, axis), grads
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            return loss, None, None, grads

        def step_body(params, model_state, opt_state, batch):
            opt_state = _stack_local(opt_state, unstack=True)
            with jax.named_scope("hvd_compute_grads"):
                loss, model_state, aux, grads = compute_grads(
                    params, model_state, batch
                )
            with jax.named_scope("hvd_reduce_and_update"):
                updates, opt_state = optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
            loss = lax.pmean(loss, axis)
            opt_state = _stack_local(opt_state)
            out = (params,)
            if stateful:
                out += (model_state,)
            out += (opt_state, loss)
            if aux is not None:
                out += (aux,)
            return out

        # Build init: trace state structure to derive out specs.
        def make_init():
            def init(params):
                shape = jax.eval_shape(init_body, params)
                out_specs = state_specs(shape)
                f = jax.shard_map(
                    init_body,
                    mesh=self.mesh,
                    in_specs=(param_spec,),
                    out_specs=out_specs,
                    check_vma=False,
                )
                return jax.jit(f)(params)

            return init

        self.init = make_init()
        self._step_cache = {}
        self._step_body = step_body
        self._param_spec = param_spec
        self._batch_spec = batch_spec
        self._state_specs = state_specs

        # Transparent autotuning (reference ParameterManager,
        # parameter_manager.h:42-105): with HVD_TPU_AUTOTUNE=1 the step
        # drives suggest -> recompile-under-threshold -> observe windows
        # by itself and freezes on the winner.  Each candidate threshold
        # is its own compiled variant (threshold is a trace-time
        # constant), keyed into the step cache.
        from ..utils import env as _env

        self._autotune = None
        # Eligible only for a DistributedOptimizer without an explicit
        # threshold: the marker must be PRESENT and None — a plain optax
        # transform (no marker) never consults the fusion threshold, so
        # exploring candidates would recompile for nothing.
        marker = getattr(optimizer.update, "_hvd_fusion_threshold", "absent")
        if _env.get_bool(_env.AUTOTUNE) and marker is None:
            from ..utils.autotune import AutotuneDriver

            self._autotune = AutotuneDriver(
                quant_eligible=getattr(
                    optimizer.update, "_hvd_quant_eligible", False
                ),
            )
        self._mark_cycles = _env.get_bool(_env.TIMELINE_MARK_CYCLES)

    def _build_step(self, specs):
        in_specs = (self._param_spec, P(), specs, self._batch_spec)
        out_specs = (self._param_spec,)
        if self.stateful:
            out_specs += (P(),)
        out_specs += (specs, P())
        if self.has_aux and not self.stateful:
            out_specs += (P(),)
        # Donate params / model state / optimizer state — the pytrees
        # the step returns updated — so XLA aliases them in place
        # instead of copying the full parameter set in HBM every step.
        # ``donate=False`` (the numerics-parity test hook) keeps the
        # inputs alive and must produce bitwise-identical losses.
        fn = jax.jit(
            jax.shard_map(
                self._step_body,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            ),
            donate_argnums=(0, 1, 2) if self._donate else (),
        )
        from .. import prof

        # Profiling plane: AOT-compile through the wrapper so XLA
        # cost/memory analysis feeds prof.flops / prof.mfu — an
        # AOT-compiled call runs the same HLO as the jit call, so
        # losses stay bitwise identical; HVD_TPU_PROF=off returns fn
        # untouched.
        return prof.wrap_executor(
            fn, key=f"train_step_{len(self._step_cache)}",
            kind="step", workload="train_step",
        )

    def __call__(self, params, *args):
        if self.stateful:
            model_state, opt_state, batch = args
        else:
            opt_state, batch = args
            model_state = None
        specs = self._state_specs(opt_state)
        from ..xir import interp as _xinterp

        # Whole-step emission mode is a trace-time constant (the update
        # closure either folds into the exchange or runs after it), so
        # each resolved mode is its own compiled variant — flipping
        # HVD_TPU_ONESTEP mid-run retraces instead of silently running
        # the stale shape.
        onestep = _xinterp.onestep_mode()
        threshold = None
        hier = None
        quant = None
        if self._autotune is not None:
            threshold = self._autotune.threshold_bytes()
            hier = self._autotune.hierarchical()
            quant = self._autotune.quantized()
            if self._autotune.converged and len(self._step_cache) > 1:
                # Exploration over: drop the losing compiled variants
                # (each is a full XLA executable holding device code).
                frozen_key = (
                    jax.tree.structure(opt_state),
                    jax.tree.structure(model_state),
                    threshold, hier, quant, onestep,
                )
                self._step_cache = {
                    k: v for k, v in self._step_cache.items()
                    if k == frozen_key
                }
        key = (
            jax.tree.structure(opt_state),
            jax.tree.structure(model_state),
            threshold, hier, quant, onestep,
        )
        fn = self._step_cache.get(key)
        built_here = fn is None
        if fn is None:
            fn = self._build_step(specs)
            self._step_cache[key] = fn

        rt = get_runtime()
        tl = rt.timeline
        if tl is not None:
            tl.begin("TrainStep", "STEP")
        import time as _time

        from .. import metrics as _metrics, trace as _trace

        # Step span (trace/): the root every exchange/bucket/rail span
        # emitted during this dispatch nests under; finalization feeds
        # the flight recorder's slow-step check and derives the
        # measured topo.rail_busy_frac gauges.  Host-side only — the
        # traced computation is untouched.
        # The onestep attr rides the step span so prof/hostgap.py
        # counts the folded step as exactly one dispatch (the exec span
        # covers exchange + update; without the attr a fallback-demoted
        # wrapper would read 0 and the epilogue could double-count).
        _step_span = _trace.step(
            compiled=not built_here,
            onestep=1 if onestep == "on" else 0,
        )
        _step_span.__enter__()
        _t0 = _time.perf_counter()
        try:
            # Tracing for a new cache entry happens inside this call, so
            # the candidate threshold (and lowering/wire choices) must
            # be visible to bucket_plan / traced.allreduce /
            # _reduce_pytree now.
            fusion.set_threshold_override(threshold)
            traced.set_hierarchical_override(hier)
            set_quantized_override(quant)
            with jax.profiler.TraceAnnotation("hvd_train_step"):
                out = fn(params, model_state, opt_state, batch)
        except QuantizedWireError:
            if quant and built_here and self._autotune is not None \
                    and not self._autotune.converged:
                # The quantized probe variant is unsupportable at trace
                # time (e.g. sparse gradients): reject the knob and
                # re-run this step on the unquantized config.  Retrying
                # is safe ONLY for the call that traced the new variant
                # (trace errors precede any donation), and ONLY for the
                # dedicated quantized-wire validation error — a user
                # ValueError must propagate, never silently reject the
                # knob.  A QuantizedWireError from a cached step's
                # execution re-raises so a real error is never masked
                # by a knob flip.
                self._step_cache.pop(key, None)
                self._autotune.reject_quantized()
                fusion.set_threshold_override(None)
                traced.set_hierarchical_override(None)
                set_quantized_override(None)
                from ..sched import hooks as _sched_hooks

                _sched_hooks.reset()  # drop the aborted trace's capture
                return self(params, *args)
            raise
        finally:
            fusion.set_threshold_override(None)
            traced.set_hierarchical_override(None)
            set_quantized_override(None)
            _step_span.__exit__(None, None, None)
            # Dispatch latency, not device latency: the step returns
            # futures (async dispatch); a cache miss shows the compile.
            _metrics.observe(
                "train.step_seconds", _time.perf_counter() - _t0
            )
            _metrics.inc_counter("train.steps")
            if tl is not None:
                tl.end("TrainStep", "STEP")
                if self._mark_cycles:
                    tl.mark_cycle()
        if self._autotune is not None:
            self._autotune.after_step(out[-1])
        return out


def distributed_train_step(
    loss_fn,
    optimizer: optax.GradientTransformation,
    *,
    axis=WORLD_AXIS,
    has_aux: bool = False,
    stateful: bool = False,
    donate: bool = True,
) -> TrainStep:
    """Build the compiled SPMD train step; see ``TrainStep``.

    ``loss_fn(params, batch) -> loss`` (or with ``stateful=True``,
    ``loss_fn(params, model_state, batch) -> (loss, new_model_state)``)
    is written for a *local* batch shard; batches passed to the step
    carry the global batch with leading dimension divisible by ``size``.

    With the exchange service on and a staleness bound
    (``HVD_TPU_SVC=on``, ``HVD_TPU_SVC_STALENESS=k>=1``), an eligible
    DistributedOptimizer (plain averaged DP over the whole world, no
    aux/model state) returns the bounded-staleness step instead
    (:class:`~horovod_tpu.svc.stale.StaleTrainStep`): the ICI leg of
    the exchange stays synchronous, the DCN leg is submitted to the
    service and lands as a correction ``k`` steps later.  Ineligible
    shapes — and ``staleness=0``, which is bitwise identical to
    ``HVD_TPU_SVC=off`` — keep this synchronous step.
    """
    from .. import svc as _svc

    if (_svc.enabled() and _svc.staleness() >= 1
            and not has_aux and not stateful
            and getattr(optimizer.update, "_hvd_stale_eligible", False)):
        from ..svc import stale as _stale

        why = _stale.eligible(axis)
        if why is None:
            return _stale.StaleTrainStep(
                loss_fn, optimizer.update._hvd_inner, axis=axis,
                donate=donate,
            )
        from ..utils.logging import get_logger

        get_logger().warning(
            "HVD_TPU_SVC_STALENESS=%d requested but unavailable (%s); "
            "running the synchronous step", _svc.staleness(), why,
        )
    return TrainStep(
        loss_fn, optimizer, axis=axis, has_aux=has_aux,
        stateful=stateful, donate=donate,
    )
