"""Delta-Adasum optimizer (reference ``_DistributedAdasumOptimizer``,
``horovod/torch/optimizer.py:335-503``).

Where the plain ``DistributedOptimizer(op=Adasum)`` adaptively combines
*gradients*, the reference's Adasum optimizer applies the inner
optimizer *locally* first and adaptively combines the resulting
parameter *deltas* — this preserves Adasum's scale-invariance through
optimizers with per-parameter state (Adam etc.), which is the variant
the Adasum paper (arXiv:2006.02924) recommends.

Since PR 10 this is a thin preset over the ``DistributedOptimizer``
reduction machinery with the exchange lowering pinned to
``hier_adasum``: the delta reduction rides the bucketed overlap
scheduler — reverse-backward buckets, cost-model byte accounting, the
persistent tune DB, and (on cross-slice topologies) the hierarchical
staging that sums deltas over ICI and applies Adasum's adaptive
dot-product combination only on the DCN hop, where divergence actually
lives (docs/adasum.md).  A quantized ``compression`` compresses just
that DCN leg.  Single-slice topologies resolve the pin to ``flat`` and
reduce through the flat VHDD tree, exactly as before.
"""

from __future__ import annotations

from typing import Optional

import optax

from ..compression import Compression, Compressor
from ..ops import traced
from ..process_sets import ProcessSet
from ..runtime import WORLD_AXIS


def DistributedAdasumOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    compression: type[Compressor] = Compression.none,
    process_set: Optional[ProcessSet] = None,
    fusion_threshold_bytes: Optional[int] = None,
    axis=WORLD_AXIS,
) -> optax.GradientTransformation:
    """Wrap an optax transform: local update -> Adasum of the deltas.

    The returned transform's ``update`` must run in SPMD context (inside
    ``shard_map``), like ``DistributedOptimizer``.
    """

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(grads, state, params=None):
        from .distributed_optimizer import _reduce_gradients

        updates, state = optimizer.update(grads, state, params)
        reduced = _reduce_gradients(
            updates,
            axis=axis,
            op=traced.Adasum,
            compression=compression,
            prescale_factor=1.0,
            postscale_factor=1.0,
            process_set=process_set,
            fusion_threshold_bytes=fusion_threshold_bytes,
            lowering="hier_adasum",
        )
        return reduced, state

    return optax.GradientTransformation(init_fn, update_fn)
