from .adasum_optimizer import DistributedAdasumOptimizer  # noqa: F401
from .distributed_optimizer import (  # noqa: F401
    DistributedOptimizer,
    DistributedOptimizerState,
    distributed_train_step,
)
from .zero import (  # noqa: F401
    sharded_gradient_transformation,
    fsdp_train_step,
    zero_train_step,
)
