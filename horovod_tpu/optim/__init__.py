from .adasum_optimizer import DistributedAdasumOptimizer  # noqa: F401
from .distributed_optimizer import (  # noqa: F401
    DistributedOptimizer,
    DistributedOptimizerState,
    distributed_train_step,
    remesh_optimizer_state,
)
from .zero import (  # noqa: F401
    clip_by_global_norm,
    global_norm,
    sharded_gradient_transformation,
    fsdp_train_step,
    zero_train_step,
)
