from .adasum_optimizer import DistributedAdasumOptimizer  # noqa: F401
from .distributed_optimizer import (  # noqa: F401
    DistributedOptimizer,
    DistributedOptimizerState,
    distributed_train_step,
)
