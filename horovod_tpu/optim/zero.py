"""ZeRO-1/ZeRO-3 sharded training states (capability add beyond the reference).

The reference replicates optimizer state on every rank (its
DistributedOptimizer wraps a local optimizer; only gradients cross the
wire).  On TPU the bandwidth-optimal gradient primitive is
``reduce_scatter`` (each chip receives only 1/N of the reduced
gradient), which makes optimizer-state sharding free to bolt on:

    grads --psum_scatter--> grad shard          (same bytes as allreduce's
    shard update with optax on the 1/N slice     reduce-scatter half)
    params <--all_gather-- updated param shards (the other half)

Total comms equal one allreduce (reduce-scatter + all-gather), but
optimizer state (e.g. Adam's two moments) shrinks N-fold per chip, and
the optimizer update itself runs on 1/N of the elements.

Sharding is over the *flattened* parameter vector, so it is exact for
elementwise transforms (sgd, momentum, adam(w), rmsprop, lamb's
elementwise core...).  Transforms that need global-across-parameters
reductions (e.g. ``optax.clip_by_global_norm``) would see only their
shard — close that gap with :func:`global_norm` (psum of per-shard
squared norms over the sync axis) and the ``pre_update`` hook on
:func:`sharded_gradient_transformation` /
:func:`zero_train_step`: :func:`clip_by_global_norm` is the ready-made
hook matching optax semantics on sharded gradients.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.flatten_util import ravel_pytree

from ..runtime import WORLD_AXIS


def global_norm(shards, axis=WORLD_AXIS) -> jax.Array:
    """Global L2 norm of a sharded flat vector (or list/pytree of
    shards): psum of per-shard squared norms over the sync ``axis``,
    then sqrt — every rank sees the same *global* norm even though it
    holds only 1/N of the elements.  Zero-padding in the shards is
    norm-neutral.  Must run inside ``shard_map`` over ``axis``."""
    sq = sum(
        jnp.sum(jnp.square(s)) for s in jax.tree.leaves(shards)
    )
    return jnp.sqrt(lax.psum(sq, axis))


def clip_by_global_norm(max_norm: float, axis=WORLD_AXIS):
    """``pre_update`` hook clipping sharded gradients to a global norm
    (the ``optax.clip_by_global_norm`` semantics the flat-shard layout
    otherwise breaks): scales every shard by ``max_norm / norm`` when
    the GLOBAL norm exceeds ``max_norm``.  Accepts one shard or a
    list of per-bucket shards (``sched.bucketed_zero_step``)."""

    def hook(shards):
        single = not isinstance(shards, (list, tuple))
        leaves = [shards] if single else list(shards)
        norm = global_norm(leaves, axis)
        scale = jnp.where(
            norm > max_norm, max_norm / jnp.maximum(norm, 1e-16), 1.0
        )
        out = [s * scale.astype(s.dtype) for s in leaves]
        return out[0] if single else out

    return hook


def _resolve_wire(wire):
    """None ⇒ follow the scheduler's ``HVD_TPU_SCHED_WIRE`` /
    ``HVD_TPU_SCHED_WIRE_EF`` knobs; explicit values pin it."""
    from ..sched import current_config

    cfg = current_config()
    w = cfg.wire if wire is None else wire
    w = (w or "off").strip().lower()
    if w in ("none", ""):
        w = "off"
    return w, cfg.wire_ef


def sharded_gradient_transformation(
    tx: optax.GradientTransformation,
    axis=WORLD_AXIS,
    pre_update=None,
    wire=None,
) -> optax.GradientTransformation:
    """Wrap ``tx`` so init/update act on this rank's flat param shard.

    For use inside ``shard_map`` with replicated params: ``init`` builds
    state for the local 1/N slice; ``update`` takes *unreduced local
    grads*, reduce-scatters them (average), updates the slice, and
    returns full-size updates assembled by all-gather.

    ``pre_update``: hook on the reduced gradient shard before the inner
    update — the composition point for global-across-parameters
    transforms (:func:`clip_by_global_norm`); it runs after the
    reduce-scatter, so :func:`global_norm`-style psums inside it see
    every shard.

    ``wire``: ``"int8"`` / ``"fp8"`` runs both collectives on the
    quantized wire (``ops/quantized.py`` — the reduce-scatter carries
    ``quantize(g + r)`` with the error-feedback residual ``r`` folded
    into the state as ``{"tx": ..., "ef": ...}``; the sharded update
    consumes the dequantized fp32 shard; the post-update all-gather
    re-quantizes).  ``None`` follows ``HVD_TPU_SCHED_WIRE``; ``"off"``
    pins the dense wire (state structure unchanged).
    """
    wire, wire_ef = _resolve_wire(wire)
    quantized = wire in ("int8", "fp8")
    ef = quantized and wire_ef

    def _shard_meta(params):
        flat, unravel = ravel_pytree(params)
        n = flat.shape[0]
        world = lax.axis_size(axis)
        unit = world
        if quantized:
            # Shards must stay quantization-block-aligned so the
            # post-update all_gather re-quantizes without repadding.
            from ..ops.quantized import quant_block

            unit = world * quant_block()
        padded = -(-n // unit) * unit
        return flat, unravel, n, world, padded

    def init_fn(params):
        flat, _, n, world, padded = _shard_meta(params)
        idx = lax.axis_index(axis)
        shard_len = padded // world
        flat = jnp.pad(flat, (0, padded - n))
        my = lax.dynamic_slice(flat, (idx * shard_len,), (shard_len,))
        state = tx.init(my)
        if ef:
            state = {"tx": state, "ef": jnp.zeros((padded,), jnp.float32)}
        return state

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("sharded optimizer requires params")
        gflat, _, n, world, padded = _shard_meta(grads)
        pflat, unravel, _, _, _ = _shard_meta(params)
        shard_len = padded // world
        idx = lax.axis_index(axis)

        gflat = jnp.pad(gflat, (0, padded - n))
        residual = None
        if quantized:
            from ..ops.quantized import (
                quantized_all_gather,
                quantized_reduce_scatter,
            )
            from ..ops.traced import Sum

            if ef:
                e = gflat.astype(jnp.float32) + state["ef"]
                gshard, residual = quantized_reduce_scatter(
                    e, axis, op=Sum, wire=wire, ef=True,
                )
                state = state["tx"]
            else:
                gshard = quantized_reduce_scatter(
                    gflat, axis, op=Sum, wire=wire,
                )
            gshard = gshard / world
        else:
            # Average-reduce-scatter: each rank gets its 1/N of the
            # mean grad.
            gshard = lax.psum_scatter(
                gflat, axis, scatter_dimension=0, tiled=True
            ) / world
        pshard = lax.dynamic_slice(
            jnp.pad(pflat, (0, padded - n)), (idx * shard_len,), (shard_len,)
        )
        if pre_update is not None:
            gshard = pre_update(gshard)
        ushard, state = tx.update(gshard.astype(pshard.dtype), state, pshard)
        # Assemble the full update vector; params stay replicated.
        if quantized:
            uflat = quantized_all_gather(ushard, axis, wire=wire)[:n]
            uflat = uflat.astype(pshard.dtype)
        else:
            uflat = lax.all_gather(ushard, axis, tiled=True)[:n]
        if ef:
            state = {"tx": state, "ef": residual}
        return unravel(uflat), state

    return optax.GradientTransformation(init_fn, update_fn)


def zero_train_step(
    loss_fn,
    tx: optax.GradientTransformation,
    *,
    axis=WORLD_AXIS,
    pre_update=None,
    wire=None,
):
    """Compiled SPMD step with ZeRO-1 sharded optimizer state.

    Same call convention as ``distributed_train_step``'s stateless form:
    ``step.init(params)`` then ``step(params, opt_state, batch) ->
    (params, opt_state, loss)``.  Params are replicated; optimizer state
    leaves live sharded (leading dim padded_n/N per chip).
    ``pre_update`` hooks the reduced gradient shard before the inner
    update (global-norm clipping etc. — see
    :func:`clip_by_global_norm`).  ``wire`` as in
    :func:`sharded_gradient_transformation` (quantized RS/AG + error
    feedback; default follows ``HVD_TPU_SCHED_WIRE``).
    """
    from jax.sharding import PartitionSpec as P

    from .. import runtime as _rt

    stx = sharded_gradient_transformation(
        tx, axis=axis, pre_update=pre_update, wire=wire
    )
    rt = _rt.get_runtime()
    mesh = rt.mesh
    param_spec = P()
    wire_resolved, wire_ef = _resolve_wire(wire)
    ef = wire_resolved in ("int8", "fp8") and wire_ef

    def init_body(params):
        return stx.init(params)

    def step_body(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = stx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, lax.pmean(loss, axis)

    def state_spec_for(params):
        # Opt-state leaves are device-varying shards -> P(axis); the
        # structure comes from an axis-free emulation of init.
        def abstract_init(p):
            flat, _ = ravel_pytree(p)
            world = rt.size
            unit = world
            if wire_resolved in ("int8", "fp8"):
                from ..ops.quantized import quant_block

                unit = world * quant_block()
            padded = -(-flat.shape[0] // unit) * unit
            state = tx.init(jnp.zeros((padded // world,), flat.dtype))
            if ef:
                state = {
                    "tx": state,
                    "ef": jnp.zeros((padded,), jnp.float32),
                }
            return state

        return _state_spec(jax.eval_shape(abstract_init, params), axis)

    class _Step:
        def __init__(self):
            self._fn = None

        def init(self, params):
            f = jax.shard_map(
                init_body, mesh=mesh, in_specs=(param_spec,),
                out_specs=state_spec_for(params), check_vma=False,
            )
            return jax.jit(f)(params)

        def __call__(self, params, opt_state, batch):
            if self._fn is None:
                state_spec = _state_spec(opt_state, axis)
                batch_spec = jax.tree.map(lambda _: P(axis), batch)
                self._fn = jax.jit(jax.shard_map(
                    step_body, mesh=mesh,
                    in_specs=(param_spec, state_spec, batch_spec),
                    out_specs=(param_spec, state_spec, P()),
                    check_vma=False,
                ), donate_argnums=(0, 1))
            return self._fn(params, opt_state, batch)

    return _Step()


def _state_spec(tree, axis):
    """Spec pytree: array leaves shard over ``axis``, scalars replicate."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda leaf: P(axis) if getattr(leaf, "ndim", 0) > 0 else P(), tree
    )


def _flat_layout(params_like, world: int):
    """(n, padded, shard_len, ravel, unravel) for a param pytree.

    Works on concrete arrays OR shape/dtype structs
    (``jax.eval_shape`` output), so the layout can be rebuilt for
    checkpoint restore without materializing full parameters.  The
    ravel preserves each leaf's dtype (no common-dtype promotion)."""
    import numpy as np

    leaves, treedef = jax.tree.flatten(params_like)
    shapes = [tuple(l.shape) for l in leaves]
    dtypes = [jnp.dtype(l.dtype) for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    n = sum(sizes)
    padded = -(-n // world) * world

    def ravel(tree):
        ls = jax.tree.leaves(tree)
        return jnp.concatenate(
            [l.reshape(-1).astype(jnp.float32) for l in ls]
        )

    def unravel(flat):
        out, off = [], 0
        for sh, dt, sz in zip(shapes, dtypes, sizes):
            out.append(flat[off : off + sz].reshape(sh).astype(dt))
            off += sz
        return jax.tree.unflatten(treedef, out)

    return n, padded, padded // world, ravel, unravel


def _fsdp_exchange(op_name: str, x: jax.Array, axis, bucket: int = 0
                   ) -> jax.Array:
    """One FSDP exchange phase through the exchange IR (``xir``): the
    per-step parameter ``all_gather`` or gradient ``reduce_scatter``.
    The interpreter emits the identical flat ``lax`` collective
    (``HVD_TPU_XIR=off`` calls it directly — bitwise either way); the
    wire stays dense here (FSDP's wire compression is its own
    ``compression=`` kwarg, applied by the caller around this hop) and
    the lowering stays flat (the 1/N shard layout is the optimizer-
    state contract, so the hierarchy's own layout cannot substitute).
    What FSDP gains is the FSDP_EXCHANGE timeline lane, kind-labeled
    byte gauges, and a persistent-store key for its program."""
    from .. import xir

    if not xir.enabled():
        if op_name == "all_gather":
            return lax.all_gather(x, axis, tiled=True)
        return lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
    if op_name == "all_gather":
        op = xir.all_gather(
            axis, lowering="flat", bucket=bucket,
            nbytes=x.size * x.dtype.itemsize, dtype=x.dtype,
        )
    else:
        op = xir.reduce_scatter(
            axis, lowering="flat", bucket=bucket,
            nbytes=x.size * x.dtype.itemsize, dtype=x.dtype,
        )
    return xir.execute(
        xir.program("fsdp", [op]), [x], axis_size=lax.axis_size(axis)
    )[0]


def fsdp_train_step(
    loss_fn,
    tx: optax.GradientTransformation,
    *,
    axis=WORLD_AXIS,
    example_params=None,
    compression=None,
):
    """ZeRO-3-style fully sharded step: *parameters and optimizer state*
    both live as 1/N flat shards between steps.

    Per step: one tiled ``all_gather`` re-materializes the full
    parameter vector for fwd/bwd, one ``psum_scatter`` reduces
    gradients straight into shards, and the optimizer update runs on
    the 1/N slice — the same total wire bytes as an allreduce, with
    persistent per-chip storage of ``(1 + opt_moments)/N`` of the
    model instead of ``1 + opt_moments`` replicated (FSDP over the
    flattened vector; per-layer gather scheduling is XLA's latency
    hiding problem under jit).

    Call convention::

        step = fsdp_train_step(loss_fn, tx)
        pshards, opt_state = step.init(params)          # shard it all
        pshards, opt_state, loss = step(pshards, opt_state, batch)
        params = step.gather(pshards)                   # eval/checkpoint

    Checkpoint restore without materializing full params: pass the
    parameter *structure* up front (``example_params`` may be
    ``jax.eval_shape`` output — no real arrays needed), then feed the
    restored shards straight into ``step``/``gather``::

        shapes = jax.eval_shape(model.init, rng, dummy)
        step = fsdp_train_step(loss_fn, tx, example_params=shapes)
        pshards, opt_state = restored  # from your checkpoint
        pshards, opt_state, loss = step(pshards, opt_state, batch)

    Sharding is over the flattened fp32-raveled vector; leaf dtypes are
    restored on unravel.
    """
    from jax.sharding import PartitionSpec as P

    from .. import runtime as _rt

    rt = _rt.get_runtime()
    mesh = rt.mesh
    world = rt.size
    meta = {}

    def _set_layout(params_like):
        (meta["n"], meta["padded"], meta["shard_len"], meta["ravel"],
         meta["unravel"]) = _flat_layout(params_like, world)

    if example_params is not None:
        _set_layout(example_params)

    def _layout():
        if "unravel" not in meta:
            raise RuntimeError(
                "fsdp_train_step: parameter layout unknown — call "
                "init(params) first, or construct with "
                "example_params=jax.eval_shape(model.init, ...) when "
                "restoring shards from a checkpoint"
            )
        return meta

    def init_body(params):
        m = _layout()
        flat = m["ravel"](params)
        idx = lax.axis_index(axis)
        flat = jnp.pad(flat, (0, m["padded"] - m["n"]))
        pshard = lax.dynamic_slice(
            flat, (idx * m["shard_len"],), (m["shard_len"],)
        )
        return pshard, tx.init(pshard)

    def step_body(pshard, opt_state, batch):
        m = _layout()
        pfull = _fsdp_exchange("all_gather", pshard, axis)[: m["n"]]
        params = m["unravel"](pfull)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        gflat = m["ravel"](grads)
        gflat = jnp.pad(gflat, (0, m["padded"] - m["n"]))
        if compression is not None:
            # wire compression on the reduce-scatter (the DP fused-
            # allreduce compression knob, applied to the RS phase)
            wire, ctx = compression.compress(gflat)
            gshard = _fsdp_exchange("reduce_scatter", wire, axis,
                                    bucket=1)
            gshard = compression.decompress(gshard, ctx) / world
        else:
            gshard = _fsdp_exchange("reduce_scatter", gflat, axis,
                                    bucket=1) / world
        ushard, opt_state = tx.update(gshard, opt_state, pshard)
        pshard = optax.apply_updates(pshard, ushard)
        return pshard, opt_state, lax.pmean(loss, axis)

    def gather_body(pshard):
        m = _layout()
        return m["unravel"](
            _fsdp_exchange("all_gather", pshard, axis, bucket=2)[: m["n"]]
        )

    class _Step:
        def __init__(self):
            self._fn = None
            self._gather = None

        def init(self, params):
            _set_layout(params)
            f = jax.shard_map(
                init_body, mesh=mesh, in_specs=(P(),),
                out_specs=(
                    P(axis),
                    _state_spec(
                        jax.eval_shape(
                            lambda: tx.init(jnp.zeros(
                                (meta["shard_len"],), jnp.float32
                            ))
                        ),
                        axis,
                    ),
                ),
                check_vma=False,
            )
            return jax.jit(f)(params)

        def __call__(self, pshard, opt_state, batch):
            _layout()
            if self._fn is None:
                state_spec = _state_spec(opt_state, axis)
                batch_spec = jax.tree.map(lambda _: P(axis), batch)
                self._fn = jax.jit(jax.shard_map(
                    step_body, mesh=mesh,
                    in_specs=(P(axis), state_spec, batch_spec),
                    out_specs=(P(axis), state_spec, P()),
                    check_vma=False,
                ), donate_argnums=(0, 1))
            return self._fn(pshard, opt_state, batch)

        def gather(self, pshard):
            _layout()
            if self._gather is None:
                self._gather = jax.jit(jax.shard_map(
                    gather_body, mesh=mesh, in_specs=(P(axis),),
                    out_specs=P(), check_vma=False,
                ))
            return self._gather(pshard)

    return _Step()
