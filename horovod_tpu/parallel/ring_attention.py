"""Ring attention: context parallelism over an ICI ring.

Capability add mandated by SURVEY.md §5 ("long-context / sequence
parallelism — absent" in the reference; the nearest primitives are
``alltoall`` and process sets).  Design is TPU-first: the sequence is
sharded over a mesh axis, each device keeps its Q block resident and
streams K/V blocks around the ring with ``lax.ppermute`` while
accumulating the attention output with an online (flash-style) softmax.
Per step each device does one [T_loc × T_loc] block attention — MXU
matmuls — while the next K/V block is in flight on ICI, so compute
hides the communication for T_loc·D ≳ per-hop latency·bandwidth.

Memory is O(T_loc²) per block score matrix and O(T_loc·D) state —
never O(T²) — which is what makes million-token contexts feasible.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import SP_AXIS

_NEG_INF = -1e30


def full_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Plain softmax attention, f32 accumulation: [B, T, H, D] → same.

    The single-device reference semantics that ``ring_attention`` and
    ``ulysses_attention`` must match bit-for-bit up to fp error.
    ``segment_ids`` ([B, T]) restricts attention to same-segment keys
    (packed sequences).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), dtype=bool), k=tk - tq)
        s = jnp.where(mask, s, _NEG_INF)
    if segment_ids is not None:
        segmask = (
            segment_ids[:, :, None] == segment_ids[:, None, :]
        )[:, None]  # [B, 1, Tq, Tk]
        s = jnp.where(segmask, s, _NEG_INF)
        # no fully-masked row is possible: q and k share one segment
        # array, so every query matches at least its own key (diagonal)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str = SP_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis``.

    Args: q/k/v of shape [B, T_local, H, D] per device, where the global
    sequence is the concatenation of blocks in axis order.  Must be
    called inside ``shard_map`` (or pmap) over ``axis``.  Returns the
    local [B, T_local, H, D] output block, exactly equal (up to fp) to
    the corresponding slice of ``full_attention`` on the gathered
    sequence.
    """
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    b, t, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    qf = q.astype(jnp.float32) * scale
    q_pos = idx * t + jnp.arange(t)  # global positions of local queries

    # Online-softmax state: output accum, row sum, row max ([B, H, T]).
    # pcast marks the accumulators device-varying so the fori_loop carry
    # type matches its (varying) outputs under shard_map.
    o = lax.pcast(jnp.zeros((b, t, h, d), jnp.float32), (axis,), to="varying")
    l = lax.pcast(jnp.zeros((b, h, t), jnp.float32), (axis,), to="varying")
    m = lax.pcast(
        jnp.full((b, h, t), _NEG_INF, jnp.float32), (axis,), to="varying"
    )

    shift = [(j, (j + 1) % n) for j in range(n)]

    def block_update(o, l, m, kb, vb, i):
        # After i rotations device `idx` holds the K/V block originally
        # owned by device (idx - i) mod n.
        kv_block = (idx - i) % n
        k_pos = kv_block * t + jnp.arange(t)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32))
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, _NEG_INF)
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # Fully-masked-so-far rows keep m == -inf; subtract 0 there so
        # exp(-inf - 0) == 0 instead of exp(nan).
        m_safe = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(jnp.where(m <= _NEG_INF, _NEG_INF, m) - m_safe)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, vb.astype(jnp.float32)
        )
        return o, l, m_new

    def step(i, carry):
        o, l, m, kb, vb = carry
        # Launch the next hop first: the block matmuls below have no
        # data dependence on it, so XLA overlaps compute with the ICI
        # transfer (double buffering).
        kb_next = lax.ppermute(kb, axis, shift)
        vb_next = lax.ppermute(vb, axis, shift)
        o, l, m = block_update(o, l, m, kb, vb, i)
        return o, l, m, kb_next, vb_next

    # n-1 rotations, n block updates: the last block computes on the
    # final carried buffers with no trailing (dead) ppermute.
    o, l, m, k, v = lax.fori_loop(0, n - 1, step, (o, l, m, k, v))
    o, l, m = block_update(o, l, m, k, v, n - 1)
    l = l.transpose(0, 2, 1)[..., None]  # [B, T, H, 1]
    return (o / jnp.where(l == 0.0, 1.0, l)).astype(q.dtype)
