"""Gradient synchronization for hybrid-parallel training.

The reference's DistributedOptimizer allreduces every gradient over the
world (``horovod/torch/optimizer.py:506``) because all params are
replicated under pure DP.  Under hybrid parallelism the rule is
per-parameter.  Derivation: inside ``shard_map``, ``jax.grad`` seeds
every device's (replicated-after-psum) loss with 1, and collective
transposes (psum↔psum, ppermute↔inverse-ppermute, all_to_all↔inverse)
route cotangents across devices — so each device's raw gradient is
``d(Σ_devices L_i)/dθ_local``.  To recover the gradient of the MEAN
per-device loss:

* **pmean** over every sync axis the parameter is NOT sharded over
  (replicated copies each collect a partial contribution);
* **divide by the axis size** for every sync axis the parameter IS
  sharded over (its raw gradient already aggregates all devices'
  contributions via the collective transposes, but counts the
  model-axis-replicated loss ``axis_size`` times).

This one rule covers dp (classic allreduce-average), sp (ring/Ulysses
cotangents arrive via ppermute/all_to_all transposes), tp (Megatron
replicated-vs-sharded split), and ep (expert grads arrive via the
all_to_all transpose).

``param_shard_axes`` pytrees use space-separated axis-name strings
("", "tp", "ep") as leaves so they stay pytree-compatible.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
from jax import lax

from .mesh import DP_AXIS, EP_AXIS, SP_AXIS, TP_AXIS
from .tensor import _axis_present


def _parse(axes: str) -> Tuple[str, ...]:
    return tuple(a for a in axes.split() if a)


def sync_gradients(
    grads,
    param_shard_axes=None,
    axes: Sequence[str] = (DP_AXIS, SP_AXIS, TP_AXIS, EP_AXIS),
    scheduled: bool | None = None,
):
    """Synchronize a gradient pytree inside shard_map.

    ``param_shard_axes``: pytree matching ``grads`` whose leaves are
    space-separated axis names the corresponding PARAMETER is sharded
    over ("" = fully replicated).  None ⇒ all parameters replicated
    (pure DP/SP — every grad pmean'd over the sync axes).

    ``axes``: mesh axes to synchronize over; names not bound in the
    current shard_map are skipped, so one call site works across mesh
    shapes.

    ``scheduled``: route the pmeans through the bucketed overlap
    scheduler (``sched/``) — per-parameter semantics are unchanged
    (pmean is elementwise, so bucketing never moves a value), but the
    exchange becomes reverse-backward ordered fused buckets XLA can
    overlap with compute.  ``None`` follows the ``HVD_TPU_SCHED`` knob
    (default on).
    """
    if scheduled is None:
        from ..sched import current_config

        scheduled = current_config().enabled
    if scheduled:
        from ..sched import sync_gradients_bucketed

        return sync_gradients_bucketed(grads, param_shard_axes, axes)
    present = tuple(a for a in axes if _axis_present(a))

    def sync(g, sharded_str):
        sharded = _parse(sharded_str)
        mean_over = tuple(a for a in present if a not in sharded)
        if mean_over:
            g = lax.pmean(g, mean_over)
        scale = 1
        for a in present:
            if a in sharded:
                scale *= lax.axis_size(a)
        if scale != 1:
            g = g / scale
        return g

    if param_shard_axes is None:
        return jax.tree.map(lambda g: sync(g, ""), grads)
    return jax.tree.map(sync, grads, param_shard_axes)
