"""Ulysses-style sequence parallelism via all_to_all.

The second long-context strategy from SURVEY.md §5: where ring
attention streams K/V around the ring, Ulysses re-shards — an
all_to_all flips the sharding from sequence-sharded/head-replicated to
head-sharded/sequence-complete, runs ordinary full attention on H/n
local heads, and flips back.  Two all_to_alls move 2·[B,T_loc,H,D]
per device vs. ring's n ppermute hops of [B,T_loc,H,D] K+V; Ulysses
wins when heads ≥ devices and the per-device full-sequence score
matrix fits HBM, ring wins for extreme T.  This is the TPU-native use
of the reference's ``alltoall`` collective
(``horovod/common/operations.cc:1630``, ``NCCLAlltoall``).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax import lax

from .mesh import SP_AXIS
from .ring_attention import full_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str = SP_AXIS,
    causal: bool = False,
    attn_fn: Optional[Callable[..., jax.Array]] = None,
) -> jax.Array:
    """Attention over a sequence sharded on ``axis`` via head exchange.

    q/k/v: [B, T_local, H, D] per device with H divisible by the axis
    size.  Must run inside ``shard_map`` over ``axis``.  ``attn_fn``
    (default exact ``full_attention``) sees [B, T_global, H/n, D].
    """
    n = lax.axis_size(axis)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"heads ({h}) must be divisible by axis size {n}")

    def _flip(x, split_axis, concat_axis, bucket):
        # One head/sequence re-shard through the exchange IR: the
        # interpreter emits the identical lax.all_to_all on the dense
        # wire (HVD_TPU_XIR=off calls it directly), bf16 wire requests
        # cast around it, and the flip's bytes land in the
        # ULYSSES_EXCHANGE lane + kind-labeled gauges.
        from .. import xir

        if not xir.enabled():
            return lax.all_to_all(
                x, axis, split_axis=split_axis, concat_axis=concat_axis,
                tiled=True,
            )
        op = xir.all_to_all(
            axis, split_axis=split_axis, concat_axis=concat_axis,
            wire=xir.wire_request(), bucket=bucket,
            nbytes=x.size * x.dtype.itemsize, dtype=x.dtype,
        )
        return xir.execute(
            xir.program("ulysses", [op]), [x], axis_size=n
        )[0]

    def seq_to_heads(x, bucket=0):
        # [B, T_loc, H, D] -> [B, T_global, H/n, D]
        return _flip(x, 2, 1, bucket)

    def heads_to_seq(x):
        return _flip(x, 1, 2, 3)

    q, k, v = seq_to_heads(q, 0), seq_to_heads(k, 1), seq_to_heads(v, 2)
    out = (attn_fn or full_attention)(q, k, v, causal=causal)
    return heads_to_seq(out)
