"""Pipeline parallelism: GPipe-style microbatching over the ``pp`` axis.

SURVEY.md §2.5 lists PP as absent from the reference.  TPU-native
design (the "collective pipeline" of the scaling playbook): every
device holds one stage's parameters, activations circulate one hop per
step with ``lax.ppermute``, and the schedule is a single ``fori_loop``
of M + n − 1 steps — fully static control flow, compiled once.  The
whole pipeline is a differentiable pure function, so ``jax.grad``
through it yields the standard GPipe backward schedule without any
hand-written bubble management; wrap the stage in ``jax.checkpoint`` to
trade recompute for activation memory exactly where GPipe does.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import PP_AXIS


def pipeline_apply(
    stage_fn: Callable[[jax.Array, jax.Array], jax.Array],
    stage_params,
    microbatches: jax.Array,
    axis: str = PP_AXIS,
    broadcast_outputs: bool = True,
    remat_stage: bool = False,
) -> jax.Array:
    """Run microbatches through the n-stage pipeline.

    Must be called inside ``shard_map`` over ``axis``, with
    ``stage_params`` already sharded so each device holds ITS stage's
    parameters (e.g. a [n_stages, ...] stacked pytree sharded on dim 0
    and squeezed).  ``microbatches`` is [M, B, ...]; stage activations
    must be shape-preserving ([B, ...] in == out), the usual transformer
    -block invariant.

    Returns [M, B, ...] outputs — on every device when
    ``broadcast_outputs`` (one psum), else valid on the last stage only.

    ``remat_stage=True`` wraps the stage in ``jax.checkpoint`` so the
    backward recomputes each stage invocation's *internal*
    intermediates instead of storing them — the per-step stage inputs
    (the loop carry) are still saved by the scan backward, so memory
    remains linear in the schedule length; what shrinks is the
    per-step constant (roughly the stage's intermediates-to-input
    ratio, ~an order of magnitude for a transformer block).
    """
    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn)
    from .. import xir

    n = lax.axis_size(axis)
    stage = lax.axis_index(axis)
    m = microbatches.shape[0]
    shift = [(j, (j + 1) % n) for j in range(n)]

    def _hop(y):
        # The stage-to-stage activation hop through the exchange IR:
        # the interpreter emits the identical lax.ppermute on the
        # dense wire (HVD_TPU_XIR=off calls it directly); the hop's
        # bytes land in the PIPELINE_EXCHANGE lane + kind-labeled
        # gauges, with the DCN share computed from which (src, dst)
        # pairs cross a slice boundary.
        if not xir.enabled():
            return lax.ppermute(y, axis, shift)
        op = xir.permute(
            axis, shift, wire=xir.wire_request(),
            nbytes=y.size * y.dtype.itemsize, dtype=y.dtype,
        )
        return xir.execute(xir.program("pipeline", [op]), [y],
                           axis_size=n)[0]

    # pcast marks the loop state device-varying so the fori_loop carry
    # type matches its (varying, post-ppermute) outputs under shard_map.
    act0 = lax.pcast(
        jnp.zeros_like(microbatches[0]), (axis,), to="varying"
    )
    out0 = lax.pcast(
        jnp.zeros((m,) + microbatches.shape[1:], microbatches.dtype),
        (axis,), to="varying",
    )

    def step(s, carry):
        act, out = carry
        # Stage 0 ingests microbatch s (clipped: steps ≥ M feed a dummy
        # that never reaches the output window); later stages consume
        # the activation ppermuted from their predecessor.
        x_in = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(s, 0, m - 1), 0, keepdims=False
        )
        inp = jnp.where(stage == 0, x_in, act)
        y = stage_fn(stage_params, inp)
        # The last stage finishes microbatch s-(n-1) at step s.
        out_idx = jnp.clip(s - (n - 1), 0, m - 1)
        prev = lax.dynamic_index_in_dim(out, out_idx, 0, keepdims=False)
        write = jnp.logical_and(stage == n - 1, s >= n - 1)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(write, y.astype(out.dtype), prev), out_idx, 0
        )
        act = _hop(y)
        return act, out

    _, out = lax.fori_loop(0, m + n - 1, step, (act0, out0))
    if broadcast_outputs:
        out = lax.psum(jnp.where(stage == n - 1, out, jnp.zeros_like(out)), axis)
    return out
