"""Tensor (operator) parallelism: Megatron-style sharded dense layers.

SURVEY.md §2.5 lists TP as absent from the reference (whose only
building block for it is process sets).  TPU-native design: weights are
sharded over the ``tp`` mesh axis and the layers are written for
``shard_map`` — each device holds a [in, out/n] (column) or [in/n, out]
(row) shard, matmuls stay large and MXU-shaped, and the only
communication is one ``psum`` at the row-parallel output (the classic
f/g conjugate pair).  A column→row pair (MLP, attention out-proj)
therefore costs exactly one all-reduce per layer on the forward pass,
and XLA inserts the mirrored collectives for the backward pass
automatically since everything is a differentiable pure function.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from .mesh import TP_AXIS

Dtype = Any


def _axis_present(axis: str) -> bool:
    """True when called under shard_map/pjit with this named axis bound."""
    try:
        lax.axis_size(axis)
        return True
    except NameError:
        return False


class ColumnParallelDense(nn.Module):
    """Dense with output features sharded over ``axis``.

    ``features`` is the GLOBAL output width; each device holds and
    produces a ``features / tp`` column shard.  The output stays
    sharded — feed it to a RowParallelDense to contract the sharded
    dimension back.  No communication in forward.
    """

    features: int
    axis: str = TP_AXIS
    use_bias: bool = True
    dtype: Optional[Dtype] = None
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        n = lax.axis_size(self.axis) if _axis_present(self.axis) else 1
        if self.features % n != 0:
            raise ValueError(
                f"features ({self.features}) not divisible by "
                f"'{self.axis}' axis size {n}"
            )
        return nn.Dense(
            self.features // n,
            use_bias=self.use_bias,
            dtype=self.dtype,
            kernel_init=self.kernel_init,
        )(x)


class RowParallelDense(nn.Module):
    """Dense with input features sharded over ``axis``; partial products
    are summed with one ``psum`` (the Megatron g-operator).

    ``features`` is the GLOBAL output width.  The bias is added after
    the psum (once, not n times).  Outside shard_map (single-device
    test path) the psum is skipped.
    """

    features: int
    axis: str = TP_AXIS
    use_bias: bool = True
    dtype: Optional[Dtype] = None
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        y = nn.Dense(
            self.features,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=self.kernel_init,
        )(x)
        if _axis_present(self.axis):
            y = lax.psum(y, self.axis)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,),
                jnp.float32,
            )
            y = y + jnp.asarray(bias, y.dtype)
        return y


class TensorParallelMLP(nn.Module):
    """Transformer MLP block sharded column→row: one psum per block.

    ``hidden`` and ``features`` are GLOBAL widths; the hidden dimension
    is sharded ``hidden / tp`` per device.
    """

    hidden: int
    features: int
    axis: str = TP_AXIS
    dtype: Optional[Dtype] = None
    act: Callable = nn.gelu

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = ColumnParallelDense(
            self.hidden, axis=self.axis, dtype=self.dtype, name="wi"
        )(x)
        h = self.act(h)
        return RowParallelDense(
            self.features, axis=self.axis, dtype=self.dtype, name="wo"
        )(h)
