"""Multi-dimensional parallelism over the TPU device mesh.

The reference is a data-parallel engine: its only model-parallel
building blocks are process sets (rank subsets running concurrent
collectives, ``horovod/common/process_set.{h,cc}``) and the ``alltoall``
collective (``horovod/common/operations.cc:1630``).  SURVEY.md §2.5/§5
inventories TP / PP / SP / CP / ring attention as capabilities the
TPU-native build must cover idiomatically.  This package is that cover:
first-class mesh axes (dp / tp / pp / sp / ep) instead of hand-rolled
process sets, with each strategy lowered to XLA collectives over ICI:

* ``mesh``           — named multi-axis ``jax.sharding.Mesh`` construction
* ``tensor``         — Megatron-style column/row parallel layers (psum)
* ``ring_attention`` — context parallelism: blockwise attention with
                       K/V blocks streamed around an ICI ring (ppermute)
* ``ulysses``        — sequence parallelism via head<->sequence all_to_all
* ``pipeline``       — GPipe-style microbatch pipeline over the pp axis
* ``moe``            — expert parallelism: top-k routing + all_to_all
                       dispatch/combine over the ep axis
"""

from .mesh import (  # noqa: F401
    DP_AXIS,
    EP_AXIS,
    PP_AXIS,
    SP_AXIS,
    TP_AXIS,
    ParallelConfig,
    make_mesh,
    split_axis,
    sub_axis_names,
)
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .tensor import ColumnParallelDense, RowParallelDense  # noqa: F401
from .pipeline import pipeline_apply  # noqa: F401
from .moe import MoELayer, moe_alltoall_dispatch  # noqa: F401
from .grad_sync import sync_gradients  # noqa: F401
