"""Named multi-axis device meshes for hybrid parallelism.

The reference composes parallelism out of process sets — explicit rank
lists with their own controller/queue (``horovod/common/process_set.h:26-80``,
``test/parallel/test_process_sets_static.py``).  On TPU the idiomatic
equivalent is a multi-dimensional ``jax.sharding.Mesh`` whose named axes
*are* the process sets: a collective over axis "dp" is a concurrent
per-group collective exactly like a Horovod process-set allreduce, but
the grouping is declared once in the mesh geometry and XLA lays the
collectives onto the matching ICI dimensions.

Axis order (outer→inner) follows bandwidth needs: tp (highest traffic,
innermost → shortest ICI hops), then sp/ep, then pp, then dp (lowest
traffic, outermost → may cross DCN).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names. dp = data, pp = pipeline stages, ep = experts,
# sp = sequence/context blocks, tp = tensor (operator) sharding.
DP_AXIS = "dp"
PP_AXIS = "pp"
EP_AXIS = "ep"
SP_AXIS = "sp"
TP_AXIS = "tp"

# Outer-to-inner mesh order: innermost axes get the physically closest
# devices, so the hottest collectives ride the shortest ICI links.
AXIS_ORDER: Tuple[str, ...] = (DP_AXIS, PP_AXIS, EP_AXIS, SP_AXIS, TP_AXIS)


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Degrees of each parallelism dimension; product must equal the
    number of devices (unset axes default to 1 and are dropped from the
    mesh unless ``keep_unit_axes``)."""

    dp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def degree(self, axis: str) -> int:
        return getattr(self, axis)

    @property
    def total(self) -> int:
        return self.dp * self.pp * self.ep * self.sp * self.tp

    def axes(self, keep: Sequence[str] = ()) -> List[str]:
        """Axes of the mesh: degree->1 axes plus any in ``keep`` (axes
        the caller explicitly asked for, even at degree 1)."""
        return [
            a for a in AXIS_ORDER if self.degree(a) > 1 or a in keep
        ] or [DP_AXIS]


def sub_axis_names(axis: str) -> Tuple[str, str]:
    """Canonical ``(outer, inner)`` sub-axis names of a factored axis:
    ``"dp" -> ("dp_dcn", "dp_ici")``.  The outer (DCN) sub-axis crosses
    slices, the inner (ICI) one stays inside a slice — matching the
    outer-to-inner bandwidth ordering of ``AXIS_ORDER``."""
    return f"{axis}_dcn", f"{axis}_ici"


def split_axis(
    mesh: Mesh,
    axis: str,
    inner: int,
    names: Optional[Tuple[str, str]] = None,
) -> Mesh:
    """Factor one mesh axis into ``(outer, inner)`` sub-axes.

    ``split_axis(mesh, "dp", k)`` reshapes the ``dp`` dimension of the
    device array into ``(dp // k, k)`` and names the halves
    ``("dp_dcn", "dp_ici")`` (override with ``names``).  Because the
    reshape keeps device order, consecutive blocks of ``k`` devices
    along the axis — a slice's worth, under the slice-major device
    order :mod:`horovod_tpu.topo` documents — land on the inner
    sub-axis: collectives over ``<axis>_ici`` ride ICI only, and
    ``<axis>_dcn`` addresses the cross-slice rails.  The hierarchical
    collectives accept the pair directly
    (``hierarchical_all_reduce(x, axis=("dp_dcn", "dp_ici"))``)."""
    if axis not in mesh.axis_names:
        raise ValueError(
            f"mesh has no axis {axis!r} (axes: {mesh.axis_names})"
        )
    size = mesh.shape[axis]
    if inner <= 0 or size % inner != 0:
        raise ValueError(
            f"axis {axis!r} of size {size} does not factor by "
            f"inner={inner}"
        )
    outer_name, inner_name = names or sub_axis_names(axis)
    for n in (outer_name, inner_name):
        if n in mesh.axis_names:
            raise ValueError(f"sub-axis name {n!r} already in the mesh")
    pos = mesh.axis_names.index(axis)
    arr = mesh.devices
    new_shape = (
        arr.shape[:pos] + (size // inner, inner) + arr.shape[pos + 1:]
    )
    new_names = (
        mesh.axis_names[:pos] + (outer_name, inner_name)
        + mesh.axis_names[pos + 1:]
    )
    return Mesh(arr.reshape(new_shape), new_names)


def make_mesh(
    config: Optional[ParallelConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    keep_unit_axes: bool = False,
    **degrees: int,
) -> Mesh:
    """Build a named mesh for hybrid parallelism.

    ``make_mesh(dp=2, tp=4)`` on 8 chips → Mesh {'dp': 2, 'tp': 4}.
    One axis may be -1 (inferred from the device count, like a reshape).
    Degree-1 axes are dropped unless explicitly passed as keywords (so
    ``make_mesh(pp=1)`` still has a 'pp' axis to shard over) or
    ``keep_unit_axes`` is set (keeps all five).
    """
    explicit = tuple(AXIS_ORDER) if keep_unit_axes else tuple(degrees)
    if config is None:
        config = ParallelConfig(**degrees)
    elif degrees:
        raise ValueError("pass either a ParallelConfig or keyword degrees")
    if devices is None:
        from ..runtime import get_runtime_or_none

        rt = get_runtime_or_none()
        devices = rt.devices if rt is not None else jax.devices()
    devices = list(devices)

    vals = {a: config.degree(a) for a in AXIS_ORDER}
    unknown = [a for a, v in vals.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError("at most one axis degree may be -1")
    if unknown:
        known = int(np.prod([v for v in vals.values() if v != -1]))
        if len(devices) % known != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible by fixed degrees {known}"
            )
        vals[unknown[0]] = len(devices) // known
        config = ParallelConfig(**vals)
    if config.total != len(devices):
        raise ValueError(
            f"mesh degrees {vals} multiply to {config.total}, but "
            f"{len(devices)} devices are available"
        )
    axes = config.axes(explicit)
    shape = tuple(config.degree(a) for a in axes)
    arr = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(arr, tuple(axes))
