"""Named multi-axis device meshes for hybrid parallelism.

The reference composes parallelism out of process sets — explicit rank
lists with their own controller/queue (``horovod/common/process_set.h:26-80``,
``test/parallel/test_process_sets_static.py``).  On TPU the idiomatic
equivalent is a multi-dimensional ``jax.sharding.Mesh`` whose named axes
*are* the process sets: a collective over axis "dp" is a concurrent
per-group collective exactly like a Horovod process-set allreduce, but
the grouping is declared once in the mesh geometry and XLA lays the
collectives onto the matching ICI dimensions.

Axis order (outer→inner) follows bandwidth needs: tp (highest traffic,
innermost → shortest ICI hops), then sp/ep, then pp, then dp (lowest
traffic, outermost → may cross DCN).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names. dp = data, pp = pipeline stages, ep = experts,
# sp = sequence/context blocks, tp = tensor (operator) sharding.
DP_AXIS = "dp"
PP_AXIS = "pp"
EP_AXIS = "ep"
SP_AXIS = "sp"
TP_AXIS = "tp"

# Outer-to-inner mesh order: innermost axes get the physically closest
# devices, so the hottest collectives ride the shortest ICI links.
AXIS_ORDER: Tuple[str, ...] = (DP_AXIS, PP_AXIS, EP_AXIS, SP_AXIS, TP_AXIS)


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Degrees of each parallelism dimension; product must equal the
    number of devices (unset axes default to 1 and are dropped from the
    mesh unless ``keep_unit_axes``)."""

    dp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def degree(self, axis: str) -> int:
        return getattr(self, axis)

    @property
    def total(self) -> int:
        return self.dp * self.pp * self.ep * self.sp * self.tp

    def axes(self, keep: Sequence[str] = ()) -> List[str]:
        """Axes of the mesh: degree->1 axes plus any in ``keep`` (axes
        the caller explicitly asked for, even at degree 1)."""
        return [
            a for a in AXIS_ORDER if self.degree(a) > 1 or a in keep
        ] or [DP_AXIS]


def make_mesh(
    config: Optional[ParallelConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    keep_unit_axes: bool = False,
    **degrees: int,
) -> Mesh:
    """Build a named mesh for hybrid parallelism.

    ``make_mesh(dp=2, tp=4)`` on 8 chips → Mesh {'dp': 2, 'tp': 4}.
    One axis may be -1 (inferred from the device count, like a reshape).
    Degree-1 axes are dropped unless explicitly passed as keywords (so
    ``make_mesh(pp=1)`` still has a 'pp' axis to shard over) or
    ``keep_unit_axes`` is set (keeps all five).
    """
    explicit = tuple(AXIS_ORDER) if keep_unit_axes else tuple(degrees)
    if config is None:
        config = ParallelConfig(**degrees)
    elif degrees:
        raise ValueError("pass either a ParallelConfig or keyword degrees")
    if devices is None:
        from ..runtime import get_runtime_or_none

        rt = get_runtime_or_none()
        devices = rt.devices if rt is not None else jax.devices()
    devices = list(devices)

    vals = {a: config.degree(a) for a in AXIS_ORDER}
    unknown = [a for a, v in vals.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError("at most one axis degree may be -1")
    if unknown:
        known = int(np.prod([v for v in vals.values() if v != -1]))
        if len(devices) % known != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible by fixed degrees {known}"
            )
        vals[unknown[0]] = len(devices) // known
        config = ParallelConfig(**vals)
    if config.total != len(devices):
        raise ValueError(
            f"mesh degrees {vals} multiply to {config.total}, but "
            f"{len(devices)} devices are available"
        )
    axes = config.axes(explicit)
    shape = tuple(config.degree(a) for a in axes)
    arr = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(arr, tuple(axes))
