"""Expert parallelism: top-k routed MoE with all_to_all dispatch.

SURVEY.md §2.5: the reference's only EP-relevant primitive is the
``alltoall`` collective (``EnqueueTensorAlltoall``,
``operations.cc:1630``) — routing itself lives above Horovod.  Here the
full GShard/Switch pattern is native: experts are sharded over the
``ep`` mesh axis, tokens are dispatched to their experts with one
``all_to_all``, processed by per-expert MLPs as one batched einsum
(keeps the MXU busy across experts), and combined back with a second
``all_to_all``.  Static capacity (tokens/expert) keeps every shape
fixed for XLA; overflow tokens are dropped (zero combine weight) and
ride the residual connection, the standard Switch behaviour.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from .mesh import EP_AXIS
from .tensor import _axis_present


def _top_k_gating(
    logits: jax.Array, k: int, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing with per-expert capacity.

    logits: [S, E] (f32).  Returns (combine [S, E, C], dispatch bool
    [S, E, C], aux load-balancing loss scalar).
    """
    s, e = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)

    remaining = gates
    location_base = jnp.zeros((e,), jnp.int32)  # tokens already assigned
    combine = jnp.zeros((s, e, capacity), jnp.float32)
    importance = jnp.zeros((e,), jnp.float32)
    load = jnp.zeros((e,), jnp.float32)

    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)  # [S]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # [S, E]
        gate_val = jnp.sum(gates * onehot, axis=-1)  # [S]
        # Position of each token within its chosen expert's buffer, in
        # token order, offset by assignments from earlier choices.
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [S, E]
        pos_tok = jnp.sum(pos, axis=-1).astype(jnp.int32) + location_base[choice]
        keep = pos_tok < capacity
        slot = jax.nn.one_hot(
            jnp.where(keep, pos_tok, capacity), capacity + 1, dtype=jnp.float32
        )[:, :capacity]
        combine = combine + (
            (gate_val * keep)[:, None] * onehot
        )[..., None] * slot[:, None, :]
        location_base = location_base + jnp.sum(
            onehot * keep[:, None], axis=0
        ).astype(jnp.int32)
        importance = importance + jnp.mean(gates * onehot, axis=0)
        load = load + jnp.mean(onehot, axis=0)
        remaining = remaining * (1.0 - onehot)

    # Switch-style auxiliary loss: E · Σ_e mean-gate_e · token-frac_e,
    # computed from the first-choice statistics accumulated above.
    aux = e * jnp.sum(importance / k * load / k)
    dispatch = combine > 0.0
    return combine, dispatch, aux


def _routed_all_to_all(x: jax.Array, axis: str, split_axis: int,
                       concat_axis: int, bucket: int = 0) -> jax.Array:
    """One MoE all_to_all through the exchange IR (``xir``): the op
    carries the payload metadata the tuner/store key and the byte
    gauges need, and the interpreter emits the identical
    ``lax.all_to_all`` on the dense wire (``HVD_TPU_XIR=off`` calls it
    directly — bitwise-equal either way).  Wire requests
    (``HVD_TPU_XIR_WIRE`` / ``HVD_TPU_SCHED_WIRE``) gate through
    shuffle-op eligibility: bf16 casts the wire, int8/fp8 stay off."""
    from .. import xir

    if not xir.enabled():
        return lax.all_to_all(
            x, axis, split_axis=split_axis, concat_axis=concat_axis,
            tiled=True,
        )
    op = xir.all_to_all(
        axis, split_axis=split_axis, concat_axis=concat_axis,
        wire=xir.wire_request(), bucket=bucket,
        nbytes=x.size * x.dtype.itemsize, dtype=x.dtype,
    )
    return xir.execute(
        xir.program("moe", [op]), [x], axis_size=lax.axis_size(axis)
    )[0]


def moe_alltoall_dispatch(x: jax.Array, axis: str = EP_AXIS) -> jax.Array:
    """[E, C, d] local dispatch buffers → [E_local, n·C, d] expert shards
    (one all_to_all over the ep axis); inverse of itself with the
    reshape transposed — see MoELayer for the round trip."""
    return _routed_all_to_all(x, axis, split_axis=0, concat_axis=1)


def moe_alltoall_combine(y: jax.Array, axis: str = EP_AXIS) -> jax.Array:
    """Inverse all_to_all: send each n·C slice back to its source rank
    ([E_local, n·C, d] → [E, C, d])."""
    return _routed_all_to_all(y, axis, split_axis=1, concat_axis=0,
                              bucket=1)


class MoELayer(nn.Module):
    """Mixture-of-experts FFN sharded over the ``ep`` axis.

    ``num_experts_local`` experts per device (global E = n·local);
    returns (output [B,T,d], aux_loss).  Outside shard_map it degrades
    to a single-device MoE with E = num_experts_local (the test path).
    """

    num_experts_local: int
    hidden: int
    k: int = 2
    capacity_factor: float = 1.25
    axis: str = EP_AXIS
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        b, t, d = x.shape
        n = lax.axis_size(self.axis) if _axis_present(self.axis) else 1
        e = n * self.num_experts_local
        s = b * t
        capacity = max(1, int(s * self.capacity_factor * self.k / e))

        xf = x.reshape(s, d)
        # Router always in f32: tiny matmul, numerically load-bearing.
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            xf.astype(jnp.float32)
        )
        combine, dispatch, aux = _top_k_gating(logits, self.k, capacity)

        buf = jnp.einsum(
            "sec,sd->ecd", dispatch.astype(xf.dtype), xf
        )  # [E, C, d]
        if n > 1:
            buf = moe_alltoall_dispatch(buf, self.axis)  # [E_loc, n·C, d]
        else:
            buf = buf.reshape(self.num_experts_local, n * capacity, d)

        wi = self.param(
            "wi", nn.initializers.lecun_normal(),
            (self.num_experts_local, d, self.hidden), jnp.float32,
        )
        wo = self.param(
            "wo", nn.initializers.lecun_normal(),
            (self.num_experts_local, self.hidden, d), jnp.float32,
        )
        compute_dtype = self.dtype or x.dtype
        h = jnp.einsum(
            "ecd,edh->ech", buf.astype(compute_dtype),
            wi.astype(compute_dtype),
        )
        h = nn.gelu(h)
        y = jnp.einsum("ech,ehd->ecd", h, wo.astype(compute_dtype))

        if n > 1:
            y = moe_alltoall_combine(y, self.axis)
        else:
            y = y.reshape(e, capacity, d)
        out = jnp.einsum("sec,ecd->sd", combine.astype(y.dtype), y)
        return out.reshape(b, t, d), aux
