"""horovod_tpu: a TPU-native distributed training framework.

Brand-new JAX/XLA re-design with the capabilities of Horovod
(reference: maxhgerlach/horovod v0.22.1, the process-sets fork): the
``hvd.*`` API surface (init/rank/size/process sets, allreduce /
allgather / broadcast / alltoall / reducescatter, DistributedOptimizer,
Adasum, compression, elastic training, timeline, autotune, launcher) —
built on ``jax.sharding.Mesh`` + ``shard_map`` + XLA collectives over
ICI/DCN instead of a background MPI/NCCL negotiation service.

Typical use (the reference MNIST pattern, ``examples/pytorch/pytorch_mnist.py``)::

    import horovod_tpu as hvd
    hvd.init()
    tx = hvd.DistributedOptimizer(optax.adam(1e-3))
    step = hvd.distributed_train_step(loss_fn, tx)
"""

from .version import __version__  # noqa: F401

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.5 ships shard_map under jax.experimental only (with the
    # replication check spelled check_rep, not check_vma); the op
    # layers target the stable jax.shard_map spelling.
    from jax.experimental.shard_map import shard_map as _xp_shard_map

    def _shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _xp_shard_map(f, *args, **kwargs)

    _jax.shard_map = _shard_map

if not hasattr(_jax.lax, "axis_size"):
    # jax < 0.6 spelling: the static named-axis size lives on
    # jax.core.axis_frame.
    _jax.lax.axis_size = lambda name: _jax.core.axis_frame(name)

if not hasattr(_jax, "typeof"):
    # jax < 0.5 has no jax.typeof; the abstract value carries the same
    # shape/dtype info (and no .vma attribute — callers that probe
    # varying-mesh-axes via getattr(..., "vma", None) see None, which is
    # correct: the vma system doesn't exist under check_rep semantics).
    _jax.typeof = lambda x: _jax.core.get_aval(x)

if not hasattr(_jax.lax, "pcast"):
    # jax < 0.5 has no lax.pcast / varying-mesh-axes marking.  Under the
    # shimmed shard_map (check_rep=False) a loop carry needs no vma
    # annotation to match device-varying step outputs, so the marking is
    # an identity.
    def _pcast(x, axes, to="varying"):
        del axes, to
        return x

    _jax.lax.pcast = _pcast

from . import runtime as _runtime
from .exceptions import (  # noqa: F401
    CheckpointCorruptionError,
    CheckpointMissingKeysError,
    FaultInjected,
    HorovodInternalError,
    HorovodTpuError,
    HostsUpdatedInterrupt,
    NotInitializedError,
    QuantizedWireError,
    RetryTimeoutError,
)
from .process_sets import ProcessSet  # noqa: F401
from .runtime import WORLD_AXIS  # noqa: F401
from . import ops  # noqa: F401
from .ops import traced  # noqa: F401
from .ops.eager import (  # noqa: F401
    Adasum,
    Average,
    Handle,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    allgather,
    allgather_async,
    allgather_v,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    grouped_allreduce,
    grouped_allreduce_async,
    join,
    poll,
    reducescatter,
    synchronize,
)
from .ops.sparse import (  # noqa: F401
    IndexedSlices,
    dense_grad_to_indexed_slices,
    sparse_allreduce,
    sparse_allreduce_eager,
)
from .ops.quantized import (  # noqa: F401
    quantized_all_gather,
    quantized_allreduce,
    quantized_allreduce_ef,
    quantized_reduce_scatter,
)

init = _runtime.init
shutdown = _runtime.shutdown
is_initialized = _runtime.is_initialized


# ---- Topology queries (reference HorovodBasics, common/basics.py:29) ----

def size() -> int:
    """Total number of ranks (TPU chips) in the world."""
    return _runtime.get_runtime().size


def rank() -> int:
    """Global rank of this process's first chip (== reference process rank
    when running one chip per process)."""
    return _runtime.get_runtime().rank


def local_rank() -> int:
    return _runtime.get_runtime().local_rank


def local_size() -> int:
    """Chips attached to this host."""
    return _runtime.get_runtime().local_size


def cross_rank() -> int:
    """Host index (reference cross communicator rank)."""
    return _runtime.get_runtime().cross_rank


def cross_size() -> int:
    return _runtime.get_runtime().cross_size


def process_rank() -> int:
    """This controller process's index (jax.process_index)."""
    return _runtime.get_runtime().process_rank


def process_count() -> int:
    return _runtime.get_runtime().process_count


def mesh():
    """The global 1-D ``jax.sharding.Mesh`` (the world communicator)."""
    return _runtime.get_runtime().mesh


def is_homogeneous() -> bool:
    """True when every host has the same number of chips (reference
    ``horovod_is_homogeneous``)."""
    rt = _runtime.get_runtime()
    return rt.size == rt.local_size * rt.cross_size


# ---- Capability flags (reference horovod_*_built / *_enabled,
# common/basics.py) — the non-TPU backends report absent ----

def mpi_enabled() -> bool:
    return False


def mpi_built() -> bool:
    return False


def mpi_threads_supported() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def xla_built() -> bool:
    return True


def tpu_enabled() -> bool:
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---- Process sets (reference common/process_sets.py) ----

def add_process_set(ranks_or_set) -> ProcessSet:
    """Register a new process set after init (requires
    HVD_TPU_DYNAMIC_PROCESS_SETS=1, mirroring the reference gate)."""
    ps = ranks_or_set if isinstance(ranks_or_set, ProcessSet) else ProcessSet(ranks_or_set)
    return _runtime.get_runtime().process_set_table.add(ps)


def remove_process_set(ps: ProcessSet) -> None:
    _runtime.get_runtime().process_set_table.remove(ps)


def get_process_set_ids():
    return _runtime.get_runtime().process_set_table.ids()


def global_process_set() -> ProcessSet:
    return _runtime.get_runtime().process_set_table.global_set


# ---- Optimizer / functions (populated by submodules) ----
from .optim import (  # noqa: F401,E402
    DistributedAdasumOptimizer,
    DistributedOptimizer,
    distributed_train_step,
    fsdp_train_step,
    zero_train_step,
)
from .functions import (  # noqa: F401,E402
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
    broadcast_variables,
)
from . import compression  # noqa: F401,E402
from .compression import Compression  # noqa: F401,E402
from . import sched  # noqa: F401,E402
from .sched import SchedConfig  # noqa: F401,E402
from . import topo  # noqa: F401,E402
from . import xir  # noqa: F401,E402
from . import svc  # noqa: F401,E402
from . import trace  # noqa: F401,E402
from . import elastic  # noqa: F401,E402
from .sync_batch_norm import SyncBatchNorm  # noqa: F401,E402
from . import metrics  # noqa: F401,E402
from .metrics import (  # noqa: F401,E402
    get_counter,
    get_counters,
    get_gauge,
    get_histogram,
    inc_counter,
    metric_average,
    observe,
    render_prometheus,
    reset_counters,
    set_gauge,
)
from . import events  # noqa: F401,E402
from . import faults  # noqa: F401,E402
from .utils.retry import RetryPolicy  # noqa: F401,E402
from .utils.timeline import (  # noqa: F401,E402
    merge_timeline_files,
    profile_bucket_step,
    start_timeline,
    stop_timeline,
)
from . import callbacks  # noqa: F401,E402
from . import data  # noqa: F401,E402
from . import checkpoint  # noqa: F401,E402
from .checkpoint import (  # noqa: F401,E402
    latest_good_step,
    load_checkpoint,
    load_params,
    restore_or_init,
    save_checkpoint,
    verify_checkpoint,
)
from . import serve  # noqa: F401,E402
