"""Training-loop callbacks.

TPU-native re-design of the reference Keras callbacks
(``horovod/_keras/callbacks.py:23-178``): ``BroadcastGlobalVariablesCallback``,
``MetricAverageCallback``, ``LearningRateScheduleCallback`` and
``LearningRateWarmupCallback``.  The reference mutates
``model.optimizer.lr`` through Keras backend setters; here callbacks are
framework-agnostic hooks over a small :class:`TrainingLoop` context, and
the learning-rate callbacks drive a host-side ``lr_multiplier`` scalar
that the jitted step consumes as an ordinary argument — no recompilation
when it changes.

For fully-traced schedules (no host involvement at all), use
:func:`warmup_schedule`, the optax-native equivalent of
``LearningRateWarmupCallback`` + the linear-scaling rule.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from . import functions, metrics
from .utils.logging import get_logger

log = get_logger()


class TrainingLoop:
    """Minimal mutable context shared between a training loop and callbacks.

    Attributes:
      params: current model pytree (callbacks may replace it).
      lr_multiplier: host-side scalar the step function should multiply
        into its base learning rate each step.
      epoch / batch: positions maintained by the loop driver.
      logs: most recent metrics dict (epoch-end callbacks may rewrite it).
    """

    def __init__(self, params: Any = None, lr_multiplier: float = 1.0):
        self.params = params
        self.lr_multiplier = lr_multiplier
        self.epoch = 0
        self.batch = 0
        self.logs: Dict[str, Any] = {}


class Callback:
    """Hook points mirror Keras callback structure (reference base class)."""

    def on_train_begin(self, loop: TrainingLoop) -> None:  # noqa: D102
        pass

    def on_epoch_begin(self, loop: TrainingLoop) -> None:  # noqa: D102
        pass

    def on_batch_begin(self, loop: TrainingLoop) -> None:  # noqa: D102
        pass

    def on_batch_end(self, loop: TrainingLoop) -> None:  # noqa: D102
        pass

    def on_epoch_end(self, loop: TrainingLoop) -> None:  # noqa: D102
        pass

    def on_train_end(self, loop: TrainingLoop) -> None:  # noqa: D102
        pass


class CallbackList(Callback):
    def __init__(self, callbacks: Sequence[Callback]):
        self.callbacks = list(callbacks)

    def _fire(self, hook: str, loop: TrainingLoop) -> None:
        for cb in self.callbacks:
            getattr(cb, hook)(loop)

    def on_train_begin(self, loop):
        self._fire("on_train_begin", loop)

    def on_epoch_begin(self, loop):
        self._fire("on_epoch_begin", loop)

    def on_batch_begin(self, loop):
        self._fire("on_batch_begin", loop)

    def on_batch_end(self, loop):
        self._fire("on_batch_end", loop)

    def on_epoch_end(self, loop):
        self._fire("on_epoch_end", loop)

    def on_train_end(self, loop):
        self._fire("on_train_end", loop)


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast ``loop.params`` from ``root_rank`` at train begin.

    Reference: ``_keras/callbacks.py:23-46`` — ensures consistent
    initialization across ranks before the first step.
    """

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, loop: TrainingLoop) -> None:
        if loop.params is not None:
            loop.params = functions.broadcast_parameters(
                loop.params, root_rank=self.root_rank
            )


class MetricAverageCallback(Callback):
    """Allreduce-average epoch metrics so all ranks log the same numbers.

    Reference: ``_keras/callbacks.py:49-78``.
    """

    def on_epoch_end(self, loop: TrainingLoop) -> None:
        if loop.logs:
            loop.logs = metrics.metric_average(loop.logs)


class LearningRateScheduleCallback(Callback):
    """Multiply the base LR by ``multiplier(epoch)`` over an epoch range.

    Reference: ``_keras/callbacks.py:81-145``.  ``multiplier`` is either a
    constant or a callable of the (possibly fractional, when
    ``staircase=False`` and ``steps_per_epoch`` is known) epoch index.
    """

    def __init__(
        self,
        multiplier,
        start_epoch: int = 0,
        end_epoch: Optional[int] = None,
        staircase: bool = True,
        steps_per_epoch: Optional[int] = None,
    ):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _in_range(self, epoch: int) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _apply(self, loop: TrainingLoop, epoch: float) -> None:
        loop.lr_multiplier = float(self.multiplier(epoch))

    def on_epoch_begin(self, loop: TrainingLoop) -> None:
        if self.staircase and self._in_range(loop.epoch):
            self._apply(loop, loop.epoch)

    def on_batch_begin(self, loop: TrainingLoop) -> None:
        if self.staircase or not self._in_range(loop.epoch):
            return
        if self.steps_per_epoch is None:
            raise ValueError(
                "staircase=False requires steps_per_epoch (the reference "
                "derives it from the first epoch; pass it explicitly here)"
            )
        self._apply(loop, loop.epoch + float(loop.batch) / self.steps_per_epoch)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual per-batch warmup from ``initial_lr/size`` to ``initial_lr``.

    Reference: ``_keras/callbacks.py:148-178`` — implements the
    "Accurate, Large Minibatch SGD" warmup: epoch 0 starts at 1/size of
    the scaled LR and ramps linearly over ``warmup_epochs``.
    """

    def __init__(
        self,
        warmup_epochs: int = 5,
        momentum_correction: bool = True,  # kept for API parity; momentum
        # correction is handled inside DistributedOptimizer's update.
        steps_per_epoch: Optional[int] = None,
        verbose: bool = False,
        size: Optional[int] = None,
    ):
        if size is None:
            from . import runtime

            size = runtime.get_runtime().size
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        self._size = size

        def multiplier(epoch: float) -> float:
            if warmup_epochs <= 0:
                return 1.0
            frac = min(epoch / warmup_epochs, 1.0)
            # 1/size → 1.0, linear in fractional epochs.
            return 1.0 / size + (1.0 - 1.0 / size) * frac

        super().__init__(
            multiplier,
            start_epoch=0,
            end_epoch=warmup_epochs + 1,
            staircase=False,
            steps_per_epoch=steps_per_epoch,
        )

    def on_epoch_end(self, loop: TrainingLoop) -> None:
        if self.verbose and loop.epoch == self.warmup_epochs:
            log.info(
                "Epoch %d: finished gradual learning rate warmup to full scale.",
                loop.epoch,
            )


def warmup_schedule(
    base_lr: float,
    warmup_epochs: int,
    steps_per_epoch: int,
    size: Optional[int] = None,
    staircase: bool = False,
) -> Callable[[Any], Any]:
    """Optax-native schedule: linear-scaling rule + gradual warmup.

    Fully traced (the returned callable takes the step count inside jit),
    so unlike the callback variants there is zero host involvement.
    Returns ``base_lr * size`` after ``warmup_epochs``, ramping from
    ``base_lr`` at step 0.
    """
    if size is None:
        from . import runtime

        size = runtime.get_runtime().size

    import jax.numpy as jnp

    scaled = base_lr * size
    warmup_steps = max(warmup_epochs * steps_per_epoch, 1)

    def schedule(count):
        t = jnp.asarray(count, jnp.float32)
        if staircase:
            t = jnp.floor(t / steps_per_epoch) * steps_per_epoch
        frac = jnp.minimum(t / warmup_steps, 1.0)
        return base_lr + (scaled - base_lr) * frac

    return schedule
