"""Structured JSONL elastic event log.

The reference's elastic story is reconstructed from interleaved stderr
(driver warnings, worker tracebacks); a fault-injected run leaves no
machine-readable record of *what happened in what order*.  This module
gives every lifecycle transition a structured event — discovery change,
blacklist/unblacklist, round start/end, worker crash-vs-hang verdict,
round-watchdog timeout, checkpoint corruption fallback — appended as
one JSON object per line to the file named by
``HVD_TPU_ELASTIC_EVENT_LOG`` (``HOROVOD_`` prefix accepted, like every
knob in ``utils/env.py``).

Each event carries **both clocks**:

* ``wall_ts`` — ``time.time()``, merges across processes/hosts (the
  same epoch base the mergeable timeline uses), and
* ``mono_ts`` — ``time.monotonic()``, orders events *within* a process
  immune to NTP steps,

plus ``pid``/``hostname``/``rank`` provenance, so a fault-injection run
(``HVD_TPU_FAULT_PLAN``, PR 1) produces a replayable postmortem record:
``read_events(path)`` returns the injected failure sequence in order.

Writes are single ``write()`` calls on an append-mode handle, so
driver and worker processes may share one log path (POSIX appends of
one line interleave whole, not torn).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from .utils import env as hvd_env
from .utils.logging import get_logger

# Known event names (the schema's ``event`` field; emitters may add
# more — the registry is open like the fault-injection sites).
DISCOVERY_CHANGE = "discovery_change"
BLACKLIST = "blacklist"
UNBLACKLIST = "unblacklist"
ROUND_START = "round_start"
ROUND_END = "round_end"
RESTART = "restart"
WORKER_CRASH = "worker_crash"
WORKER_HANG = "worker_hang"
WATCHDOG_TIMEOUT = "watchdog_timeout"
SPAWN_FAILED = "spawn_failed"
CHECKPOINT_CORRUPT = "checkpoint_corrupt"
CHECKPOINT_FALLBACK = "checkpoint_fallback"
# In-process remesh lifecycle (elastic/remesh.py): a remesh attempt
# emits START, one PHASE entry per pipeline phase (pause/snapshot/
# publish/barrier/reinit/fetch/rebuild), then OK — or FALLBACK with the
# failing phase when it degrades to the checkpoint-restore restart
# path, or ABORT when the driver cancels the attempt.
REMESH_START = "remesh_start"
REMESH_PHASE = "remesh_phase"
REMESH_OK = "remesh_ok"
REMESH_FALLBACK = "remesh_fallback"
REMESH_ABORT = "remesh_abort"
# Exchange tracing (trace/): the flight recorder dumped its ring
# (reason = slow_step / fault:<site> / remesh / svc_death), and the
# async service's negotiation stall check named missing participants.
TRACE_ANOMALY = "trace_anomaly"
SVC_STALL = "svc_stall"
# Stall escalation (svc/negotiate.py): after HVD_TPU_STALL_ABANDON
# consecutive stalled check intervals the entry is abandoned and every
# posted participant's future resolves inline.
SVC_STALL_ABANDON = "svc_stall_abandon"
# Arbiter admission telemetry (svc/arbiter.py): an admission wait
# expired (the submission was admitted anyway — backpressure never
# wedges), and a preemption gate lifted (reason = expired | drained) —
# the event-log entries the /slo remediation history attributes rung
# (a) actions against.
SVC_ADMIT_TIMEOUT = "svc_admit_timeout"
SVC_PREEMPT_EXPIRED = "svc_preempt_expired"
# SLO watchdog (runner/slo.py): a tenant's target stayed breached for
# HVD_TPU_SLO_WINDOWS consecutive evaluation windows (BREACH), or a
# confirmed breach's metric went green again (RECOVERED).
SLO_BREACH = "slo_breach"
SLO_RECOVERED = "slo_recovered"
# Remediation lifecycle (elastic/remediate.py): an escalation-ladder
# action emits START, one PHASE entry per executed phase (plan /
# preempt / degrade / handoff / rollback), then OK — or ABORT with
# ``stable`` telling whether the rollback restored the pre-handoff
# placement (stable=False escalates to the respawn path).
REMEDIATE_START = "remediate_start"
REMEDIATE_PHASE = "remediate_phase"
REMEDIATE_OK = "remediate_ok"
REMEDIATE_ABORT = "remediate_abort"
# SLO recovery re-armed a tenant's ladder and restored the env knobs
# its degrade rung(s) had flipped (Remediator.reset).
REMEDIATE_REVERT = "remediate_revert"
# Perf-regression sentinel (prof/baseline.py): observed step p50 or
# MFU degraded past HVD_TPU_PROF_REGRESS_FACTOR against the persisted
# baseline for this (workload signature, topology, knob fingerprint).
PROF_REGRESSION = "prof_regression"


class EventLog:
    """Append-only JSONL writer; one line per event, flushed per line
    so a crashed process never leaves a torn tail beyond its last
    complete event."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1)
        self._hostname = socket.gethostname()
        self._seq = 0

    def emit(self, event: str, **fields: Any) -> Dict[str, Any]:
        record = {
            "event": event,
            "wall_ts": time.time(),
            "mono_ts": time.monotonic(),
            "pid": os.getpid(),
            "hostname": self._hostname,
            "rank": int(os.environ.get("HVD_TPU_CROSS_RANK", -1)),
        }
        record.update(fields)
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            try:
                self._fh.write(json.dumps(record, default=str) + "\n")
            except ValueError:
                pass  # closed under us during interpreter teardown
        return record

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except Exception:
                pass


_active: Optional[EventLog] = None
_active_loaded = False
_lock = threading.Lock()

ELASTIC_EVENT_LOG = "ELASTIC_EVENT_LOG"


def get_event_log() -> Optional[EventLog]:
    """The process-wide log: installed via :func:`set_event_log`, else
    opened once from ``HVD_TPU_ELASTIC_EVENT_LOG``.  None (the default)
    makes :func:`emit` a no-op."""
    global _active, _active_loaded
    with _lock:
        if not _active_loaded:
            path = hvd_env.get_env(ELASTIC_EVENT_LOG)
            if path:
                try:
                    _active = EventLog(path)
                except OSError as e:
                    get_logger().warning(
                        "cannot open elastic event log %s: %s", path, e
                    )
                    _active = None
            _active_loaded = True
        return _active


def set_event_log(log: Optional[EventLog]) -> Optional[EventLog]:
    """Install (or, with None, disable) the process-wide log — tests
    use this instead of mutating the environment."""
    global _active, _active_loaded
    with _lock:
        if _active is not None and _active is not log:
            _active.close()
        _active = log
        _active_loaded = True
        return _active


def reset() -> None:
    """Forget the installed log; the next :func:`emit` re-reads the
    environment."""
    global _active, _active_loaded
    with _lock:
        if _active is not None:
            _active.close()
        _active = None
        _active_loaded = False


def emit(event: str, **fields: Any) -> None:
    """Emit one structured event to the active log (no-op when no log
    is configured).  Never raises — observability must not take down
    the path it observes."""
    log = get_event_log()
    if log is None:
        return
    try:
        log.emit(event, **fields)
    except Exception as e:  # pragma: no cover - defensive
        get_logger().warning("elastic event emit failed: %s", e)


def read_events(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL event log back into a list of event dicts,
    skipping any torn final line (a crashed writer's last partial
    write) — the postmortem reader."""
    out: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out
