from .state import ArrayState, ObjectState, State, TpuState  # noqa: F401
from .run import run, run_fn  # noqa: F401
from .remesh import reinit_world  # noqa: F401
from .framework_states import (  # noqa: F401
    TensorFlowKerasState,
    TorchState,
)
