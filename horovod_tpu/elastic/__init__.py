from .state import ArrayState, ObjectState, State, TpuState  # noqa: F401
from .run import run, run_fn  # noqa: F401
from .remesh import (  # noqa: F401
    KVShardStore,
    Move,
    RemeshPlan,
    RemeshRequest,
    ShardLayout,
    ShardedZeroState,
    apply_moves,
    join_remesh,
    plan_moves,
    plan_reshard,
    reinit_world,
    reshard_bucket_state,
    run_remesh,
)
from .framework_states import (  # noqa: F401
    TensorFlowKerasState,
    TorchState,
)
