"""Elastic retry loop (reference ``horovod/common/elastic.py:151``
``run_fn`` and the per-framework ``hvd.elastic.run`` decorators).

``run(func)`` wraps a training function taking a ``State`` first
argument.  On ``HorovodInternalError`` (a peer died mid-collective) the
state is restored from the last commit and the mesh re-initialized; on
``HostsUpdatedInterrupt`` (membership changed without failure) training
continues from live state after a re-sync.
"""

from __future__ import annotations

import functools
from typing import Callable

from .. import runtime
from ..exceptions import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
    RemeshError,
    RemeshInterrupt,
)
from ..utils.logging import get_logger
from .state import State


def run_fn(func: Callable, reset: Callable) -> Callable:
    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        notification_manager = _get_notification_manager()
        elastic_job = notification_manager is not None
        if elastic_job:
            notification_manager.init()
            notification_manager.register_listener(state)
            _maybe_join_remesh(state, notification_manager)
        skip_sync = False
        try:
            while True:
                if not skip_sync:
                    state.sync()
                try:
                    return func(state, *args, **kwargs)
                except HorovodInternalError:
                    get_logger().warning(
                        "collective failure; restoring committed state"
                    )
                    if elastic_job:
                        # TPU elastic restarts the process: the committed
                        # state is already persisted in the launcher KV
                        # store.  Exit with RESTART_CODE — this worker is
                        # a *survivor* observing a peer failure, and must
                        # not be blacklisted as the faulty host (the dead
                        # worker's own non-zero exit marks its host).
                        _exit_for_restart(_RESTART_CODE)
                    state.restore()
                    skip_sync = False
                except RemeshInterrupt as e:
                    # Zero-downtime path: reshard live state in place
                    # and keep training — any failure degrades to the
                    # checkpoint-restore restart round below
                    # (docs/fault_tolerance.md).
                    if elastic_job and e.request is not None:
                        from . import remesh as _remesh

                        try:
                            _remesh.run_remesh(
                                state, notification_manager, e.request
                            )
                        except SystemExit as shed:
                            # shed rank: clean departure, state already
                            # handed off through the KV store
                            _exit_for_restart(int(shed.code or 0))
                        except RemeshError as err:
                            get_logger().warning(
                                "remesh failed (%s); falling back to "
                                "checkpoint-restore restart", err,
                            )
                            _exit_for_restart(_RESTART_CODE)
                        # Success: the world is re-initialized; clear
                        # stale compiled state and rebuild via the
                        # user's reset callbacks, then re-sync over the
                        # new mesh (joiners adopt rank 0's replicated
                        # attrs there).
                        state.on_reset()
                        skip_sync = False
                        continue
                    # Non-elastic (or malformed request): behave like a
                    # plain membership change.
                    get_logger().info("hosts updated; re-initializing")
                    skip_sync = e.skip_sync
                except HostsUpdatedInterrupt as e:
                    get_logger().info("hosts updated; re-initializing")
                    if elastic_job:
                        # commit() persisted the snapshot just before
                        # raising; nothing further to save here.
                        _exit_for_restart(_RESTART_CODE)
                    skip_sync = e.skip_sync
                reset()
                state.on_reset()
        finally:
            if elastic_job:
                notification_manager.remove_listener(state)

    return wrapper


def _maybe_join_remesh(state: State, manager) -> None:
    """A worker spawned to JOIN an in-flight remesh
    (``HVD_TPU_REMESH_JOIN`` in its env) fetches its shard of the
    exchanged state from the KV store before the first sync; replicated
    attributes arrive through the normal ``sync()`` broadcast.  Any
    failure exits for a restart round — the joiner has no state to
    lose."""
    try:
        request = manager.remesh_join_request()
    except Exception:
        request = None
    if request is None:
        return
    from . import remesh as _remesh

    try:
        _remesh.join_remesh(state, manager, request)
    except RemeshError as err:
        get_logger().warning(
            "remesh join failed (%s); exiting for a restart round", err
        )
        _exit_for_restart(_RESTART_CODE)


_RESTART_CODE = 73  # runner/elastic_driver.py RESTART_CODE


def _exit_for_restart(code: int) -> None:
    import os
    import sys

    # Quiesce the async exchange service first: in-flight DCN hops
    # resolve (or fall back inline) so no producer thread is mid-submit
    # when the process dies — a restart round must never orphan a
    # future another thread will block on during interpreter teardown.
    try:
        from .. import svc as _svc

        _svc.drain(timeout_s=5.0)
        _svc.reset_service()
    except Exception:  # the exit path must never wedge on the service
        pass
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)  # skip atexit: the mesh may be wedged on a dead peer


def _default_reset() -> None:
    """Full re-initialization: tear down the runtime (dropping compiled
    collectives for the old mesh) and re-init against the (possibly
    changed) device world — the analog of the reference's
    ``hvd.shutdown(); hvd.init()`` in ``tensorflow/elastic.py:64``.
    ``runtime.shutdown`` also restarts the exchange service, whose
    cached executors were compiled against the old mesh."""
    runtime.shutdown()
    runtime.init()


def _get_notification_manager():
    """Worker-side host-update listener, registered by the elastic
    launcher (reference ``runner/elastic/worker.py``); None outside an
    elastic job."""
    try:
        from ..runner.elastic_worker import get_notification_manager

        return get_notification_manager()
    except Exception:
        return None


def run(func: Callable) -> Callable:
    """Decorator: ``@hvd.elastic.run`` (reference per-framework
    ``elastic.run``)."""
    return run_fn(func, _default_reset)
