"""Framework-flavored elastic states.

Reference: ``horovod/torch/elastic/state.py`` (``TorchState`` — model /
optimizer handlers over ``ObjectState``) and
``horovod/tensorflow/elastic.py`` (``TensorFlowKerasState``).  These
wrap live framework objects: ``save()`` snapshots their state dicts to
host memory, ``restore()`` loads the snapshot back, ``sync()``
broadcasts from rank 0 through the object-broadcast path the interop
bridges use.  Arbitrary extra attributes (epoch, batch, samplers) ride
along with :class:`~horovod_tpu.elastic.state.ObjectState` semantics.
"""

from __future__ import annotations

import copy
from typing import Any, Dict

from .. import functions
from .state import ObjectState


class TorchState(ObjectState):
    """Elastic state around a torch model/optimizer (reference
    ``torch/elastic/state.py:27``: ``TorchState(model=..., optimizer=...,
    epoch=0, batch=0)``)."""

    def __init__(self, model=None, optimizer=None, **kwargs):
        import torch  # noqa: F401  (fail fast with a clear error)

        self.model = model
        self.optimizer = optimizer
        self._model_snapshot = None
        self._opt_snapshot = None
        super().__init__(**kwargs)
        self.save()

    # -- handlers (reference ModelStateHandler / OptimizerStateHandler) --
    def _snap(self):
        model = (copy.deepcopy(self.model.state_dict())
                 if self.model is not None else None)
        opt = (copy.deepcopy(self.optimizer.state_dict())
               if self.optimizer is not None else None)
        return model, opt

    def save(self) -> None:
        super().save()
        self._model_snapshot, self._opt_snapshot = self._snap()

    def restore(self) -> None:
        super().restore()
        if self.model is not None and self._model_snapshot is not None:
            self.model.load_state_dict(self._model_snapshot)
        if self.optimizer is not None and self._opt_snapshot is not None:
            self.optimizer.load_state_dict(self._opt_snapshot)

    def sync(self) -> None:
        from ..interop import torch as hvd_torch

        if not self._saved_state:
            # no plain attributes: ObjectState.sync would skip entirely,
            # including persisted-snapshot adoption
            self._load_persisted()
        super().sync()  # plain attributes broadcast + persisted adopt
        if self.model is not None:
            hvd_torch.broadcast_parameters(
                self.model.state_dict(), root_rank=0
            )
        if self.optimizer is not None:
            hvd_torch.broadcast_optimizer_state(
                self.optimizer, root_rank=0
            )
        self._model_snapshot, self._opt_snapshot = self._snap()

    # Cross-round persistence: ship the state dicts as host tensors.
    def _serialize(self):
        import pickle

        import torch

        from ..interop.torch import _tensor_to_numpy

        model, opt = self._snap()
        wire_model = (
            {k: _tensor_to_numpy(torch, v) if torch.is_tensor(v) else v
             for k, v in model.items()} if model is not None else None
        )
        return pickle.dumps(
            {"attrs": self._saved_state, "model": wire_model, "opt": opt}
        )

    def _deserialize(self, blob) -> bool:
        import pickle

        import torch

        from ..interop.torch import _to_torch

        try:
            saved = pickle.loads(blob)
        except Exception:
            return False
        if not isinstance(saved, dict) or "attrs" not in saved:
            return False
        if set(saved["attrs"]) != set(self._saved_state):
            return False
        # Load framework state FIRST (with rollback) so a failure never
        # leaves half-adopted state; attrs mutate only after success.
        pre_model, pre_opt = self._snap()
        try:
            if self.model is not None and saved.get("model") is not None:
                self.model.load_state_dict({
                    k: _to_torch(v, None) if not torch.is_tensor(v) else v
                    for k, v in saved["model"].items()
                })
            if self.optimizer is not None and saved.get("opt") is not None:
                self.optimizer.load_state_dict(saved["opt"])
        except Exception:
            if self.model is not None and pre_model is not None:
                self.model.load_state_dict(pre_model)
            if self.optimizer is not None and pre_opt is not None:
                self.optimizer.load_state_dict(pre_opt)
            return False
        self._saved_state.update(saved["attrs"])
        for k, v in saved["attrs"].items():
            setattr(self, k, v)
        return True


class TensorFlowKerasState(ObjectState):
    """Elastic state around a keras model/optimizer (reference
    ``tensorflow/elastic.py`` ``TensorFlowKerasState(model, optimizer,
    batch=0, epoch=0)``)."""

    def __init__(self, model=None, optimizer=None, **kwargs):
        import tensorflow  # noqa: F401  (fail fast with a clear error)

        self.model = model
        self.optimizer = optimizer
        self._weights_snapshot = None
        self._opt_snapshot = None
        super().__init__(**kwargs)
        self.save()

    def _snap(self):
        weights = (self.model.get_weights()
                   if self.model is not None else None)
        opt = ([v.numpy() for v in self.optimizer.variables]
               if self.optimizer is not None else None)
        return weights, opt

    def _load(self, weights, opt) -> None:
        if self.model is not None and weights is not None:
            self.model.set_weights(weights)
        if self.optimizer is not None and opt is not None:
            for var, val in zip(self.optimizer.variables, opt):
                var.assign(val)

    def save(self) -> None:
        super().save()
        self._weights_snapshot, self._opt_snapshot = self._snap()

    def restore(self) -> None:
        super().restore()
        self._load(self._weights_snapshot, self._opt_snapshot)

    def sync(self) -> None:
        if not self._saved_state:
            self._load_persisted()
        super().sync()
        weights, opt = self._snap()
        synced = functions.broadcast_object(
            {"weights": weights, "opt": opt}, root_rank=0
        )
        self._load(synced["weights"], synced["opt"])
        self._weights_snapshot, self._opt_snapshot = self._snap()

    def _serialize(self):
        import pickle

        weights, opt = self._snap()
        return pickle.dumps(
            {"attrs": self._saved_state, "weights": weights, "opt": opt}
        )

    def _deserialize(self, blob) -> bool:
        import pickle

        try:
            saved = pickle.loads(blob)
        except Exception:
            return False
        if not isinstance(saved, dict) or "attrs" not in saved:
            return False
        if set(saved["attrs"]) != set(self._saved_state):
            return False
        # Framework state first (with rollback); attrs only on success.
        pre_weights, pre_opt = self._snap()
        try:
            self._load(saved.get("weights"), saved.get("opt"))
        except Exception:
            try:
                self._load(pre_weights, pre_opt)
            except Exception:
                pass
            return False
        self._saved_state.update(saved["attrs"])
        for k, v in saved["attrs"].items():
            setattr(self, k, v)
        return True
