"""SLO remediation policy: the escalation ladder, executed safely.

The acting half of the self-healing control plane (the sensing half is
``runner/slo.py``).  A confirmed SLO breach picks a rung from an
escalation ladder, cheapest first:

``preempt``
    arbiter reweight/preempt (:meth:`~horovod_tpu.svc.arbiter.Arbiter.
    request_preempt`): gate lower-priority lanes so the breaching
    tenant's backlog drains first — bounded, reversible, no state
    moves;
``degrade``
    degraded mode: bump ``HVD_TPU_SVC_STALENESS`` (hide the sick DCN
    rail behind more steps of the bounded-staleness pipeline) and
    downgrade hier→flat lowering (``HVD_TPU_TOPO_LOWER=flat``) so
    cross-slice staging stops touching the slow rail;
``handoff``
    slice handoff: shrink a donor tenant at a commit boundary, reshard
    its state through the PR 6 remesh pipeline
    (:func:`~horovod_tpu.elastic.remesh.reshard_shards` — the same
    ``plan_moves``/``apply_moves`` math, so the exchange is a
    permutation with checksums preserved), grow the breaching tenant.
    **No restarts** — the move happens inside the running processes.

Every rung runs under a :class:`~horovod_tpu.utils.retry.RetryPolicy`
(per-phase timeout ``HVD_TPU_REMEDIATE_TIMEOUT``, exponential backoff,
``HVD_TPU_REMEDIATE_RETRIES`` attempts), counts ``slo.*`` metrics, and
emits ``remediate_start``/``remediate_phase``/``remediate_ok``/
``remediate_abort`` event-log entries.  Fault sites (``faults.py``):
``remediate.plan`` fires while the action is planned (nothing changed
yet), ``remediate.handoff`` inside the handoff execution, and
``remediate.rollback`` inside the rollback.  The abort contract
extends PR 6's: any fault mid-handoff rolls the placement back to the
pre-handoff state and dumps the flight recorder; only a fault in the
*rollback itself* leaves ``stable=False`` in the abort record — the
caller's signal to fall back to the respawn path.  A tenant's ladder
escalates only while its breach persists past ``HVD_TPU_SLO_COOLDOWN``
seconds per rung, and re-arms from the cheapest rung on
:meth:`Remediator.reset` — which the SLO controller calls on the
breach→recovered transition, and which also *reverts degraded mode*:
every knob the tenant's degrade rung(s) flipped is restored to its
pre-degrade value (a breach/recover cycle is a round trip, not a
ratchet), locally and — through the optional ``undegrade`` actuator —
on every worker.

See docs/fault_tolerance.md (remediation ladder) and
docs/multitenant.md (SLO specs + ``/slo``).
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import events, faults, metrics
from ..exceptions import HorovodTpuError
from ..utils import env
from ..utils.logging import get_logger
from ..utils.retry import RetryPolicy

DEFAULT_COOLDOWN_S = 30.0
DEFAULT_TIMEOUT_S = 30.0
DEFAULT_RETRIES = 2

# The escalation ladder, cheapest rung first.
RUNGS = ("preempt", "degrade", "handoff")


class RemediationError(HorovodTpuError):
    """A remediation rung failed (after retries)."""


def cooldown_s() -> float:
    """``HVD_TPU_SLO_COOLDOWN``: seconds a tenant's ladder holds at a
    rung before a still-confirmed breach escalates (default 30)."""
    return max(0.0, env.get_float(env.SLO_COOLDOWN, DEFAULT_COOLDOWN_S))


def phase_timeout_s() -> float:
    return max(0.1, env.get_float(env.REMEDIATE_TIMEOUT,
                                  DEFAULT_TIMEOUT_S))


def phase_retries() -> int:
    return max(1, env.get_int(env.REMEDIATE_RETRIES, DEFAULT_RETRIES))


# ------------------------------------------------------------ placement


def plan_handoff(placement: Dict[str, int], donor: str, recipient: str,
                 slices: int = 1) -> Dict[str, int]:
    """The handoff plan: move ``slices`` from donor to recipient.  Pure
    — validation errors raise :class:`RemediationError` before anything
    changed (the abort-before-mutation half of the contract)."""
    if donor == recipient:
        raise RemediationError("handoff donor == recipient "
                               f"({donor!r})")
    have = placement.get(donor, 0)
    if have - slices < 1:
        raise RemediationError(
            f"donor {donor!r} has {have} slice(s); moving {slices} "
            "would starve it (donors keep >= 1)"
        )
    out = dict(placement)
    out[donor] = have - slices
    out[recipient] = out.get(recipient, 0) + slices
    return out


def pick_donor(placement: Dict[str, int],
               recipient: str) -> Optional[str]:
    """The donor policy: the tenant holding the most slices (ties by
    name) that can spare one; None when nobody can."""
    candidates = [
        (count, name) for name, count in placement.items()
        if name != recipient and count >= 2
    ]
    if not candidates:
        return None
    candidates.sort(key=lambda c: (-c[0], c[1]))
    return candidates[0][1]


# ---------------------------------------------------- default actuators


def _default_preempt(tenant: str, breach: Dict[str, Any]) -> None:
    """Rung (a) against the in-process exchange service; a world with
    no service has nothing to preempt — the rung fails and the ladder
    escalates."""
    from ..svc import service as service_mod

    svc = service_mod.get_service_or_none()
    if svc is None:
        raise RemediationError(
            "no in-process exchange service to preempt through"
        )
    svc.arbiter.request_preempt(tenant)


def _default_degrade(tenant: str,
                     breach: Dict[str, Any]) -> Dict[str, str]:
    """Rung (b): bump the bounded-staleness depth one step (hide the
    sick DCN rail behind one more step of the PR 12 pipeline) and pin
    the lowering to flat (stop staging through the slow rail).
    Returns the knob changes so the record — and an operator — can see
    exactly what degraded mode means here."""
    old = max(0, env.get_int(env.SVC_STALENESS, 0))
    changes = {
        env.SVC_STALENESS: str(old + 1),
        env.TOPO_LOWER: "flat",
    }
    for name, value in changes.items():
        env.set_env(name, value)
    return {f"HVD_TPU_{k}": v for k, v in changes.items()}


# ------------------------------------------------------------ remediator


class Remediator:
    """Executes the escalation ladder over a tenant→slice placement.

    ``actuators`` plugs the environment in: ``preempt(tenant, breach)``,
    ``degrade(tenant, breach) -> changes``, ``handoff(old_placement,
    new_placement, breach)`` and ``rollback(old_placement,
    new_placement, breach)`` — the elastic driver wires KV-backed ones,
    tests wire in-process ones that move real shard buffers through
    :func:`~horovod_tpu.elastic.remesh.reshard_shards`.  Omitted
    actuators fall back to the defaults above (handoff/rollback default
    to the placement commit itself).  ``sleep`` injects the retry
    backoff clock for tests."""

    def __init__(
        self,
        placement: Optional[Dict[str, int]] = None,
        actuators: Optional[Dict[str, Callable]] = None,
        cooldown_s_: Optional[float] = None,
        retry_timeout_s: Optional[float] = None,
        retry_attempts: Optional[int] = None,
        history_cap: int = 64,
        clock: Callable[[], float] = time.monotonic,
        sleep: Optional[Callable[[float], None]] = None,
    ):
        self._placement = dict(placement or {})
        self._actuators = dict(actuators or {})
        self.cooldown_s = (cooldown_s() if cooldown_s_ is None
                           else max(0.0, cooldown_s_))
        self._timeout_s = (phase_timeout_s() if retry_timeout_s is None
                           else retry_timeout_s)
        self._attempts = (phase_retries() if retry_attempts is None
                          else max(1, retry_attempts))
        self._sleep = sleep
        self._clock = clock
        self._lock = threading.Lock()
        self._rung_idx: Dict[str, int] = {}
        self._last_action: Dict[str, float] = {}
        # tenant -> {env name -> pre-degrade value (None = was unset)}:
        # what reset() restores. First degrade wins per knob, so
        # repeated degrades still revert to the ORIGINAL values.
        self._degrade_undo: Dict[str, Dict[str, Optional[str]]] = {}
        self._history: collections.deque = collections.deque(
            maxlen=max(1, history_cap)
        )

    # ----------------------------------------------------------- state

    def placement(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._placement)

    def set_placement(self, placement: Dict[str, int]) -> None:
        with self._lock:
            self._placement = dict(placement)

    def history(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._history)

    def reset(self, tenant: Optional[str] = None) -> None:
        """Re-arm the ladder from the cheapest rung (SLO recovered, or
        test isolation) and revert degraded mode: every env knob the
        tenant's degrade rung(s) flipped is restored to its pre-degrade
        value — locally here, and on every worker when an ``undegrade``
        actuator is wired (the elastic driver publishes the restore on
        ``__slo__/degrade``).  ``None`` resets every tenant."""
        with self._lock:
            if tenant is None:
                self._rung_idx.clear()
                self._last_action.clear()
                undos = self._degrade_undo
                self._degrade_undo = {}
            else:
                self._rung_idx.pop(tenant, None)
                self._last_action.pop(tenant, None)
                undos = {}
                undo = self._degrade_undo.pop(tenant, None)
                if undo:
                    undos[tenant] = undo
        for t, undo in undos.items():
            self._revert_degrade(t, undo)

    def _revert_degrade(self, tenant: str,
                        undo: Dict[str, Optional[str]]) -> None:
        """Restore the pre-degrade knob values (None = unset) and tell
        the workers through the ``undegrade`` actuator.  Never raises —
        reset runs on the recovery path, which must stay green."""
        for name, prior in undo.items():
            if prior is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = prior
        metrics.inc_counter("slo.degrade_reverts")
        events.emit(events.REMEDIATE_REVERT, tenant=tenant,
                    restored=dict(undo))
        get_logger().info(
            "SLO recovered: degraded mode reverted for tenant %s (%s)",
            tenant, undo,
        )
        act = self._actuators.get("undegrade")
        if act is not None:
            try:
                act(tenant, dict(undo))
            except Exception as e:
                get_logger().warning(
                    "undegrade publication failed for tenant %s: %s "
                    "(local knobs restored; workers keep degraded "
                    "values until the next publication)", tenant, e,
                )

    def _retry(self, name: str) -> RetryPolicy:
        kw: Dict[str, Any] = dict(
            max_attempts=self._attempts,
            base_delay_s=0.1, multiplier=2.0, max_delay_s=5.0,
            attempt_timeout_s=self._timeout_s,
            name=f"remediate.{name}", seed=0,
        )
        if self._sleep is not None:
            kw["sleep"] = self._sleep
        return RetryPolicy(**kw)

    @contextlib.contextmanager
    def _phase(self, record: Dict[str, Any], phase: str,
               fault_site: Optional[str] = None, **ctx: Any):
        """Instrument one remediation phase (the ``remesh_phase``
        pattern): counter, event-log entry, per-phase wall clock in the
        record — and the registered fault site, where the chaos tests
        fail any phase on demand."""
        if fault_site is not None:
            faults.inject(fault_site, **ctx)
        metrics.inc_counter(f"slo.remediate.phase.{phase}")
        events.emit(events.REMEDIATE_PHASE, phase=phase, **ctx)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            record["phases"].append({
                "phase": phase,
                "seconds": time.perf_counter() - t0,
            })
            metrics.observe("slo.remediate.phase_seconds",
                            time.perf_counter() - t0)

    # ---------------------------------------------------------- policy

    def consider(self, breach: Dict[str, Any],
                 now: Optional[float] = None
                 ) -> Optional[Dict[str, Any]]:
        """The policy gate: act on a confirmed breach unless the
        tenant's last rung is still inside its cooldown.  Each action
        advances the tenant's ladder one rung (capped at handoff), so
        a breach that persists *escalates* instead of hammering the
        cheapest rung forever."""
        tenant = breach.get("tenant") or "default"
        now = self._clock() if now is None else now
        with self._lock:
            last = self._last_action.get(tenant)
            if last is not None and now - last < self.cooldown_s:
                return None
            rung_i = min(self._rung_idx.get(tenant, 0), len(RUNGS) - 1)
            # Claim the slot before releasing the lock: concurrent
            # ticks must not double-fire a rung.
            self._last_action[tenant] = now
            self._rung_idx[tenant] = rung_i + 1
        return self.remediate(breach, RUNGS[rung_i])

    # ------------------------------------------------------- execution

    def remediate(self, breach: Dict[str, Any],
                  rung: str) -> Dict[str, Any]:
        """Execute one rung for one breach; returns the history record
        (``outcome`` = ok | abort | abort_unstable).  Never raises —
        failures land in the record and the event log, and the
        flight recorder dumps around every abort."""
        if rung not in RUNGS:
            raise ValueError(f"unknown rung {rung!r} (one of {RUNGS})")
        tenant = breach.get("tenant") or "default"
        record: Dict[str, Any] = {
            "tenant": tenant, "rung": rung,
            "kind": breach.get("kind"),
            "observed": breach.get("observed"),
            "target": breach.get("target"),
            "wall_ts": time.time(),
            "phases": [], "outcome": "ok", "error": None,
            "stable": True,
        }
        metrics.inc_counter("slo.remediations")
        metrics.inc_counter(f"slo.remediations.{rung}")
        events.emit(events.REMEDIATE_START, tenant=tenant, rung=rung,
                    kind=breach.get("kind"),
                    observed=breach.get("observed"),
                    target=breach.get("target"))
        old_placement = self.placement()
        new_placement: Optional[Dict[str, int]] = None
        handoff_started = False
        try:
            # -- plan: decide the concrete action; nothing mutates yet.
            with self._phase(record, "plan", "remediate.plan",
                             tenant=tenant, rung=rung):
                if rung == "handoff":
                    donor = breach.get("donor") or pick_donor(
                        old_placement, tenant
                    )
                    if donor is None:
                        raise RemediationError(
                            f"no donor tenant can spare a slice for "
                            f"{tenant!r} (placement {old_placement})"
                        )
                    new_placement = plan_handoff(
                        old_placement, donor, tenant,
                        slices=int(breach.get("slices", 1)),
                    )
                    record["donor"] = donor
                    record["placement_before"] = old_placement
                    record["placement_after"] = new_placement
            # -- execute the rung under its RetryPolicy.
            if rung == "preempt":
                act = self._actuators.get("preempt", _default_preempt)
                with self._phase(record, "preempt", tenant=tenant):
                    self._retry("preempt").call(act, tenant, breach)
            elif rung == "degrade":
                act = self._actuators.get("degrade", _default_degrade)
                env_before = dict(os.environ)
                with self._phase(record, "degrade", tenant=tenant):
                    record["changes"] = self._retry("degrade").call(
                        act, tenant, breach
                    ) or {}
                with self._lock:
                    # remember what each flipped knob held BEFORE the
                    # first degrade, so reset() can undo the whole
                    # ladder of bumps in one restore.
                    undo = self._degrade_undo.setdefault(tenant, {})
                    for name in record["changes"]:
                        undo.setdefault(name, env_before.get(name))
            else:  # handoff
                act = self._actuators.get("handoff")
                with self._phase(record, "handoff",
                                 tenant=tenant,
                                 donor=record.get("donor")):
                    handoff_started = True

                    def run_handoff():
                        faults.inject("remediate.handoff",
                                      tenant=tenant,
                                      donor=record.get("donor"))
                        if act is not None:
                            act(old_placement, new_placement, breach)

                    self._retry("handoff").call(run_handoff)
                self.set_placement(new_placement)
                metrics.inc_counter("slo.handoffs")
            events.emit(events.REMEDIATE_OK, tenant=tenant, rung=rung)
            metrics.inc_counter("slo.remediation_ok")
            get_logger().info(
                "SLO remediation ok: tenant %s rung %s", tenant, rung,
            )
        except Exception as e:
            record["outcome"] = "abort"
            record["error"] = str(e)
            metrics.inc_counter("slo.remediation_abort")
            from .. import trace

            trace.trigger_dump("remediate", tenant=tenant, rung=rung,
                               error=str(e))
            stable = True
            if handoff_started:
                stable = self._rollback(record, old_placement,
                                        new_placement, breach)
            record["stable"] = stable
            events.emit(events.REMEDIATE_ABORT, tenant=tenant,
                        rung=rung, error=str(e), stable=stable)
            if not stable:
                metrics.inc_counter("slo.remediation_unstable")
            get_logger().warning(
                "SLO remediation aborted: tenant %s rung %s (%s); "
                "placement %s", tenant, rung, e,
                "restored" if stable else "UNSTABLE — escalate to "
                "respawn",
            )
        with self._lock:
            self._history.append(record)
        return record

    def _rollback(self, record: Dict[str, Any],
                  old_placement: Dict[str, int],
                  new_placement: Optional[Dict[str, int]],
                  breach: Dict[str, Any]) -> bool:
        """Abort a mid-flight handoff back to the pre-handoff
        placement (the PR 6 abort contract).  True = stable (placement
        restored); False = the rollback itself failed and the caller
        must treat the placement as dirty."""
        act = self._actuators.get("rollback")
        tenant = record["tenant"]
        try:
            # The remediate.rollback site fires inside run_rollback so
            # each retry attempt re-arms it, like the handoff site.
            with self._phase(record, "rollback", tenant=tenant):

                def run_rollback():
                    faults.inject("remediate.rollback", tenant=tenant)
                    if act is not None:
                        act(old_placement, new_placement, breach)

                self._retry("rollback").call(run_rollback)
            self.set_placement(old_placement)
            metrics.inc_counter("slo.rollbacks")
            return True
        except Exception as e:
            record["rollback_error"] = str(e)
            self.set_placement(old_placement)
            return False
