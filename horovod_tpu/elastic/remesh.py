"""Experimental in-process world re-initialization.

Probe evidence (``tools/probe_remesh.py`` →
``tools/probe_remesh_findings.json``): after a full XLA backend reset
(``jax.extend.backend.clear_backends``), ``jax.distributed`` accepts a
fresh ``initialize()`` with a *different* world in the same process —
so a membership-change survivor CAN re-mesh without respawning, at
least on the CPU backend.  The elastic driver's default remains
respawn-per-round (``runner/elastic_driver.py:1-22``): the respawn path
is validated on every backend, while live-TPU PJRT client teardown via
``clear_backends`` is not, and recompilation — the dominant restart
cost — happens either way (bound it with the persistent compilation
cache, see ``tests/integration/test_elastic.py``).

Use :func:`reinit_world` from a surviving worker after the launcher
hands it the new world description; all live jax Arrays from the old
backend become invalid — restore state from host copies or the KV
store (``elastic.State`` commits are host-side for exactly this
reason).
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.logging import get_logger


def reinit_world(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Tear down the runtime + XLA backend and rejoin a new world.

    With no arguments, re-initializes single-process (the surviving
    worker continues alone on its local devices).  Passing the new
    coordination triple rejoins a resized multi-process world.

    EXPERIMENTAL: relies on ``jax.extend.backend.clear_backends``
    (internal-adjacent API).  Every jax Array created before the call
    is invalidated.
    """
    import jax

    from .. import runtime as _rt

    # Validate inputs and resolve the backend-reset entry point BEFORE
    # any teardown — failing after shutdown would strand the survivor
    # with no runtime at all.
    if coordinator_address is not None and (
        num_processes is None or process_id is None
    ):
        raise ValueError(
            "reinit_world: coordinator_address requires num_processes "
            "and process_id (a partial triple would silently fall back "
            "to a single-process world)"
        )
    reset = None
    try:
        from jax.extend import backend as _xb

        reset = getattr(_xb, "clear_backends", None)
    except ImportError:
        pass
    if reset is None:
        reset = getattr(jax, "clear_backends", None)
    if reset is None:
        raise RuntimeError(
            "reinit_world: this JAX exposes no backend-reset entry "
            "point (neither jax.extend.backend.clear_backends nor "
            "jax.clear_backends); use the respawn-per-round path"
        )

    _rt.shutdown()
    try:
        jax.distributed.shutdown()
    except Exception:  # not initialized / already down
        pass
    reset()

    # Clear BOTH env spellings the knob layer reads (utils/env.py
    # falls back from HVD_TPU_* to HOROVOD_*).
    for name in ("COORDINATOR_ADDR", "CROSS_RANK", "CROSS_SIZE"):
        os.environ.pop("HVD_TPU_" + name, None)
        os.environ.pop("HOROVOD_" + name, None)
    if coordinator_address is not None:
        os.environ["HVD_TPU_COORDINATOR_ADDR"] = coordinator_address
        os.environ["HVD_TPU_CROSS_SIZE"] = str(num_processes)
        os.environ["HVD_TPU_CROSS_RANK"] = str(process_id)
    get_logger().warning(
        "reinit_world: backend reset, rejoining world "
        "(coordinator=%s, processes=%s)",
        coordinator_address or "<single-process>", num_processes or 1,
    )
    _rt.init()
