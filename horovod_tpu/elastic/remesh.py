"""Zero-downtime elastic remesh: reshard live training state across
membership changes instead of restarting.

Built on the validated :func:`reinit_world` probe (``tools/
probe_remesh.py`` → ``tools/probe_remesh_findings.json``): after a full
XLA backend reset (``jax.extend.backend.clear_backends``),
``jax.distributed`` accepts a fresh ``initialize()`` with a *different*
world in the same process — so a membership-change survivor CAN re-mesh
without respawning.  Horovod's elastic mode (arXiv:1802.05799) survives
membership changes by tearing workers down and restoring from
checkpoint; every distributed state we hold — ZeRO-1 optimizer shards
(arXiv:2004.13336, ``sched/zero1._BucketLayout``), EF residuals
(``optim/distributed_optimizer.DistributedOptimizerState.residual``),
bucket plans (``sched/plan.py``) — has a *deterministic* per-rank
layout, so a remesh is a computable shard exchange plus a plan rebuild,
not a checkpoint round-trip.

Three layers live here:

1. **Shard math** — :class:`ShardLayout` / :func:`plan_moves` compute
   the old-layout→new-layout movement of one flat sharded buffer as a
   deterministic interval exchange (a partition of the valid elements:
   every byte moves exactly once, verified by the layout-exchange unit
   tests).  :func:`plan_reshard` lifts that to whole bucket schedules
   (``sched/zero1.bucket_layouts``), validating that old and new plans
   agree on bucket membership (they must — the plan is a pure function
   of gradient metadata, not of world size).
2. **State movement** — :class:`KVShardStore` ships host shard blobs
   through the launcher KV store (chunked + sha256-checksummed, the
   general case covering disjoint old/new worlds);
   :func:`apply_moves` / :func:`reshard_bucket_state` reassemble a new
   rank's shard (and per-bucket optimizer-state pytrees) from fetched
   old shards, raising :class:`~horovod_tpu.exceptions.
   ShardChecksumError` on any integrity mismatch.  When old and new
   worlds overlap, the same plan drives an in-mesh ``all_to_all`` fast
   path — host-side KV is the fallback that always works.
3. **The worker pipeline** — :func:`run_remesh` sequences the phases
   (pause → snapshot → publish → barrier → reinit → fetch → rebuild)
   with per-phase ``remesh.*`` metrics, elastic event-log entries, and
   a ``REMESH`` timeline lane; any failure raises
   :class:`~horovod_tpu.exceptions.RemeshError` and the caller
   (``elastic/run.py``) falls back to the checkpoint-restore restart
   path — the remesh is an optimization, never a new way to wedge.

Use :func:`reinit_world` from a surviving worker after the launcher
hands it the new world description; all live jax Arrays from the old
backend become invalid — restore state from host copies or the KV
store (``elastic.State`` commits are host-side for exactly this
reason).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..exceptions import RemeshError, ShardChecksumError
from ..utils.logging import get_logger


def reinit_world(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Tear down the runtime + XLA backend and rejoin a new world.

    With no arguments, re-initializes single-process (the surviving
    worker continues alone on its local devices).  Passing the new
    coordination triple rejoins a resized multi-process world.

    EXPERIMENTAL: relies on ``jax.extend.backend.clear_backends``
    (internal-adjacent API).  Every jax Array created before the call
    is invalidated.
    """
    import jax

    from .. import runtime as _rt

    # Validate inputs and resolve the backend-reset entry point BEFORE
    # any teardown — failing after shutdown would strand the survivor
    # with no runtime at all.
    if coordinator_address is not None and (
        num_processes is None or process_id is None
    ):
        raise ValueError(
            "reinit_world: coordinator_address requires num_processes "
            "and process_id (a partial triple would silently fall back "
            "to a single-process world)"
        )
    reset = None
    try:
        from jax.extend import backend as _xb

        reset = getattr(_xb, "clear_backends", None)
    except ImportError:
        pass
    if reset is None:
        reset = getattr(jax, "clear_backends", None)
    if reset is None:
        raise RuntimeError(
            "reinit_world: this JAX exposes no backend-reset entry "
            "point (neither jax.extend.backend.clear_backends nor "
            "jax.clear_backends); use the respawn-per-round path"
        )

    _rt.shutdown()
    try:
        jax.distributed.shutdown()
    except Exception:  # not initialized / already down
        pass
    reset()

    # Clear BOTH env spellings the knob layer reads (utils/env.py
    # falls back from HVD_TPU_* to HOROVOD_*).
    for name in ("COORDINATOR_ADDR", "CROSS_RANK", "CROSS_SIZE"):
        os.environ.pop("HVD_TPU_" + name, None)
        os.environ.pop("HOROVOD_" + name, None)
    if coordinator_address is not None:
        os.environ["HVD_TPU_COORDINATOR_ADDR"] = coordinator_address
        os.environ["HVD_TPU_CROSS_SIZE"] = str(num_processes)
        os.environ["HVD_TPU_CROSS_RANK"] = str(process_id)
    get_logger().warning(
        "reinit_world: backend reset, rejoining world "
        "(coordinator=%s, processes=%s)",
        coordinator_address or "<single-process>", num_processes or 1,
    )
    _rt.init()


# =====================================================================
# 1. Shard math: deterministic old-layout -> new-layout interval moves
# =====================================================================


@dataclasses.dataclass(frozen=True)
class ShardLayout:
    """Layout of one flat buffer sharded contiguously over ranks.

    ``n`` valid elements, padded up to ``shards * shard_len``; rank
    ``r`` holds global elements ``[r*shard_len, (r+1)*shard_len)`` —
    exactly the ``sched/zero1._BucketLayout`` convention (``lowering=
    "flat"``: shards == world; ``"hier"``: shards == slice_size with
    the shard replicated across slices — either way the global
    element->rank map below is the layout's own)."""

    n: int
    shards: int
    shard_len: int

    def __post_init__(self):
        if self.shards < 1 or self.shard_len < 0 or self.n < 0:
            raise RemeshError(
                f"invalid shard layout n={self.n} shards={self.shards} "
                f"shard_len={self.shard_len}"
            )
        if self.n > self.shards * self.shard_len:
            raise RemeshError(
                f"shard layout too small: n={self.n} > "
                f"{self.shards}x{self.shard_len}"
            )

    @property
    def padded(self) -> int:
        return self.shards * self.shard_len

    def interval(self, rank: int) -> Tuple[int, int]:
        """Global ``[start, stop)`` of VALID elements rank holds (may be
        empty when the whole shard is padding)."""
        if not 0 <= rank < self.shards:
            raise RemeshError(
                f"rank {rank} out of range for {self.shards} shards"
            )
        start = rank * self.shard_len
        return min(start, self.n), min(start + self.shard_len, self.n)


@dataclasses.dataclass(frozen=True)
class Move:
    """One interval of a destination shard, sourced from one old rank.

    Offsets are shard-relative: copy ``length`` elements from the
    source rank's shard at ``src_off`` into the destination shard at
    ``dst_off``."""

    src_rank: int
    src_off: int
    dst_off: int
    length: int


def plan_moves(old: ShardLayout, new: ShardLayout,
               dst_rank: int) -> List[Move]:
    """Shard-exchange plan for one destination rank: which slices of
    which old ranks' shards assemble the new shard.

    Deterministic, pure, and a *partition*: across all ``dst_rank``
    values the moves cover every valid element exactly once (the
    layout-exchange unit tests assert this), so the exchange is a
    permutation of the data — checksums are preserved by construction.
    Elements past ``new.interval(dst_rank)`` are padding and are
    zero-filled by :func:`apply_moves`, never moved.
    """
    if old.n != new.n:
        raise RemeshError(
            f"reshard changes valid length: {old.n} != {new.n}"
        )
    lo, hi = new.interval(dst_rank)
    moves: List[Move] = []
    pos = lo
    while pos < hi:
        src_rank = pos // old.shard_len if old.shard_len else 0
        src_lo, src_hi = old.interval(src_rank)
        take = min(hi, src_hi) - pos
        if take <= 0:  # defensive: implies old layout inconsistency
            raise RemeshError(
                f"shard plan stuck at {pos} (old={old}, new={new})"
            )
        moves.append(Move(
            src_rank=src_rank,
            src_off=pos - src_rank * old.shard_len,
            dst_off=pos - dst_rank * new.shard_len,
            length=take,
        ))
        pos += take
    return moves


def apply_moves(
    moves: Sequence[Move],
    dst_len: int,
    dtype: Any,
    fetch: Callable[[int], np.ndarray],
) -> np.ndarray:
    """Assemble one destination shard from ``fetch(src_rank)`` host
    arrays.  Unsourced positions (padding) are zero.  A fetched shard
    that is too short for a planned move raises :class:`RemeshError`
    (the caller falls back to checkpoint restore)."""
    out = np.zeros((dst_len,), dtype=dtype)
    for m in moves:
        src = np.asarray(fetch(m.src_rank)).reshape(-1)
        if m.src_off + m.length > src.size:
            raise RemeshError(
                f"source shard from rank {m.src_rank} too short: need "
                f"[{m.src_off}:{m.src_off + m.length}), have {src.size}"
            )
        out[m.dst_off:m.dst_off + m.length] = (
            src[m.src_off:m.src_off + m.length]
        )
    return out


def reshard_shards(
    shards: Sequence[np.ndarray],
    old: ShardLayout,
    new: ShardLayout,
) -> List[np.ndarray]:
    """Re-partition one buffer's per-rank shard arrays from ``old`` to
    ``new`` in process — the slice-handoff executor of the SLO
    remediation ladder (``elastic/remediate.py``): a donor tenant's
    shrink and a recipient's grow are each ONE call through the same
    :func:`plan_moves`/:func:`apply_moves` pipeline the cross-process
    remesh rides, so the handoff inherits its permutation guarantee —
    every valid element lands exactly once, checksums preserved by
    construction.  Raises :class:`RemeshError` (caller rolls back) when
    the supplied shards do not match the old layout."""
    if len(shards) != old.shards:
        raise RemeshError(
            f"have {len(shards)} shard(s) for a {old.shards}-shard "
            "layout"
        )
    srcs = [np.asarray(s).reshape(-1) for s in shards]
    for r, s in enumerate(srcs):
        if s.size < old.shard_len:
            raise RemeshError(
                f"source shard {r} too short: {s.size} < "
                f"{old.shard_len}"
            )
    dtype = srcs[0].dtype if srcs else np.float32
    return [
        apply_moves(
            plan_moves(old, new, dst), new.shard_len, dtype,
            lambda src_rank: srcs[src_rank],
        )
        for dst in range(new.shards)
    ]


# =====================================================================
# 2. Bucket-schedule resharding (ZeRO-1 optimizer shards + EF state)
# =====================================================================


@dataclasses.dataclass(frozen=True)
class BucketReshard:
    """Reshard recipe for one bucket: the old/new flat layouts plus the
    bucket identity fields both plans must agree on."""

    indices: Tuple[int, ...]
    dtype: str
    old: ShardLayout
    new: ShardLayout


@dataclasses.dataclass(frozen=True)
class RemeshPlan:
    """Per-bucket reshard recipes for one parameter tree, old world ->
    new world.  Pure function of the two bucket-layout lists — every
    rank (and the driver) computes the identical plan."""

    buckets: Tuple[BucketReshard, ...]

    def moves_for(self, bucket: int, dst_rank: int) -> List[Move]:
        b = self.buckets[bucket]
        return plan_moves(b.old, b.new, dst_rank)

    def src_ranks(self, dst_rank: int) -> List[int]:
        """All old ranks the destination rank needs shards from."""
        out: set = set()
        for bi in range(len(self.buckets)):
            for m in self.moves_for(bi, dst_rank):
                out.add(m.src_rank)
        return sorted(out)


def _layout_of(lay: Any) -> ShardLayout:
    """A ``sched/zero1._BucketLayout`` (or anything with n/shards/
    shard_len) as a :class:`ShardLayout`."""
    return ShardLayout(
        n=int(lay.n), shards=int(lay.shards), shard_len=int(lay.shard_len)
    )


def plan_reshard(old_layouts: Sequence[Any],
                 new_layouts: Sequence[Any]) -> RemeshPlan:
    """Build the :class:`RemeshPlan` from two bucket-layout lists
    (``sched/zero1.bucket_layouts`` for the old and new worlds).

    Bucket membership is a pure function of gradient metadata — not of
    world size — so the two schedules MUST pair up bucket-for-bucket
    (same leaf ``indices``, same dtype, same valid length).  Any
    disagreement raises :class:`RemeshError`: the state cannot be
    exchanged shard-wise and the caller falls back to the checkpoint
    path.
    """
    if len(old_layouts) != len(new_layouts):
        raise RemeshError(
            f"bucket count changed across worlds: "
            f"{len(old_layouts)} != {len(new_layouts)} (plan must be "
            "world-size independent)"
        )
    buckets = []
    for bi, (o, nw) in enumerate(zip(old_layouts, new_layouts)):
        if tuple(o.indices) != tuple(nw.indices):
            raise RemeshError(
                f"bucket {bi} membership changed: {o.indices} != "
                f"{nw.indices}"
            )
        if str(o.dtype) != str(nw.dtype):
            raise RemeshError(
                f"bucket {bi} dtype changed: {o.dtype} != {nw.dtype}"
            )
        buckets.append(BucketReshard(
            indices=tuple(int(i) for i in o.indices),
            dtype=str(o.dtype),
            old=_layout_of(o),
            new=_layout_of(nw),
        ))
    return RemeshPlan(buckets=tuple(buckets))


def reshard_bucket_state(
    plan: RemeshPlan,
    bucket: int,
    dst_rank: int,
    fetch_state: Callable[[int], Any],
) -> Any:
    """Reshard one bucket's optimizer-state pytree to ``dst_rank``.

    ``fetch_state(src_rank)`` returns that old rank's HOST pytree for
    this bucket (e.g. one entry of ``bucketed_zero_step``'s state
    tuple, ``jax.device_get``-ed).  Leaves whose leading dimension is
    the old shard length (Adam ``m``/``v``, the parameter shard) are
    moved through the interval plan; everything else (step counters,
    scalars — replicated across ranks) is taken verbatim from the
    lowest-numbered source rank.  EF residual leaves (``"ef"``, shaped
    ``(old padded,)``) are re-zeroed: the residual is a *rank-local*
    quantization error and has no meaning under a new partition —
    zeros are safe (plain quantization until feedback refills).
    """
    import jax

    b = plan.buckets[bucket]
    moves = plan.moves_for(bucket, dst_rank)
    srcs = sorted({m.src_rank for m in moves}) or [0]
    cache: Dict[int, Any] = {}

    def state_of(rank: int) -> Any:
        if rank not in cache:
            cache[rank] = fetch_state(rank)
        return cache[rank]

    ref = state_of(srcs[0])

    def is_ef_dict(x):
        return isinstance(x, dict) and set(x) == {"tx", "ef"}

    if is_ef_dict(ref):
        new_ef = np.zeros((b.new.padded,), np.float32)
        tx = reshard_bucket_state(
            plan, bucket, dst_rank,
            lambda r: state_of(r)["tx"],
        )
        return {"tx": tx, "ef": new_ef}

    leaves, treedef = jax.tree.flatten(ref)
    out = []
    for li, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        if arr.ndim >= 1 and arr.shape[0] == b.old.shard_len:

            def fetch(src_rank: int, _li=li) -> np.ndarray:
                peer = jax.tree.leaves(state_of(src_rank))[_li]
                return np.asarray(peer).reshape(-1)

            out.append(apply_moves(
                moves, b.new.shard_len, arr.dtype, fetch
            ))
        else:
            out.append(arr)
    return jax.tree.unflatten(treedef, out)


def full_buffer(layout: ShardLayout,
                shards: Dict[int, np.ndarray]) -> np.ndarray:
    """Reassemble the valid flat buffer from per-rank shards (test and
    checksum helper: ``full_buffer(old, ...) == full_buffer(new, ...)``
    is the exchange-correctness invariant)."""
    parts = []
    for r in range(layout.shards):
        lo, hi = layout.interval(r)
        if hi > lo:
            parts.append(np.asarray(shards[r]).reshape(-1)[: hi - lo])
    if not parts:
        return np.zeros((0,), np.float32)
    return np.concatenate(parts)


# =====================================================================
# 3. Host-side shard movement through the launcher KV store
# =====================================================================


def _digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class KVShardStore:
    """Chunked, checksummed shard blobs in the rendezvous KV store.

    The general-case transport of the state exchange: works whether or
    not the old and new jax worlds overlap (survivors publish BEFORE
    the backend reset; joiners fetch AFTER — no live mesh required).
    One scope per remesh attempt so a torn exchange never pollutes the
    next; blobs are chunked under the controller protocol's frame cap
    and carry a sha256 manifest, so a torn or corrupted shard surfaces
    as :class:`ShardChecksumError` — never as silently wrong numerics.
    """

    _CHUNK = 16 << 20  # controller frames cap at 64MB; stay well under

    def __init__(self, client: Any, remesh_id: int):
        self._client = client
        self.scope = f"__remesh_state__{int(remesh_id)}"

    def _key(self, rank: int, name: str) -> str:
        return f"r{int(rank)}.{name}"

    def put(self, rank: int, name: str, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr)
        blob = arr.tobytes()
        from .. import faults

        if faults.inject("remesh.publish", rank=rank, name=name):
            # cooperative corruption: damage the payload after the
            # manifest digest is computed from the good bytes, so the
            # receiver's checksum verification MUST catch it
            blob = (b"\x00" * 8 + blob[8:]) if len(blob) >= 8 else b"\xff"
        key = self._key(rank, name)
        n = max(1, (len(blob) + self._CHUNK - 1) // self._CHUNK)
        for i in range(n):
            self._client.put(
                self.scope, f"{key}.chunk{i}",
                blob[i * self._CHUNK:(i + 1) * self._CHUNK],
            )
        manifest = json.dumps({
            "chunks": n,
            "bytes": len(arr.tobytes()),
            "sha256": _digest(arr.tobytes()),
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
        })
        self._client.put(self.scope, key, manifest.encode())

    def get(self, rank: int, name: str,
            timeout_ms: int = 10000) -> np.ndarray:
        key = self._key(rank, name)
        raw = self._client.get(self.scope, key, timeout_ms=timeout_ms)
        if raw is None:
            raise RemeshError(
                f"shard {key} missing from {self.scope} (source rank "
                "died before publishing?)"
            )
        manifest = json.loads(raw.decode())
        parts = []
        for i in range(int(manifest["chunks"])):
            chunk = self._client.get(
                self.scope, f"{key}.chunk{i}", timeout_ms=timeout_ms
            )
            if chunk is None:
                raise RemeshError(f"shard {key} chunk {i} missing")
            parts.append(chunk)
        blob = b"".join(parts)[: int(manifest["bytes"])]
        if _digest(blob) != manifest["sha256"]:
            raise ShardChecksumError(
                f"shard {key}: sha256 mismatch after transport"
            )
        return np.frombuffer(
            blob, dtype=np.dtype(manifest["dtype"])
        ).reshape(manifest["shape"]).copy()


# =====================================================================
# Remesh request + worker-side pipeline instrumentation
# =====================================================================


@dataclasses.dataclass(frozen=True)
class RemeshRequest:
    """The driver's broadcast describing one remesh attempt: the new
    world triple plus the old->new rank mapping."""

    remesh_id: int
    round_id: int
    np_old: int
    np_new: int
    coordinator_addr: str
    # old rank -> new rank for survivors (absent = shed); joiners get
    # new ranks not in the mapping's values.
    survivors: Dict[int, int]
    deadline_s: float = 60.0
    # Device worlds, when they differ from np * devices-per-process
    # (e.g. the single-process device-subset resize): None defaults to
    # the constant-devices-per-process fleet convention.
    dev_old: Optional[int] = None
    dev_new: Optional[int] = None

    def new_rank(self, old_rank: int) -> Optional[int]:
        return self.survivors.get(int(old_rank))

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["survivors"] = {str(k): v for k, v in self.survivors.items()}
        return json.dumps(d)

    @classmethod
    def from_json(cls, raw: str) -> "RemeshRequest":
        d = json.loads(raw)
        d["survivors"] = {
            int(k): int(v) for k, v in d.get("survivors", {}).items()
        }
        return cls(**d)


PHASES = ("pause", "snapshot", "publish", "barrier", "reinit",
          "fetch", "rebuild")


@contextlib.contextmanager
def remesh_phase(phase: str, **ctx: Any):
    """Instrument one remesh phase: ``remesh.phase.<name>`` counter,
    ``remesh.phase_seconds`` histogram, a REMESH timeline-lane event,
    an elastic event-log entry, and a fault-injection site
    (``remesh.<phase>``) — so a postmortem shows exactly which phase
    failed, and tests can fail any phase on demand."""
    from .. import events, faults, metrics
    from ..runtime import get_runtime_or_none

    faults.inject(f"remesh.{phase}", **ctx)
    metrics.inc_counter(f"remesh.phase.{phase}")
    events.emit(events.REMESH_PHASE, phase=phase, **ctx)
    rt = get_runtime_or_none()
    tl = rt.timeline if rt is not None else None
    if tl is not None:
        tl.begin(f"remesh.{phase}", "REMESH")
    t0 = time.perf_counter()
    try:
        yield
    finally:
        metrics.observe(
            "remesh.phase_seconds", time.perf_counter() - t0
        )
        rt = get_runtime_or_none()
        tl2 = rt.timeline if rt is not None else None
        if tl2 is tl and tl is not None:
            tl.end(f"remesh.{phase}", "REMESH")


def run_remesh(state: Any, manager: Any, request: RemeshRequest) -> None:
    """Worker-side remesh pipeline, called from the elastic retry loop
    (``elastic/run.py``) when a :class:`~horovod_tpu.exceptions.
    RemeshInterrupt` lands at a step boundary.

    Phases (each wrapped in :func:`remesh_phase` instrumentation):

    1. **pause** — ack the driver's request through the heartbeat/KV
       channel; the step boundary is the pause point.
    2. **snapshot** — ``state.save()`` snapshots replicated attrs to
       host; registered *sharded* attrs (``state.sharded_attrs``) are
       ``device_get``-ed per bucket shard.
    3. **publish** — this rank's shards go into the
       :class:`KVShardStore` (general-case transport: survivors
       publish before the backend reset so joiners — and survivors
       whose new shard needs foreign intervals — can fetch after).
    4. **barrier** — wait until every survivor published (the driver
       flips the ``go`` key once all snapshot acks are in).
    5. **reinit** — shed ranks exit cleanly; survivors
       :func:`reinit_world` into the new triple.
    6. **fetch/rebuild** — reassemble this rank's new shards through
       the :class:`RemeshPlan` and hand them back to the state
       (``state.import_sharded``); replicated attrs restore from the
       host snapshot.

    Any exception is re-raised as :class:`RemeshError` after emitting
    ``remesh.fallback`` bookkeeping — the caller degrades to the
    checkpoint-restore restart path.  A shed rank (not in
    ``request.survivors``) raises :class:`SystemExit` with the shed
    exit code after the publish barrier; the driver treats that exit
    as a clean departure, not a failure.
    """
    from .. import events, metrics

    old_rank = manager.rank
    new_rank = request.new_rank(old_rank)
    metrics.inc_counter("remesh.attempts")
    events.emit(
        events.REMESH_START, remesh_id=request.remesh_id,
        np_old=request.np_old, np_new=request.np_new,
        old_rank=old_rank, new_rank=new_rank,
    )
    # Flight-recorder anomaly trigger (trace/): a membership change is
    # a step-time discontinuity — dump the pre-remesh span ring so the
    # postmortem can see what the exchange path looked like before.
    from .. import trace as _trace

    _trace.trigger_dump(
        "remesh", remesh_id=request.remesh_id,
        np_old=request.np_old, np_new=request.np_new,
    )
    store = KVShardStore(manager.kv_client(), request.remesh_id)
    try:
        with remesh_phase("pause", remesh_id=request.remesh_id,
                          rank=old_rank):
            # Quiesce the exchange service at the pause point: every
            # in-flight submission (a delayed DCN hop, a tenant's
            # eager program) resolves before state snapshots, and the
            # service restarts lazily against the NEW mesh after
            # reinit — its cached executors must not cross a world
            # change.
            from .. import svc as _svc

            _svc.drain(timeout_s=request.deadline_s)
            _svc.reset_service()
            manager.remesh_ack(request.remesh_id, "pause")

        sharded = getattr(state, "sharded_attrs", lambda: {})()
        with remesh_phase("snapshot", rank=old_rank):
            state.save()
            for spec in sharded.values():
                spec.snapshot()

        with remesh_phase("publish", rank=old_rank):
            for name, spec in sharded.items():
                spec.publish(store, name, old_rank)
            manager.remesh_ack(request.remesh_id, "snapshot")

        with remesh_phase("barrier", rank=old_rank):
            manager.remesh_wait_go(
                request.remesh_id, timeout_s=request.deadline_s
            )

        if new_rank is None:
            # Shed: our shards are published; leave the mesh cleanly.
            # ("shed", not "done" — done keys are keyed by NEW ranks
            # and a shed worker's old rank could collide with one.)
            metrics.inc_counter("remesh.shed")
            manager.remesh_ack(request.remesh_id, "shed")
            raise SystemExit(REMESH_SHED_CODE)

        with remesh_phase("reinit", rank=old_rank, new_rank=new_rank):
            if request.np_new == 1:
                reinit_world()
            else:
                reinit_world(
                    coordinator_address=request.coordinator_addr,
                    num_processes=request.np_new,
                    process_id=new_rank,
                )
            manager.on_world_changed(new_rank)

        with remesh_phase("fetch", rank=new_rank):
            fetched: Dict[str, Any] = {}
            for name, spec in sharded.items():
                fetched[name] = spec.reshard(
                    request, store, name, new_rank
                )

        with remesh_phase("rebuild", rank=new_rank):
            # restore FIRST (replicated attrs re-device-put from the
            # host snapshot), THEN install the resharded shards — the
            # other order would clobber the exchanged state with the
            # stale old-world snapshot.
            state.restore()
            for name, spec in sharded.items():
                spec.install(fetched[name])
            manager.remesh_ack(request.remesh_id, "done")
        metrics.inc_counter("remesh.success")
        events.emit(
            events.REMESH_OK, remesh_id=request.remesh_id,
            rank=new_rank, np=request.np_new,
        )
    except SystemExit:
        raise
    except RemeshError as e:
        metrics.inc_counter("remesh.fallback")
        events.emit(
            events.REMESH_FALLBACK, remesh_id=request.remesh_id,
            rank=old_rank, error=str(e),
        )
        raise
    except Exception as e:
        metrics.inc_counter("remesh.fallback")
        events.emit(
            events.REMESH_FALLBACK, remesh_id=request.remesh_id,
            rank=old_rank, error=f"{type(e).__name__}: {e}",
        )
        raise RemeshError(
            f"remesh {request.remesh_id} failed: "
            f"{type(e).__name__}: {e}"
        ) from e


# Exit code a shed worker leaves with after a successful remesh hand-
# off: the driver counts it as a clean departure (the worker's state
# was resharded away), NOT a failure — its host is not blacklisted.
REMESH_SHED_CODE = 75


def join_remesh(state: Any, manager: Any,
                request: RemeshRequest) -> None:
    """Worker-side pipeline for a JOINER — a process the driver spawned
    into the new world mid-remesh (``HVD_TPU_REMESH_JOIN``).

    The joiner runs the user script from scratch, so by the time the
    elastic loop calls this its runtime is already initialized in the
    NEW world and its state holds fresh-init values.  All it needs is
    the fetch/rebuild tail of :func:`run_remesh`: reassemble its shard
    of every registered sharded attribute from the survivors' published
    blobs (replicated attributes arrive through the normal ``sync()``
    broadcast afterwards).  Failures raise :class:`RemeshError`; the
    caller exits for a restart round — a joiner has no state to lose.
    """
    from .. import events, metrics

    new_rank = manager.rank
    metrics.inc_counter("remesh.joins")
    events.emit(
        events.REMESH_START, remesh_id=request.remesh_id,
        np_old=request.np_old, np_new=request.np_new,
        old_rank=None, new_rank=new_rank, join=True,
    )
    store = KVShardStore(manager.kv_client(), request.remesh_id)
    sharded = getattr(state, "sharded_attrs", lambda: {})()
    try:
        with remesh_phase("snapshot", rank=new_rank, join=True):
            for spec in sharded.values():
                spec.snapshot()  # fresh-init treedefs/layouts only
        with remesh_phase("fetch", rank=new_rank, join=True):
            fetched = {
                name: spec.reshard(request, store, name, new_rank)
                for name, spec in sharded.items()
            }
        with remesh_phase("rebuild", rank=new_rank, join=True):
            for name, spec in sharded.items():
                spec.install(fetched[name])
            manager.remesh_ack(request.remesh_id, "done")
        events.emit(
            events.REMESH_OK, remesh_id=request.remesh_id,
            rank=new_rank, np=request.np_new, join=True,
        )
    except Exception as e:
        metrics.inc_counter("remesh.fallback")
        events.emit(
            events.REMESH_FALLBACK, remesh_id=request.remesh_id,
            rank=new_rank, join=True,
            error=f"{type(e).__name__}: {e}",
        )
        if isinstance(e, RemeshError):
            raise
        raise RemeshError(
            f"remesh join {request.remesh_id} failed: "
            f"{type(e).__name__}: {e}"
        ) from e


# =====================================================================
# Sharded-state adapters (what a State registers for remesh)
# =====================================================================


class ShardedZeroState:
    """Remesh adapter for a ``sched.bucketed_zero_step`` state tuple
    held on an elastic :class:`~horovod_tpu.elastic.state.State`.

    Registers via ``state.register_sharded("zero", ShardedZeroState(
    state, params_attr="params", states_attr="opt_state"))``.  The
    exchange runs at *process* granularity: each bucket's flat global
    buffer (``padded`` elements, contiguous device shards in
    slice-major order) splits into ``process_count`` equal slabs, the
    old→new slab movement is :func:`plan_moves`' interval exchange, and
    within a process the devices re-shard for free at ``device_put``
    time.  ZeRO leaves whose leading dimension is the slab length move
    through the plan; replicated leaves (step counters) copy from the
    lowest surviving rank; EF residuals (``{"tx","ef"}`` bucket states)
    re-zero — the residual is rank-local quantization error with no
    meaning under a new partition (zeros degrade to plain quantization
    until feedback refills, the documented EF cold-start).
    """

    def __init__(self, state: Any, params_attr: str = "params",
                 states_attr: str = "opt_state", cfg: Any = None):
        self._state = state
        self._params_attr = params_attr
        self._states_attr = states_attr
        self._cfg = cfg
        self._snap: Optional[Dict[str, Any]] = None

    # -- helpers ------------------------------------------------------
    def _config(self):
        if self._cfg is not None:
            return self._cfg
        from ..sched.plan import current_config

        return current_config()

    def _proc_layouts(self, world_devices: int,
                      processes: int) -> List[Tuple[Any, ShardLayout]]:
        """(bucket_layout, process-granularity ShardLayout) pairs for a
        device world of ``world_devices`` split over ``processes``."""
        from ..sched.zero1 import bucket_layouts

        params = getattr(self._state, self._params_attr)
        lays = bucket_layouts(params, world_devices, self._config())
        out = []
        for lay in lays:
            if lay.lowering in ("hier", "hier_adasum"):
                # Hier-family buckets replicate their ICI-sharded state
                # across slices — the contiguous-slab exchange below
                # does not describe them.  Degrade honestly: the caller
                # falls back to checkpoint restore
                # (docs/fault_tolerance.md).
                raise RemeshError(
                    "in-place reshard of hierarchically-lowered ZeRO "
                    "buckets is not supported; set "
                    "HVD_TPU_TOPO_LOWER=flat for remeshable jobs or "
                    "rely on the checkpoint fallback"
                )
            padded = int(lay.padded)
            if padded % processes:
                raise RemeshError(
                    f"bucket padded length {padded} does not split "
                    f"over {processes} process slab(s)"
                )
            out.append((lay, ShardLayout(
                n=int(lay.n), shards=processes,
                shard_len=padded // processes,
            )))
        return out

    # -- remesh pipeline hooks ---------------------------------------
    def snapshot(self) -> None:
        """``device_get`` this process's slab of every bucket state
        leaf (full buffers in a single-process world)."""
        import jax

        from ..runtime import get_runtime

        rt = get_runtime()
        states = getattr(self._state, self._states_attr)
        self._old_devices = rt.size
        self._old_processes = rt.process_count
        self._local_devices = len(rt.local_devices)
        host = []
        for st in states:
            leaves, treedef = jax.tree.flatten(st)
            got = []
            for leaf in leaves:
                if hasattr(leaf, "addressable_shards") and \
                        rt.process_count > 1:
                    shards = sorted(
                        leaf.addressable_shards,
                        key=lambda s: (
                            s.index[0].start or 0
                            if s.index and s.index[0].start is not None
                            else 0
                        ),
                    )
                    got.append(np.concatenate(
                        [np.asarray(s.data).reshape(-1) for s in shards]
                    ))
                else:
                    got.append(np.asarray(jax.device_get(leaf)))
            host.append(jax.tree.unflatten(treedef, got))
        self._snap = {"states": host}

    def publish(self, store: KVShardStore, name: str,
                old_rank: int) -> None:
        if self._snap is None:
            raise RemeshError("ShardedZeroState.publish before snapshot")
        import jax

        for bi, st in enumerate(self._snap["states"]):
            for li, leaf in enumerate(jax.tree.leaves(st)):
                store.put(old_rank, f"{name}.b{bi}.l{li}",
                          np.asarray(leaf).reshape(-1)
                          if np.ndim(leaf) else np.asarray(leaf))

    def reshard(self, request: RemeshRequest, store: KVShardStore,
                name: str, new_rank: int) -> List[Any]:
        """Assemble this new rank's per-bucket host state slabs from
        the published old slabs."""
        import jax

        if self._snap is None:
            raise RemeshError("ShardedZeroState.reshard before snapshot")
        # Device worlds derive from the request so the SAME math runs
        # on survivors (snapshot taken in the old world) and joiners
        # (snapshot of their fresh-init state in the new world — used
        # only for treedefs): devices-per-process is the fleet-wide
        # slot convention unless the request pins explicit device
        # worlds (the single-process device-subset resize does).
        dev_per_proc = self._old_devices // self._old_processes
        old_dev = request.dev_old or dev_per_proc * request.np_old
        new_dev = request.dev_new or dev_per_proc * request.np_new
        old_pairs = self._proc_layouts(old_dev, request.np_old)
        new_pairs = self._proc_layouts(new_dev, request.np_new)
        plan = plan_reshard(
            [p for p, _ in old_pairs], [p for p, _ in new_pairs]
        )

        # old process rank -> which OLD rank id to fetch from: the
        # store is keyed by old ranks; survivors published under their
        # old ids, so the plan's src ranks map 1:1.
        out_states: List[Any] = []
        for bi, ((old_lay, old_proc), (new_lay, new_proc)) in enumerate(
            zip(old_pairs, new_pairs)
        ):
            ref = self._snap["states"][bi]
            is_ef = isinstance(ref, dict) and set(ref) == {"tx", "ef"}
            tx_ref = ref["tx"] if is_ef else ref
            moves = plan_moves(old_proc, new_proc, new_rank)
            leaves, treedef = jax.tree.flatten(tx_ref)
            new_leaves = []
            cache: Dict[Tuple[int, int], np.ndarray] = {}

            def fetch(src: int, li: int) -> np.ndarray:
                if (src, li) not in cache:
                    key = (
                        f"{name}.b{bi}.l{li}" if not is_ef
                        else f"{name}.b{bi}.l{li + 1}"
                    )
                    cache[(src, li)] = store.get(src, key)
                return cache[(src, li)]

            for li, leaf in enumerate(leaves):
                arr = np.asarray(leaf)
                if arr.ndim >= 1 and arr.shape[0] == old_proc.shard_len:
                    new_leaves.append(apply_moves(
                        moves, new_proc.shard_len, arr.dtype,
                        lambda src, _li=li: fetch(src, _li),
                    ))
                else:
                    # Replicated leaf (Adam count, hyperparam scalars):
                    # take the PUBLISHED old-rank-0 value, not the
                    # local snapshot — a joiner's fresh-init scalars
                    # (count=0) must not survive into the new world.
                    new_leaves.append(fetch(0, li).reshape(arr.shape)
                                      .astype(arr.dtype))
            tx_new = jax.tree.unflatten(treedef, new_leaves)
            if is_ef:
                # one residual buffer per local device, re-zeroed at
                # the new padded length
                ef = np.zeros(
                    (self._local_devices * new_lay.padded,), np.float32
                )
                out_states.append({"tx": tx_new, "ef": ef})
            else:
                out_states.append(tx_new)
        self._new_layouts = [lay for lay, _ in new_pairs]
        return out_states

    def install(self, host_states: List[Any]) -> None:
        """Device-put the resharded host slabs onto the NEW mesh and
        set them back on the state (must run after ``reinit_world``)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..runtime import WORLD_AXIS, get_runtime

        rt = get_runtime()
        mesh = rt.mesh
        placed = []
        for st in host_states:
            def put(leaf):
                arr = np.asarray(leaf)
                if arr.ndim == 0:
                    return jax.device_put(
                        arr, NamedSharding(mesh, P())
                    )
                sharding = NamedSharding(mesh, P(WORLD_AXIS))
                if rt.process_count > 1:
                    return jax.make_array_from_process_local_data(
                        sharding, arr
                    )
                return jax.device_put(arr, sharding)

            placed.append(jax.tree.map(put, st))
        setattr(self._state, self._states_attr, tuple(placed))
        self._snap = None
