"""Elastic state: in-memory checkpoint + cross-process sync.

Reference: ``horovod/common/elastic.py:26-148`` (State/ObjectState) and
the per-framework subclasses (``horovod/torch/elastic/state.py``,
``tensorflow/elastic.py``).  A State owns everything that must survive a
membership change: ``commit()`` snapshots to host memory, ``restore()``
rolls back after a failure, ``sync()`` re-broadcasts from the new rank 0
after a re-rendezvous.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional

import jax

from .. import functions, runtime
from ..exceptions import HostsUpdatedInterrupt


class State:
    """Base elastic state (reference ``common/elastic.py:26``)."""

    def __init__(self, **kwargs):
        self._host_messages: list = []
        self._reset_callbacks: list = []
        self._known_hosts: Optional[frozenset] = None

    def register_reset_callbacks(self, callbacks) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self, timestamp, update_res) -> None:
        self._host_messages.append((timestamp, update_res))

    def commit(self) -> None:
        """Snapshot + check for host changes (reference ``elastic.py:60``)."""
        self.save()
        self.check_host_updates()

    def check_host_updates(self) -> None:
        """Raise HostsUpdatedInterrupt when membership changed
        (reference ``elastic.py:73-96``)."""
        if self._host_messages:
            self._host_messages.clear()
            raise HostsUpdatedInterrupt()

    # Subclass responsibilities (reference elastic.py:99-113):
    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        """Re-initialize the runtime/mesh after membership change."""
        from ..ops import eager

        eager.clear_cache()


class ObjectState(State):
    """Checkpoints arbitrary python attributes (reference
    ``common/elastic.py:116``): attributes passed as kwargs are saved /
    restored / synced by broadcast from rank 0."""

    def __init__(self, **kwargs):
        super().__init__()
        self._saved_state: Dict[str, Any] = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)

    def save(self) -> None:
        for k in self._saved_state:
            self._saved_state[k] = copy.deepcopy(getattr(self, k))

    def restore(self) -> None:
        for k, v in self._saved_state.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        # Deliberate deviation: broadcast *live* attribute values from
        # rank 0.  The reference broadcasts the last-saved snapshot, but
        # its commit() saves before checking for host updates, so
        # saved == live at every interrupt point; saving first here is
        # equivalent there and additionally avoids rolling back progress
        # when sync() is reached outside a commit boundary.
        if self._saved_state:
            self.save()
            synced = functions.broadcast_object(self._saved_state, root_rank=0)
            for k, v in synced.items():
                self._saved_state[k] = v
                setattr(self, k, v)


class ArrayState(ObjectState):
    """Elastic state for JAX pytrees (params/opt_state): the TPU-native
    ``TorchState`` (reference ``torch/elastic/state.py:27-140``).

    Pytree attributes are snapshotted to host memory with
    ``jax.device_get`` (surviving a mesh re-initialization) and restored
    with ``jax.device_put``; ``sync`` broadcasts from the root process.
    """

    def __init__(self, **kwargs):
        self._array_attrs = {
            k for k, v in kwargs.items() if _is_pytree_of_arrays(v)
        }
        super().__init__(**kwargs)
        self.save()

    def save(self) -> None:
        for k in list(self._saved_state):
            v = getattr(self, k)
            if k in self._array_attrs:
                self._saved_state[k] = jax.device_get(v)
            else:
                self._saved_state[k] = copy.deepcopy(v)

    def restore(self) -> None:
        for k, v in self._saved_state.items():
            if k in self._array_attrs:
                setattr(self, k, jax.device_put(v))
            else:
                setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        if self._saved_state:
            self.save()
            synced = functions.broadcast_object(self._saved_state, root_rank=0)
            for k, v in synced.items():
                self._saved_state[k] = v
                setattr(
                    self, k, jax.device_put(v) if k in self._array_attrs else v
                )


# Framework-flavored alias matching reference naming (TorchState /
# TensorFlowState -> TpuState).
TpuState = ArrayState


def _is_pytree_of_arrays(v: Any) -> bool:
    leaves = jax.tree.leaves(v)
    return bool(leaves) and all(
        hasattr(l, "shape") and hasattr(l, "dtype") for l in leaves
    )
