"""Elastic state: in-memory checkpoint + cross-process sync.

Reference: ``horovod/common/elastic.py:26-148`` (State/ObjectState) and
the per-framework subclasses (``horovod/torch/elastic/state.py``,
``tensorflow/elastic.py``).  A State owns everything that must survive a
membership change: ``commit()`` snapshots to host memory, ``restore()``
rolls back after a failure, ``sync()`` re-broadcasts from the new rank 0
after a re-rendezvous.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional

import jax

from .. import functions, runtime
from ..exceptions import HostsUpdatedInterrupt, RemeshInterrupt


class State:
    """Base elastic state (reference ``common/elastic.py:26``)."""

    def __init__(self, **kwargs):
        self._host_messages: list = []
        self._reset_callbacks: list = []
        self._known_hosts: Optional[frozenset] = None
        self._remesh_request = None
        self._sharded: Dict[str, Any] = {}
        self._commit_count = 0
        self._tenant_placement: Optional[Dict[str, int]] = None

    def register_reset_callbacks(self, callbacks) -> None:
        self._reset_callbacks.extend(callbacks)

    def register_sharded(self, name: str, spec) -> None:
        """Register a sharded-state adapter (e.g.
        :class:`~horovod_tpu.elastic.remesh.ShardedZeroState`) whose
        per-rank shards the in-process remesh must exchange — see
        ``docs/fault_tolerance.md``.  Replicated attributes need no
        registration: ``save()``/``restore()``/``sync()`` already carry
        them across a remesh."""
        self._sharded[name] = spec

    def sharded_attrs(self) -> Dict[str, Any]:
        return dict(self._sharded)

    def on_reset(self) -> None:
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self, timestamp, update_res) -> None:
        self._host_messages.append((timestamp, update_res))

    def on_remesh_requested(self, request) -> None:
        """Driver authorized an in-process remesh: the next commit
        boundary raises :class:`RemeshInterrupt` instead of the plain
        restart interrupt (``runner/elastic_worker.py`` poller)."""
        self._remesh_request = request

    def on_placement_updated(self, placement) -> None:
        """An SLO slice handoff changed the tenant→slice placement
        (``runner/slo_consumer.py``).  The arbiter-weight half is
        already enacted by the consumer; the default here just records
        the placement — a state that shards per tenant overrides this
        to reshard at its next commit boundary."""
        self._tenant_placement = dict(placement)

    def commit(self) -> None:
        """Snapshot + check for host changes (reference ``elastic.py:60``).

        In an elastic job the snapshot is also persisted to the launcher
        KV store (rank 0): workers restart across membership rounds on
        TPU (see runner/elastic_driver.py), so host memory alone cannot
        carry state between rounds the way the reference's surviving
        processes do.
        """
        from .. import faults

        self._commit_count += 1
        # Deterministic kill-at-step-boundary site: the fault plan's
        # kill_at_step sugar targets exactly this arrival counter
        # (docs/fault_tolerance.md — seed-reproducible kill-and-resize
        # remesh tests).
        faults.inject("worker.commit", step=self._commit_count)
        self.save()
        self._persist()
        self.check_host_updates()

    def _persist(self) -> None:
        from ..runner import elastic_worker

        mgr = elastic_worker.get_notification_manager()
        if mgr is not None:
            mgr.init()
            blob = self._serialize()
            if blob is not None:
                mgr.save_state_blob(blob)
            elif not getattr(self, "_warned_no_serialize", False):
                self._warned_no_serialize = True
                from ..utils.logging import get_logger

                get_logger().warning(
                    "elastic job with a State that does not serialize: "
                    "progress cannot survive worker restarts — use "
                    "ObjectState/ArrayState or override _serialize()"
                )

    def _load_persisted(self) -> bool:
        """Adopt the previous round's snapshot — only on the FIRST sync
        after process start (later syncs must not roll live progress back
        to the last commit) and only on rank 0 (the subsequent broadcast
        overwrites every other rank anyway)."""
        if getattr(self, "_restore_attempted", False):
            return False
        self._restore_attempted = True
        from ..runner import elastic_worker

        mgr = elastic_worker.get_notification_manager()
        if mgr is None or mgr.rank != 0:
            return False
        mgr.init()
        blob = mgr.load_state_blob()
        if blob is None:
            return False
        return self._deserialize(blob)

    # Serialization hooks for cross-round persistence (subclasses with
    # array state override to host-ify leaves).
    def _serialize(self):
        return None

    def _deserialize(self, blob) -> bool:
        return False

    def check_host_updates(self) -> None:
        """Raise HostsUpdatedInterrupt when membership changed
        (reference ``elastic.py:73-96``) — or :class:`RemeshInterrupt`
        when the driver authorized resharding live state in place
        (``elastic/remesh.py``)."""
        if self._remesh_request is not None:
            req, self._remesh_request = self._remesh_request, None
            self._host_messages.clear()
            raise RemeshInterrupt(req)
        if self._host_messages:
            self._host_messages.clear()
            raise HostsUpdatedInterrupt()

    # Subclass responsibilities (reference elastic.py:99-113):
    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        """Re-initialize the runtime/mesh after membership change."""
        from ..ops import eager

        eager.clear_cache()


class ObjectState(State):
    """Checkpoints arbitrary python attributes (reference
    ``common/elastic.py:116``): attributes passed as kwargs are saved /
    restored / synced by broadcast from rank 0."""

    def __init__(self, **kwargs):
        super().__init__()
        self._saved_state: Dict[str, Any] = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)

    def save(self) -> None:
        for k in self._saved_state:
            self._saved_state[k] = copy.deepcopy(getattr(self, k))

    def restore(self) -> None:
        for k, v in self._saved_state.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        # Deliberate deviation: broadcast *live* attribute values from
        # rank 0.  The reference broadcasts the last-saved snapshot, but
        # its commit() saves before checking for host updates, so
        # saved == live at every interrupt point; saving first here is
        # equivalent there and additionally avoids rolling back progress
        # when sync() is reached outside a commit boundary.
        if self._saved_state:
            # Fresh elastic round: adopt the persisted snapshot from the
            # previous round, if any, before broadcasting.
            self._load_persisted()
            self.save()
            synced = functions.broadcast_object(self._saved_state, root_rank=0)
            for k, v in synced.items():
                self._saved_state[k] = v
                setattr(self, k, v)

    def _serialize(self):
        import pickle

        return pickle.dumps(self._saved_state)

    def _deserialize(self, blob) -> bool:
        import pickle

        try:
            saved = pickle.loads(blob)
        except Exception:
            return False
        if set(saved) != set(self._saved_state):
            return False
        self._saved_state.update(saved)
        for k, v in saved.items():
            setattr(self, k, v)
        return True


class ArrayState(ObjectState):
    """Elastic state for JAX pytrees (params/opt_state): the TPU-native
    ``TorchState`` (reference ``torch/elastic/state.py:27-140``).

    Pytree attributes are snapshotted to host memory with
    ``jax.device_get`` (surviving a mesh re-initialization) and restored
    with ``jax.device_put``; ``sync`` broadcasts from the root process.
    """

    def __init__(self, **kwargs):
        self._array_attrs = {
            k for k, v in kwargs.items() if _is_pytree_of_arrays(v)
        }
        super().__init__(**kwargs)
        self.save()

    def save(self) -> None:
        for k in list(self._saved_state):
            v = getattr(self, k)
            if k in self._array_attrs:
                self._saved_state[k] = jax.device_get(v)
            else:
                self._saved_state[k] = copy.deepcopy(v)

    def restore(self) -> None:
        for k, v in self._saved_state.items():
            if k in self._array_attrs:
                setattr(self, k, jax.device_put(v))
            else:
                setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        if self._saved_state:
            self._load_persisted()
            self.save()
            synced = functions.broadcast_object(self._saved_state, root_rank=0)
            for k, v in synced.items():
                self._saved_state[k] = v
                setattr(
                    self, k, jax.device_put(v) if k in self._array_attrs else v
                )

    def _deserialize(self, blob) -> bool:
        if not super()._deserialize(blob):
            return False
        # re-device the array attributes (the blob holds host arrays)
        for k in self._array_attrs:
            setattr(self, k, jax.device_put(self._saved_state[k]))
        return True


# Framework-flavored alias matching reference naming (TorchState /
# TensorFlowState -> TpuState).
TpuState = ArrayState


def _is_pytree_of_arrays(v: Any) -> bool:
    leaves = jax.tree.leaves(v)
    return bool(leaves) and all(
        hasattr(l, "shape") and hasattr(l, "dtype") for l in leaves
    )
