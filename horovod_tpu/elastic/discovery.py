"""Host discovery for elastic training.

Reference: ``horovod/runner/elastic/discovery.py`` — ``HostManager``
runs a user-supplied discovery script emitting ``host[:slots]`` lines,
tracks current hosts, and blacklists hosts that failed.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, Set

from ..runner import hosts as hosts_mod
from ..utils.logging import get_logger


class HostDiscovery:
    """Base interface (reference ``HostDiscovery``)."""

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs the user script; each stdout line is ``host[:slots]``
    (reference ``HostDiscoveryScript``)."""

    def __init__(self, discovery_script: str, default_slots: int = 1):
        self.script = discovery_script
        self.default_slots = default_slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.run(
            self.script, shell=True, capture_output=True, text=True, timeout=60
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"discovery script failed ({out.returncode}): {out.stderr}"
            )
        hosts: Dict[str, int] = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            info = hosts_mod.HostInfo.from_string(line)
            hosts[info.hostname] = (
                info.slots if ":" in line else self.default_slots
            )
        return hosts


class FixedHosts(HostDiscovery):
    """Static host set (used when elastic runs with -H but no script)."""

    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Current + blacklisted hosts (reference ``HostManager``)."""

    def __init__(self, discovery: HostDiscovery):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._current: Dict[str, int] = {}
        self._blacklist: Set[str] = set()

    def update_available_hosts(self) -> bool:
        """Polls discovery; returns True when the usable set changed."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            usable = {
                h: s for h, s in found.items() if h not in self._blacklist
            }
            changed = usable != self._current
            self._current = usable
            return changed

    def blacklist(self, hostname: str) -> None:
        with self._lock:
            if hostname not in self._blacklist:
                get_logger().warning("blacklisting host %s", hostname)
            self._blacklist.add(hostname)
            self._current.pop(hostname, None)

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            return hostname in self._blacklist

    @property
    def current_hosts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._current)

    def available_slots(self) -> int:
        with self._lock:
            return sum(self._current.values())
