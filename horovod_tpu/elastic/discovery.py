"""Host discovery for elastic training.

Reference: ``horovod/runner/elastic/discovery.py`` — ``HostManager``
runs a user-supplied discovery script emitting ``host[:slots]`` lines,
tracks current hosts, and blacklists hosts that failed.

Hardened beyond the reference: discovery-script flakes are absorbed by
a :class:`~horovod_tpu.utils.retry.RetryPolicy` (the reference re-polls
a period later, stretching membership staleness by a full discovery
interval per flake), and blacklisting is *cooldown-based* — a failed
host is quarantined for an exponentially growing, capped interval
instead of forever.  Permanent blacklisting turns every transient host
fault (OOM kill, preemption, network partition) into permanently lost
capacity; a production elastic job must be able to win hosts back.
Reference behavior is one env knob away
(``HVD_TPU_BLACKLIST_COOLDOWN=0`` → permanent).
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Callable, Dict, Optional

from .. import faults
from ..runner import hosts as hosts_mod
from ..utils import env as hvd_env
from ..utils.logging import get_logger
from ..utils.retry import RetryPolicy

# Cooldown before a blacklisted host may return, doubling per repeated
# failure: min(base * 2**(failures-1), cap).  base <= 0 restores the
# reference's permanent blacklist.
BLACKLIST_COOLDOWN = "BLACKLIST_COOLDOWN"          # seconds, default 30
BLACKLIST_COOLDOWN_MAX = "BLACKLIST_COOLDOWN_MAX"  # seconds, default 600
DISCOVERY_RETRIES = "DISCOVERY_RETRIES"            # attempts, default 3

DEFAULT_COOLDOWN_S = 30.0
DEFAULT_COOLDOWN_MAX_S = 600.0


class HostDiscovery:
    """Base interface (reference ``HostDiscovery``)."""

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs the user script; each stdout line is ``host[:slots]``
    (reference ``HostDiscoveryScript``).  Transient script failures are
    retried per ``retry`` (default: ``HVD_TPU_DISCOVERY_RETRIES``
    attempts with short exponential backoff) before the error reaches
    the driver's discovery loop."""

    def __init__(self, discovery_script: str, default_slots: int = 1,
                 retry: Optional[RetryPolicy] = None):
        self.script = discovery_script
        self.default_slots = default_slots
        self.retry = retry or RetryPolicy(
            max_attempts=max(1, hvd_env.get_int(DISCOVERY_RETRIES, 3)),
            base_delay_s=0.2,
            max_delay_s=2.0,
            name="discovery",
        )

    def _run_script(self) -> Dict[str, int]:
        faults.inject("discovery.script", script=self.script)
        out = subprocess.run(
            self.script, shell=True, capture_output=True, text=True, timeout=60
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"discovery script failed ({out.returncode}): {out.stderr}"
            )
        hosts: Dict[str, int] = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            info = hosts_mod.HostInfo.from_string(line)
            hosts[info.hostname] = (
                info.slots if ":" in line else self.default_slots
            )
        return hosts

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return self.retry.call(self._run_script)


class FixedHosts(HostDiscovery):
    """Static host set (used when elastic runs with -H but no script)."""

    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


def _rescale_hosts(found: Dict[str, int], np_target: int) -> Dict[str, int]:
    """Shrink or grow a discovered ``{host: slots}`` map to exactly
    ``np_target`` total slots, deterministically: slots are trimmed
    from (or added to) hosts in sorted-name order, and a host trimmed
    to zero drops out — the ``resize_to`` fault's world reshaper."""
    out = dict(found)
    total = sum(out.values())
    for h in sorted(out):
        if total == np_target:
            break
        if total > np_target:
            take = min(out[h], total - np_target)
            out[h] -= take
            total -= take
        else:
            out[h] += np_target - total
            total = np_target
    if total < np_target and not out:
        out["localhost"] = np_target
    return {h: s for h, s in out.items() if s > 0}


class _BlacklistEntry:
    __slots__ = ("failures", "until")

    def __init__(self, failures: int, until: float):
        self.failures = failures
        self.until = until  # monotonic deadline; inf = permanent


class HostManager:
    """Current + blacklisted hosts (reference ``HostManager``), with
    cooldown-based un-blacklisting.

    ``cooldown_s``/``cooldown_max_s`` default from
    ``HVD_TPU_BLACKLIST_COOLDOWN`` / ``..._MAX``; ``cooldown_s <= 0``
    means permanent (reference semantics).  ``clock`` is injectable for
    deterministic tests."""

    def __init__(
        self,
        discovery: HostDiscovery,
        cooldown_s: Optional[float] = None,
        cooldown_max_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._current: Dict[str, int] = {}
        self._blacklist: Dict[str, _BlacklistEntry] = {}
        self._clock = clock
        if cooldown_s is None:
            cooldown_s = hvd_env.get_float(
                BLACKLIST_COOLDOWN, DEFAULT_COOLDOWN_S
            )
        if cooldown_max_s is None:
            cooldown_max_s = hvd_env.get_float(
                BLACKLIST_COOLDOWN_MAX, DEFAULT_COOLDOWN_MAX_S
            )
        self.cooldown_s = cooldown_s
        self.cooldown_max_s = cooldown_max_s

    def _expire_blacklist_locked(self) -> None:
        """Lift expired cooldowns.  The failure count survives the lift:
        a host that flaps fails straight into a doubled cooldown."""
        now = self._clock()
        for h, entry in self._blacklist.items():
            if entry.until != float("-inf") and entry.until <= now:
                entry.until = float("-inf")  # lifted, history kept
                get_logger().warning(
                    "blacklist cooldown expired for host %s "
                    "(%d prior failure(s))", h, entry.failures,
                )
                from .. import events, metrics

                metrics.inc_counter("elastic.unblacklist")
                events.emit(events.UNBLACKLIST, host=h,
                            failures=entry.failures)

    def update_available_hosts(self) -> bool:
        """Polls discovery; returns True when the usable set changed."""
        found = self._discovery.find_available_hosts_and_slots()
        # Scripted membership change (HVD_TPU_FAULT_PLAN
        # 'discovery.resize:resize_to:np=N'): rescale the discovered
        # slot total to exactly N — the seed-reproducible resize half
        # of kill-and-resize remesh tests, no scripted-discovery fake
        # needed (docs/fault_tolerance.md).
        from .. import faults

        resize = faults.inject("discovery.resize", total=sum(found.values()))
        if isinstance(resize, dict) and resize.get("np"):
            found = _rescale_hosts(found, int(resize["np"]))
        with self._lock:
            self._expire_blacklist_locked()
            usable = {
                h: s for h, s in found.items()
                if not self._is_blacklisted_locked(h)
            }
            changed = usable != self._current
            self._current = usable
            return changed

    def blacklist(self, hostname: str) -> None:
        with self._lock:
            entry = self._blacklist.get(hostname)
            failures = (entry.failures if entry else 0) + 1
            if self.cooldown_s <= 0:
                until = float("inf")
                desc = "permanently"
            else:
                cooldown = min(
                    self.cooldown_s * (2.0 ** (failures - 1)),
                    self.cooldown_max_s,
                )
                until = self._clock() + cooldown
                desc = f"for {cooldown:.1f}s (failure #{failures})"
            get_logger().warning(
                "blacklisting host %s %s", hostname, desc
            )
            self._blacklist[hostname] = _BlacklistEntry(failures, until)
            self._current.pop(hostname, None)
        from .. import events, metrics

        metrics.inc_counter("elastic.blacklist")
        events.emit(
            events.BLACKLIST, host=hostname, failures=failures,
            permanent=(until == float("inf")),
        )

    def _is_blacklisted_locked(self, hostname: str) -> bool:
        entry = self._blacklist.get(hostname)
        return entry is not None and entry.until > self._clock()

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            return self._is_blacklisted_locked(hostname)

    def failure_count(self, hostname: str) -> int:
        with self._lock:
            entry = self._blacklist.get(hostname)
            return entry.failures if entry else 0

    @property
    def current_hosts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._current)

    def available_slots(self) -> int:
        with self._lock:
            return sum(self._current.values())
