"""Bucketed overlap scheduler: the gradient-exchange pipeline.

Horovod's headline capability is the *scheduling* around the allreduce
— tensor fusion, cycle-driven dispatch, compute/comms overlap (Sergeev
& Del Balso, arXiv:1802.05799 §4) — run by its background controller
loop.  Under XLA that loop has no process to live in; this package is
its trace-time replacement, three stages over one gradient pytree:

* ``plan``    — build a :class:`~horovod_tpu.sched.plan.BucketSchedule`:
                reverse-backward bucket order, dtype grouping via
                ``ops/fusion.bucket_plan``, per-bucket wire compression,
                ``allreduce`` vs ``reduce_scatter+all_gather`` exchange
                modes (the latter with ZeRO-1 shard updates, ``zero1``).
* ``execute`` — emit per-bucket collectives sequenced by
                ``lax.optimization_barrier`` and interleaved with the
                backward via ``jax.grad``-boundary taps (``hooks``), so
                XLA's latency-hiding scheduler overlaps wire time with
                the remaining compute.
* ``tune``    — wire ``utils/autotune.FusionAutotuner`` to the
                bucket-size knob, scoring windows from the metrics
                registry.
* ``store``   — persist converged (bucket_bytes, wire, lowering)
                winners to ``HVD_TPU_TUNE_DB``, keyed by (schedule
                signature, topology, jax version, knob fingerprint);
                the tuner warm-starts from a hit with zero exploration
                windows and the elastic driver serves entries
                fleet-wide (``/schedules``).  See docs/autotune.md.

``DistributedOptimizer`` uses this pipeline by default; set
``HVD_TPU_SCHED=off`` for the legacy single-fused-exchange path.  See
docs/scheduler.md.
"""

from . import execute, hooks, plan, tune, zero1  # noqa: F401
from .execute import (  # noqa: F401
    exchange,
    hier_phase_factory,
    quantized_exchange_flat,
    sync_gradients_bucketed,
)
from .plan import (  # noqa: F401
    LOWER_CHOICES,
    WIRE_CHOICES,
    Bucket,
    BucketSchedule,
    SchedConfig,
    build_schedule,
    current_config,
    eligible_wire,
    resolve_lowering,
    set_config_override,
    wire_bytes,
)
from .store import ScheduleStore, knob_fingerprint, make_key  # noqa: F401
from .tune import ScheduleTuner  # noqa: F401
from .zero1 import bucketed_zero_step  # noqa: F401
