"""Tune stage: drive the bucket-size knob from registry metrics.

``utils/autotune.FusionAutotuner`` owns the suggest/observe search
(the reference ParameterManager's Bayesian loop); what the scheduler
adds is the *scoring feed*: instead of a caller hand-timing windows,
scores are computed from the PR 2 metrics registry — the counters and
histograms the hot path already maintains (``train.steps``,
``train.step_seconds``, ``sched.bytes_per_step``) — so any training
loop that bumps standard metrics gets bucket-size tuning for free.

Usage::

    tuner = ScheduleTuner()
    while not tuner.converged:
        cfg = dataclasses.replace(cfg, bucket_bytes=tuner.bucket_bytes())
        tuner.begin_window()
        run_steps(window)                 # bumps train.* / sched.*
        tuner.end_window()
    cfg = dataclasses.replace(cfg, bucket_bytes=tuner.bucket_bytes())

Under ``HVD_TPU_AUTOTUNE=1`` the plan stage already follows the
``TrainStep`` autotune driver (``bucket_bytes=None`` defers to the
fusion-threshold override), so this class is for loops that want
registry-scored tuning without the wall-clock window driver.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from .. import metrics
from ..utils import env
from ..utils.autotune import FusionAutotuner


def registry_view() -> Dict[str, float]:
    """Snapshot the registry series the window score derives from."""
    hist = metrics.get_histogram("train.step_seconds")
    return {
        "steps": float(metrics.get_counter("train.steps")),
        "step_seconds_sum": float(hist["sum"]) if hist else 0.0,
        "bytes_per_step": float(
            metrics.get_gauge("sched.bytes_per_step") or 0.0
        ),
        "mono": time.monotonic(),
    }


def window_score(
    before: Dict[str, float], after: Dict[str, float]
) -> float:
    """Score one closed window from two registry snapshots.

    Primary: exchanged **bytes/sec** — steps/sec (from the
    ``train.steps`` counter over the ``train.step_seconds`` histogram
    sum, falling back to wall clock when the histogram is idle) times
    the planned ``sched.bytes_per_step`` gauge.  Without a bytes gauge
    the score degrades to plain steps/sec, which ranks candidates
    identically for a fixed model.
    """
    steps = after["steps"] - before["steps"]
    if steps <= 0:
        return 0.0
    dt = after["step_seconds_sum"] - before["step_seconds_sum"]
    if dt <= 0:
        dt = after["mono"] - before["mono"]
    rate = steps / max(dt, 1e-9)
    bytes_per_step = after["bytes_per_step"]
    return rate * bytes_per_step if bytes_per_step > 0 else rate


class ScheduleTuner:
    """FusionAutotuner wired to the scheduler's bucket-size knob with
    registry-fed window scores.

    ``explore_wire=True`` adds the quantized-wire dimension: each
    window runs under one wire candidate (``wire_candidates``, default
    off → bf16 → int8 → fp8), scored from the same registry deltas;
    once every candidate has a score the best freezes and bucket-size
    tuning proceeds under it.  Apply the suggestion per bucket with
    :meth:`wire` + :func:`~horovod_tpu.sched.plan.build_schedule`'s
    ``wire=`` argument (small buckets below ``wire_min_bucket_bytes``
    stay dense — the fp32 scale sidecar dominates tiny payloads)::

        tuner = ScheduleTuner(explore_wire=True)
        while not tuner.converged:
            cfg = dataclasses.replace(
                cfg, bucket_bytes=tuner.bucket_bytes(), wire=tuner.wire())
            tuner.begin_window(); run_steps(window); tuner.end_window()

    Scores are exchanged-bytes/sec over the *dense* byte gauge, so a
    wire that trains the same steps/sec wins only via its bucket plan —
    and a quantized wire that slows convergence shows up as fewer
    steps (the EF residual keeps trajectories close; see
    docs/quantization.md).

    ``store``/``store_key`` engage the persistent autotuning DB
    (``sched/store.py``, docs/autotune.md): a hit freezes every knob
    before window 0 (``sched.tune.db_hit``), a miss explores as usual
    and writes the winner back on convergence
    (``sched.tune.db_store``)::

        tuner = ScheduleTuner(store="env",
                              store_key=schedule.signature())
    """

    def __init__(self, explore_wire: bool = False,
                 wire_candidates=("off", "bf16", "int8", "fp8"),
                 wire_min_bucket_bytes: int = 1 << 16,
                 explore_lowering: bool = False,
                 lowering_candidates=("flat", "hier", "hier_adasum"),
                 explore_backend: bool = False,
                 backend_candidates=("phase", "fused"),
                 explore_pipeline: bool = False,
                 pipeline_candidates=("off", "on", "auto"),
                 explore_onestep: bool = False,
                 onestep_candidates=("off", "on", "auto"),
                 store="env",
                 store_key=None,
                 store_kind="dense_grad",
                 **tuner_kwargs):
        self.tuner = FusionAutotuner(**tuner_kwargs)
        self._baseline: Optional[Dict[str, float]] = None
        self._explore_wire = explore_wire
        self._wire_candidates = tuple(wire_candidates)
        self.wire_min_bucket_bytes = wire_min_bucket_bytes
        self._wire_scores: Dict[str, float] = {}
        self._wire_frozen: Optional[str] = None if explore_wire else "off"
        # Quantized-wire backend exploration (HVD_TPU_QUANT_BACKEND as
        # a tuned dimension): each window runs one candidate — the
        # suggestion is applied process-wide via the env knob, since
        # the backend resolves at trace time — scored from the same
        # registry deltas; the winner freezes and is pinned into the
        # environment.  "env" defers to the operator's knob (the
        # default: not a tuned dimension).
        self._explore_backend = explore_backend
        self._backend_candidates = tuple(backend_candidates)
        self._backend_scores: Dict[str, float] = {}
        self._backend_frozen: Optional[str] = (
            None if explore_backend else "env"
        )
        # Rail-pipeliner exploration (HVD_TPU_XIR_PIPELINE as a tuned
        # dimension, xir/pipeline.py): each window runs one candidate —
        # applied process-wide through the env knob, since engagement
        # resolves at trace time — scored from the same registry
        # deltas; the winner freezes, pins the knob, and persists in
        # entry meta.pipeline.  Reordering is numerics-free (losses
        # bitwise-identical across candidates), so the score ranks pure
        # wall-clock.  On a single-slice topology nothing ever engages:
        # exploration is skipped and the knob pins "off" immediately.
        self._explore_pipeline = explore_pipeline
        self._pipeline_candidates = tuple(pipeline_candidates)
        self._pipeline_scores: Dict[str, float] = {}
        if not explore_pipeline:
            self._pipeline_frozen: Optional[str] = "env"
        elif self._topo_multi_slice():
            self._pipeline_frozen = None
        else:
            self._pipeline_frozen = "off"
        # Whole-step-emission exploration (HVD_TPU_ONESTEP as a tuned
        # dimension, xir/interp.py): each window runs one candidate —
        # applied process-wide through the env knob, since the fold
        # resolves at trace time — scored from the same registry
        # deltas; the winner freezes, pins the knob, and persists in
        # entry meta.onestep.  The fold is ordering-only (losses
        # bitwise-identical across candidates), so the score ranks
        # pure wall-clock: dispatch round-trips saved vs the larger
        # compiled program.
        self._explore_onestep = explore_onestep
        self._onestep_candidates = tuple(onestep_candidates)
        self._onestep_scores: Dict[str, float] = {}
        self._onestep_frozen: Optional[str] = (
            None if explore_onestep else "env"
        )
        # Lowering exploration (the HVD_TPU_TOPO_LOWER knob as a tuned
        # dimension): each window runs one candidate — including
        # hier_adasum, the adaptive cross-slice combine the cost model
        # never picks on its own — scored from the same registry
        # deltas; the winner freezes.  On a single-slice topology every
        # candidate resolves flat anyway, so exploration is skipped and
        # the knob pins to "flat" immediately.
        self._explore_lowering = explore_lowering
        self._lowering_candidates = tuple(lowering_candidates)
        self._lowering_scores: Dict[str, float] = {}
        if not explore_lowering:
            # Not a tuned dimension: defer to the cost model ("auto").
            self._lowering_frozen: Optional[str] = "auto"
        elif self._topo_multi_slice():
            self._lowering_frozen = None
        else:
            self._lowering_frozen = "flat"
        # Persistent warm start (sched/store.py): ``store_key`` is any
        # deterministic schedule identity — canonically
        # ``BucketSchedule.signature()`` — hashed together with the
        # topology, jax version, and knob fingerprint.  The default
        # ``store="env"`` resolves HVD_TPU_TUNE_DB, so persistence
        # engages for ANY tuner given a key (and stays off when the
        # env is unset — bit-identical to no store at all).
        if store == "env":
            if store_key is None:
                store = None  # keyless tuner: nothing to look up
            else:
                from .store import ScheduleStore

                store = ScheduleStore.from_env()
        self._store = store
        self._store_key: Optional[str] = None
        self._db_written = False
        self._best_score = 0.0
        if store is not None and store_key is not None:
            from .store import make_key

            # ``store_kind`` discriminates the workload in the DB key
            # (xir.KINDS): a tuner scoring a MoE program must never
            # collide with a dense-gradient schedule of equal payload
            # signature.
            self._store_key = (
                store_key if isinstance(store_key, str)
                and len(store_key) == 64
                else make_key(store_key, kind=store_kind)
            )
            entry = store.lookup(self._store_key)
            if entry is not None:
                self._warm_start(entry)
            else:
                metrics.inc_counter("sched.tune.db_miss")

    def _warm_start(self, entry: Dict) -> None:
        """Adopt a stored winner: every knob freezes before the first
        window, so ``converged`` is True at window 0 and the job pays
        zero exploration windows."""
        from ..utils.logging import get_logger

        self.tuner.freeze(int(entry["bucket_bytes"]))
        wire = str(entry.get("wire", "off"))
        self._wire_frozen = (
            wire if wire in self._wire_candidates + ("off",) else "off"
        )
        lowering = str(entry.get("lowering", "auto"))
        self._lowering_frozen = (
            lowering if lowering in self._lowering_candidates + ("auto",)
            else "auto"
        )
        backend = str((entry.get("meta") or {}).get("backend", ""))
        if backend in self._backend_candidates:
            self._backend_frozen = backend
            if self._explore_backend:
                env.set_env("QUANT_BACKEND", backend)
        elif self._backend_frozen is None:
            self._backend_frozen = "env"
        pipe = str((entry.get("meta") or {}).get("pipeline", ""))
        if pipe in self._pipeline_candidates:
            self._pipeline_frozen = pipe
            if self._explore_pipeline:
                env.set_env("XIR_PIPELINE", pipe)
        elif self._pipeline_frozen is None:
            self._pipeline_frozen = "env"
        onestep = str((entry.get("meta") or {}).get("onestep", ""))
        if onestep in self._onestep_candidates:
            self._onestep_frozen = onestep
            if self._explore_onestep:
                env.set_env("ONESTEP", onestep)
        elif self._onestep_frozen is None:
            self._onestep_frozen = "env"
        self._best_score = float(entry.get("score", 0.0))
        self._db_written = True  # a re-write would only echo the entry
        metrics.inc_counter("sched.tune.db_hit")
        metrics.set_gauge("sched.tune.warm_start", 1.0)
        get_logger().info(
            "schedule tuner warm start: bucket_bytes=%d wire=%s "
            "lowering=%s (stored score %.3g, %d prior hits)",
            int(entry["bucket_bytes"]), self._wire_frozen,
            self._lowering_frozen, self._best_score,
            int(entry.get("hits", 0)),
        )

    def _maybe_store(self) -> None:
        """Write the converged winner back once (miss path only)."""
        if (self._db_written or self._store is None
                or self._store_key is None or not self.converged):
            return
        self._db_written = True
        self._store.record(
            self._store_key,
            bucket_bytes=self.bucket_bytes(),
            wire=self.wire(),
            lowering=self.lowering(),
            score=self._best_score,
            meta={"backend": self.backend(),
                  "pipeline": self.pipeline(),
                  "onestep": self.onestep()},
        )

    @staticmethod
    def _topo_multi_slice() -> bool:
        from ..topo import model as topo_model

        return topo_model.current().multi_slice

    def bucket_bytes(self) -> int:
        """Bucket-size suggestion for the next window (frozen winner
        after convergence)."""
        return self.tuner.threshold_bytes()

    def wire(self) -> str:
        """Wire-format suggestion for the next window: the next unscored
        candidate while exploring, the frozen winner after."""
        if self._wire_frozen is not None:
            return self._wire_frozen
        for w in self._wire_candidates:
            if w not in self._wire_scores:
                return w
        return self._wire_frozen or "off"

    def backend(self) -> str:
        """Quantized-wire backend suggestion for the next window: the
        next unscored candidate while exploring, the frozen winner
        after, or the ``HVD_TPU_QUANT_BACKEND`` env knob when the
        backend is not a tuned dimension.  Exploration applies the
        suggestion through the env knob in :meth:`begin_window` —
        the backend resolves at trace time, so the caller rebuilds its
        step per window exactly as with wire exploration."""
        if self._backend_frozen == "env":
            from ..ops.quantized import quant_backend

            return quant_backend()
        if self._backend_frozen is not None:
            return self._backend_frozen
        for b in self._backend_candidates:
            if b not in self._backend_scores:
                return b
        return "phase"

    def pipeline(self) -> str:
        """Rail-pipeliner mode suggestion for the next window
        (``HVD_TPU_XIR_PIPELINE``): the next unscored candidate while
        exploring, the frozen winner after, or the env knob's resolved
        mode when pipelining is not a tuned dimension.  Exploration
        applies the suggestion through the env knob in
        :meth:`begin_window` — engagement resolves at trace time, so
        the caller rebuilds its step per window exactly as with
        backend exploration."""
        if self._pipeline_frozen == "env":
            from ..xir import pipeline as railpipe

            return railpipe.mode()
        if self._pipeline_frozen is not None:
            return self._pipeline_frozen
        for p in self._pipeline_candidates:
            if p not in self._pipeline_scores:
                return p
        return "auto"

    def onestep(self) -> str:
        """Whole-step-emission mode suggestion for the next window
        (``HVD_TPU_ONESTEP``): the next unscored candidate while
        exploring, the frozen winner after, or the env knob's resolved
        mode when the fold is not a tuned dimension.  Exploration
        applies the suggestion through the env knob in
        :meth:`begin_window` — the fold resolves at trace time, so the
        caller rebuilds its step per window exactly as with pipeline
        exploration."""
        if self._onestep_frozen == "env":
            from ..xir import interp as xir_interp

            return xir_interp.onestep_mode()
        if self._onestep_frozen is not None:
            return self._onestep_frozen
        for m in self._onestep_candidates:
            if m not in self._onestep_scores:
                return m
        return "auto"

    def lowering(self) -> str:
        """Lowering suggestion for the next window
        (``build_schedule(..., lowering=...)``): the next unscored
        candidate while exploring, the frozen winner after — "auto"
        when lowering is not an explored dimension (the cost model
        decides per bucket)."""
        if self._lowering_frozen is not None:
            return self._lowering_frozen
        for lo in self._lowering_candidates:
            if lo not in self._lowering_scores:
                return lo
        return self._lowering_frozen or "auto"

    def begin_window(self) -> None:
        # Prime the suggestion: FusionAutotuner only accepts an observe
        # for a threshold it suggested (suggest-before-observe contract).
        self.tuner.threshold_bytes()
        if self._backend_frozen is None:
            # backend candidates apply process-wide (trace-time knob)
            env.set_env("QUANT_BACKEND", self.backend())
        if self._pipeline_frozen is None:
            # pipeline candidates apply process-wide (trace-time knob)
            env.set_env("XIR_PIPELINE", self.pipeline())
        if self._onestep_frozen is None:
            # onestep candidates apply process-wide (trace-time knob)
            env.set_env("ONESTEP", self.onestep())
        self._baseline = registry_view()

    def end_window(self) -> float:
        """Close the window: score it from the registry deltas and feed
        the search.  While wire exploration is open the score lands on
        the current wire candidate; afterwards it feeds the bucket-size
        tuner.  Returns the score (0.0 when no window was open or no
        steps ran — not observed, so an idle window cannot poison the
        search)."""
        if self._baseline is None:
            return 0.0
        score = window_score(self._baseline, registry_view())
        self._baseline = None
        if score <= 0.0:
            return score
        metrics.inc_counter("sched.tune_windows")
        metrics.set_gauge("sched.tune_score", score)
        self._best_score = max(self._best_score, score)
        if self._backend_frozen is None:
            b = self.backend()
            self._backend_scores[b] = max(
                self._backend_scores.get(b, 0.0), score
            )
            metrics.set_gauge(
                "sched.tune_backend_score", score, {"backend": b}
            )
            if all(c in self._backend_scores
                   for c in self._backend_candidates):
                self._backend_frozen = max(
                    self._backend_scores, key=self._backend_scores.get
                )
                env.set_env("QUANT_BACKEND", self._backend_frozen)
                metrics.set_gauge(
                    "sched.tune_backend_frozen", 1.0,
                    {"backend": self._backend_frozen},
                )
        elif self._pipeline_frozen is None:
            p = self.pipeline()
            self._pipeline_scores[p] = max(
                self._pipeline_scores.get(p, 0.0), score
            )
            metrics.set_gauge(
                "sched.tune_pipeline_score", score, {"pipeline": p}
            )
            if all(c in self._pipeline_scores
                   for c in self._pipeline_candidates):
                self._pipeline_frozen = max(
                    self._pipeline_scores, key=self._pipeline_scores.get
                )
                env.set_env("XIR_PIPELINE", self._pipeline_frozen)
                metrics.set_gauge(
                    "sched.tune_pipeline_frozen", 1.0,
                    {"pipeline": self._pipeline_frozen},
                )
        elif self._onestep_frozen is None:
            m = self.onestep()
            self._onestep_scores[m] = max(
                self._onestep_scores.get(m, 0.0), score
            )
            metrics.set_gauge(
                "sched.tune_onestep_score", score, {"onestep": m}
            )
            if all(c in self._onestep_scores
                   for c in self._onestep_candidates):
                self._onestep_frozen = max(
                    self._onestep_scores, key=self._onestep_scores.get
                )
                env.set_env("ONESTEP", self._onestep_frozen)
                metrics.set_gauge(
                    "sched.tune_onestep_frozen", 1.0,
                    {"onestep": self._onestep_frozen},
                )
        elif self._lowering_frozen is None:
            lo = self.lowering()
            self._lowering_scores[lo] = max(
                self._lowering_scores.get(lo, 0.0), score
            )
            metrics.set_gauge(
                "sched.tune_lowering_score", score, {"lowering": lo}
            )
            if all(c in self._lowering_scores
                   for c in self._lowering_candidates):
                self._lowering_frozen = max(
                    self._lowering_scores, key=self._lowering_scores.get
                )
                metrics.set_gauge(
                    "sched.tune_lowering_frozen", 1.0,
                    {"lowering": self._lowering_frozen},
                )
        elif self._wire_frozen is None:
            w = self.wire()
            self._wire_scores[w] = max(self._wire_scores.get(w, 0.0), score)
            metrics.set_gauge(
                "sched.tune_wire_score", score, {"wire": w}
            )
            if all(c in self._wire_scores for c in self._wire_candidates):
                self._wire_frozen = max(
                    self._wire_scores, key=self._wire_scores.get
                )
                metrics.set_gauge(
                    "sched.tune_wire_frozen", 1.0,
                    {"wire": self._wire_frozen},
                )
        else:
            self.tuner.observe(score)
        self._maybe_store()
        return score

    def apply(self, schedule):
        """Stamp the current wire + lowering suggestions onto a built
        schedule, per bucket: buckets below ``wire_min_bucket_bytes``
        stay dense under a quantized suggestion (scale-sidecar overhead
        dominates tiny payloads), ineligible buckets downgrade via
        :func:`~horovod_tpu.sched.plan.eligible_wire`, and the lowering
        resolves through
        :func:`~horovod_tpu.sched.plan.resolve_lowering` (flat on a
        single-slice topology, cost-model choice under "auto")."""
        import dataclasses as _dc

        from .plan import eligible_wire, resolve_lowering

        w = self.wire()
        lo = self.lowering()
        buckets = []
        for b in schedule.buckets:
            req = w
            if w in ("int8", "fp8") and \
                    b.nbytes < self.wire_min_bucket_bytes:
                req = "off"
            buckets.append(_dc.replace(
                b,
                wire=eligible_wire(req, b.wire_dtypes),
                lowering=resolve_lowering(lo, b.nbytes,
                                          wire_dtypes=b.wire_dtypes),
            ))
        return _dc.replace(schedule, buckets=tuple(buckets))

    @property
    def converged(self) -> bool:
        return (
            self._wire_frozen is not None
            and self._lowering_frozen is not None
            and self._backend_frozen is not None
            and self._pipeline_frozen is not None
            and self._onestep_frozen is not None
            and self.tuner.converged
        )
