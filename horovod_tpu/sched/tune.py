"""Tune stage: drive the bucket-size knob from registry metrics.

``utils/autotune.FusionAutotuner`` owns the suggest/observe search
(the reference ParameterManager's Bayesian loop); what the scheduler
adds is the *scoring feed*: instead of a caller hand-timing windows,
scores are computed from the PR 2 metrics registry — the counters and
histograms the hot path already maintains (``train.steps``,
``train.step_seconds``, ``sched.bytes_per_step``) — so any training
loop that bumps standard metrics gets bucket-size tuning for free.

Usage::

    tuner = ScheduleTuner()
    while not tuner.converged:
        cfg = dataclasses.replace(cfg, bucket_bytes=tuner.bucket_bytes())
        tuner.begin_window()
        run_steps(window)                 # bumps train.* / sched.*
        tuner.end_window()
    cfg = dataclasses.replace(cfg, bucket_bytes=tuner.bucket_bytes())

Under ``HVD_TPU_AUTOTUNE=1`` the plan stage already follows the
``TrainStep`` autotune driver (``bucket_bytes=None`` defers to the
fusion-threshold override), so this class is for loops that want
registry-scored tuning without the wall-clock window driver.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from .. import metrics
from ..utils.autotune import FusionAutotuner


def registry_view() -> Dict[str, float]:
    """Snapshot the registry series the window score derives from."""
    hist = metrics.get_histogram("train.step_seconds")
    return {
        "steps": float(metrics.get_counter("train.steps")),
        "step_seconds_sum": float(hist["sum"]) if hist else 0.0,
        "bytes_per_step": float(
            metrics.get_gauge("sched.bytes_per_step") or 0.0
        ),
        "mono": time.monotonic(),
    }


def window_score(
    before: Dict[str, float], after: Dict[str, float]
) -> float:
    """Score one closed window from two registry snapshots.

    Primary: exchanged **bytes/sec** — steps/sec (from the
    ``train.steps`` counter over the ``train.step_seconds`` histogram
    sum, falling back to wall clock when the histogram is idle) times
    the planned ``sched.bytes_per_step`` gauge.  Without a bytes gauge
    the score degrades to plain steps/sec, which ranks candidates
    identically for a fixed model.
    """
    steps = after["steps"] - before["steps"]
    if steps <= 0:
        return 0.0
    dt = after["step_seconds_sum"] - before["step_seconds_sum"]
    if dt <= 0:
        dt = after["mono"] - before["mono"]
    rate = steps / max(dt, 1e-9)
    bytes_per_step = after["bytes_per_step"]
    return rate * bytes_per_step if bytes_per_step > 0 else rate


class ScheduleTuner:
    """FusionAutotuner wired to the scheduler's bucket-size knob with
    registry-fed window scores."""

    def __init__(self, **tuner_kwargs):
        self.tuner = FusionAutotuner(**tuner_kwargs)
        self._baseline: Optional[Dict[str, float]] = None

    def bucket_bytes(self) -> int:
        """Bucket-size suggestion for the next window (frozen winner
        after convergence)."""
        return self.tuner.threshold_bytes()

    def begin_window(self) -> None:
        # Prime the suggestion: FusionAutotuner only accepts an observe
        # for a threshold it suggested (suggest-before-observe contract).
        self.tuner.threshold_bytes()
        self._baseline = registry_view()

    def end_window(self) -> float:
        """Close the window: score it from the registry deltas and feed
        the tuner.  Returns the score (0.0 when no window was open or
        no steps ran — not observed, so an idle window cannot poison
        the search)."""
        if self._baseline is None:
            return 0.0
        score = window_score(self._baseline, registry_view())
        self._baseline = None
        if score > 0.0:
            self.tuner.observe(score)
            metrics.inc_counter("sched.tune_windows")
            metrics.set_gauge("sched.tune_score", score)
        return score

    @property
    def converged(self) -> bool:
        return self.tuner.converged
