"""Bucketed ZeRO-1: the scheduler's ``reduce_scatter+all_gather`` mode
with per-bucket sharded optimizer updates.

``optim/zero.zero_train_step`` already decomposes the exchange as one
whole-model ``psum_scatter -> shard update -> all_gather`` (following
arXiv:2004.13336).  This module re-cuts that pipeline at bucket
granularity using the plan stage: each bucket reduce-scatters as soon
as its gradients exist, runs the optimizer on its 1/N slice, and
all-gathers its updates — so the all-gather of bucket *k* overlaps the
reduce-scatter of bucket *k+1* instead of the whole model serializing
through three global collectives.  Optimizer state still shrinks
N-fold (each rank holds 1/N of every bucket).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from .. import metrics
from ..optim.zero import _state_spec
from ..runtime import WORLD_AXIS
from .plan import BucketSchedule, SchedConfig, build_schedule, current_config


@dataclass(frozen=True)
class _BucketLayout:
    """Host-side layout of one bucket's flat buffer."""

    indices: Tuple[int, ...]
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]  # elements per member leaf
    dtype: jnp.dtype
    n: int  # valid elements
    padded: int  # n rounded up to a shard-count multiple
    shard_len: int
    wire: str = "off"  # per-bucket wire format (plan.WIRE_CHOICES)
    # per-bucket lowering (plan.LOWER_CHOICES): "hier"/"hier_adasum"
    # shard over the ICI sub-axis only — k = slice_size shards,
    # replicated across slices — so the optimizer update and its
    # all_gather never cross DCN; only the 1/k gradient reduction
    # (plain sum for "hier", adaptive summation for "hier_adasum") does.
    lowering: str = "flat"
    shards: int = 0  # world (flat) or slice_size (hier)


def _layouts(
    params, world: int, cfg: SchedConfig
) -> Tuple[List[_BucketLayout], BucketSchedule]:
    leaves = jax.tree.leaves(params)
    sizes_bytes = [int(l.size) * jnp.dtype(l.dtype).itemsize for l in leaves]
    dtypes = [str(jnp.dtype(l.dtype)) for l in leaves]
    schedule = build_schedule(sizes_bytes, dtypes, cfg, axis_size=world)
    from ..topo import model as topo_model

    s_dcn, k_ici = topo_model.current().factor_axis(world)
    layouts = []
    for b in schedule.buckets:
        if len(b.wire_dtypes) != 1:
            raise ValueError(
                "bucketed ZeRO requires single-dtype buckets "
                f"(got {b.wire_dtypes}); pinned mixed-dtype groups are "
                "not supported here"
            )
        shapes = tuple(tuple(leaves[i].shape) for i in b.indices)
        sizes = tuple(
            int(leaves[i].size) for i in b.indices
        )
        n = sum(sizes)
        lowering = b.lowering if s_dcn > 1 else "flat"
        # Hier buckets shard over the ICI sub-axis only: k shards per
        # slice, the shard replicated across slices, so the optimizer
        # update and its all_gather stay on ICI.
        shards = k_ici if lowering in ("hier", "hier_adasum") else world
        unit = shards
        if b.wire in ("int8", "fp8"):
            # Quantized shards must stay block-aligned so the
            # post-update all_gather can re-quantize without repadding.
            from ..ops.quantized import quant_block

            unit = shards * quant_block()
        padded = -(-n // unit) * unit
        layouts.append(_BucketLayout(
            indices=b.indices, shapes=shapes, sizes=sizes,
            dtype=jnp.dtype(b.wire_dtypes[0]), n=n, padded=padded,
            shard_len=padded // shards, wire=b.wire,
            lowering=lowering, shards=shards,
        ))
    return layouts, schedule


def bucket_layouts(
    params, world: int, cfg: Optional[SchedConfig] = None
) -> List[_BucketLayout]:
    """Public layout rebuild for a given world size (the elastic
    remesh entry point): the deterministic host-side description of how
    ``bucketed_zero_step`` shards ``params``' buckets over ``world``
    ranks.  ``elastic/remesh.plan_reshard`` pairs the old and new
    worlds' layout lists to compute the shard exchange; the layouts are
    a pure function of (params metadata, world, cfg) so every rank —
    and the driver — derives the identical plan."""
    if cfg is None:
        cfg = current_config()
    layouts, _ = _layouts(params, world, cfg)
    return layouts


def _bucket_flat(leaves, layout: _BucketLayout) -> jax.Array:
    flat = jnp.concatenate(
        [leaves[i].reshape(-1) for i in layout.indices]
    ) if len(layout.indices) > 1 else leaves[layout.indices[0]].reshape(-1)
    if layout.padded != layout.n:
        flat = jnp.pad(flat, (0, layout.padded - layout.n))
    return flat


def _bucket_unflat(flat: jax.Array, layout: _BucketLayout):
    out, off = [], 0
    for shape, size in zip(layout.shapes, layout.sizes):
        out.append(flat[off:off + size].reshape(shape))
        off += size
    return out


def bucketed_zero_step(
    loss_fn,
    tx: optax.GradientTransformation,
    *,
    axis=WORLD_AXIS,
    cfg: Optional[SchedConfig] = None,
    pre_update=None,
):
    """Compiled SPMD step with bucket-granular ZeRO-1 sharding.

    Call convention matches ``optim.zero.zero_train_step``:
    ``step.init(params)`` then ``step(params, opt_state, batch) ->
    (params, opt_state, loss)``.  Params stay replicated; the optimizer
    state is a tuple of per-bucket states whose array leaves live
    sharded over ``axis`` (1/N per chip).  ``pre_update`` (e.g.
    ``optim.zero.clip_by_global_norm``) runs on the full list of
    gradient shards before any bucket's optimizer update — global
    reductions see every shard.

    ``cfg.wire`` (``HVD_TPU_SCHED_WIRE``): quantized buckets run the
    ZeRO pipeline end-to-end on the quantized wire — the per-bucket
    reduce-scatter quantizes ``g + r`` (EF residual in the bucket's
    state when ``cfg.wire_ef``), the sharded optimizer update consumes
    the dequantized **fp32** shard, and only the post-update
    ``all_gather`` re-quantizes.  A quantized bucket's state entry
    becomes ``{"tx": <inner state>, "ef": <residual>}``; with
    ``wire="off"`` the state structure is unchanged from PR 3.

    ``cfg.lowering`` (``HVD_TPU_TOPO_LOWER``): on a multi-slice
    topology, ``hier`` buckets shard over the **ICI sub-axis** — k =
    slice_size shards, replicated across slices — so the optimizer
    update and its all_gather never cross DCN; only the slice-local
    gradient shard's cross-slice sum does (and only that hop carries a
    compressed wire).  Optimizer state shrinks k-fold instead of
    N-fold: the slice-vs-world sharding trade documented in
    docs/topology.md.  ``hier_adasum`` buckets shard identically but
    the cross-slice hop adaptively combines the per-slice *mean*
    shards (Adasum, arXiv:2006.02924) before the sharded update — the
    large-batch lowering, docs/adasum.md.  Single-slice topologies
    resolve every bucket flat and reproduce the PR 3/4 behavior
    exactly.
    """
    from jax.sharding import PartitionSpec as P

    from .. import runtime as _rt

    if cfg is None:
        cfg = current_config()
    rt = _rt.get_runtime()
    mesh = rt.mesh
    world = rt.size
    meta: dict = {}

    def _set_layout(params_like):
        meta["layouts"], meta["schedule"] = _layouts(
            params_like, world, cfg
        )

    def _ef_on(lay: _BucketLayout) -> bool:
        # Hier buckets run EF-free: their quantization (if any) lives on
        # the cross-slice hop of the slice-summed shard, not on the
        # gradient, so a gradient-shaped residual has nothing to absorb.
        return (
            cfg.wire_ef and lay.wire in ("int8", "fp8")
            and lay.lowering not in ("hier", "hier_adasum")
        )

    def _shard_index(lay: _BucketLayout, idx):
        # Hier-family buckets shard over the ICI sub-axis: position
        # within the slice (slice-major device order, topo/ contract).
        if lay.lowering in ("hier", "hier_adasum"):
            return lax.rem(idx, lay.shards)
        return idx

    def _intra_groups():
        from ..topo import model as topo_model

        intra, _ = topo_model.current().axis_groups(world)
        return intra

    def init_body(params):
        leaves = jax.tree.leaves(params)
        idx = lax.axis_index(axis)
        states = []
        for lay in meta["layouts"]:
            flat = _bucket_flat(leaves, lay)
            shard = lax.dynamic_slice(
                flat, (_shard_index(lay, idx) * lay.shard_len,),
                (lay.shard_len,),
            )
            st = tx.init(shard)
            if _ef_on(lay):
                st = {"tx": st, "ef": jnp.zeros((lay.padded,), jnp.float32)}
            states.append(st)
        return tuple(states)

    def step_body(params, opt_states, batch):
        from ..ops.quantized import (
            quantized_all_gather,
            quantized_reduce_scatter,
        )
        from ..ops.traced import Sum

        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        gleaves, treedef = jax.tree.flatten(grads)
        pleaves = jax.tree.leaves(params)
        idx = lax.axis_index(axis)
        layouts = meta["layouts"]

        # Phase 1: per-bucket reduce-scatter, barrier-chained so buckets
        # issue in reverse-backward order and overlap the backward.
        # Quantized buckets ride the int8/fp8 wire (ops/quantized.py);
        # the dequant-accumulated shard is fp32 either way, so the
        # sharded optimizer update below always runs in full precision.
        #
        # Rail pipelining (xir/pipeline.py): when engaged, hier buckets
        # chain their ICI reduce-scatter on the ICI rail and their
        # cross-slice hop on the DCN rail — bucket i's DCN hop then
        # overlaps bucket i+1's ICI reduce-scatter.  hier_adasum and
        # flat buckets serialize against both rails (docs/adasum.md);
        # ordering-only, values bitwise-identical either way.
        from ..xir import pipeline as railpipe

        gshards = []
        new_residuals = []
        rails = railpipe.RailChain()
        use_rails = cfg.barriers and railpipe.engaged(
            meta["schedule"], world
        )
        pipe_overlaps = 0
        token = None
        intra = (
            _intra_groups()
            if any(lay.lowering in ("hier", "hier_adasum")
                   for lay in layouts) else None
        )
        for lay, st in zip(layouts, opt_states):
            g = _bucket_flat(gleaves, lay)
            if use_rails:
                bucket_rails = (
                    ("ici",) if lay.lowering == "hier"
                    else ("ici", "dcn")
                )
                (g,) = rails.tie([g], bucket_rails)
            elif cfg.barriers and token is not None:
                g, token = lax.optimization_barrier((g, token))
            if lay.lowering in ("hier", "hier_adasum"):
                # ICI reduce_scatter to the slice-local 1/k shard, then
                # the cross-slice hop over DCN — the only slow-network
                # leg, and the only one the bucket's wire compresses.
                # "hier" sums across slices (then /world = global
                # mean); "hier_adasum" adaptively combines the
                # per-slice means (arXiv:2006.02924) on the 1/k shard
                # before the sharded update.
                from ..topo import dcn_adasum, dcn_all_reduce

                shard = lax.psum_scatter(
                    g, axis, scatter_dimension=0, tiled=True,
                    axis_index_groups=intra,
                )
                if use_rails and lay.lowering == "hier":
                    # ICI phase done: release the ICI rail before the
                    # cross-slice hop so the next bucket's ICI
                    # reduce-scatter can overlap this bucket's DCN leg.
                    rails.bump(shard, ("ici",))
                    (shard,) = rails.tie([shard], ("dcn",))
                    pipe_overlaps += 1
                if lay.lowering == "hier_adasum":
                    shard = shard / lay.shards  # slice mean
                    shard = dcn_adasum(shard, axis, wire=lay.wire)
                else:
                    shard = dcn_all_reduce(shard, axis, wire=lay.wire)
                    shard = shard / world
                new_residuals.append(None)
            elif lay.wire in ("int8", "fp8"):
                if _ef_on(lay):
                    e = g.astype(jnp.float32) + st["ef"]
                    shard, r_new = quantized_reduce_scatter(
                        e, axis, op=Sum, wire=lay.wire, ef=True,
                    )
                    new_residuals.append(r_new)
                else:
                    shard = quantized_reduce_scatter(
                        g, axis, op=Sum, wire=lay.wire,
                    )
                    new_residuals.append(None)
                shard = shard / world
            else:
                shard = lax.psum_scatter(
                    g, axis, scatter_dimension=0, tiled=True
                ) / world
                new_residuals.append(None)
            if use_rails:
                rails.bump(
                    shard,
                    ("dcn",) if lay.lowering == "hier"
                    else ("ici", "dcn"),
                )
            elif cfg.barriers:
                token = shard.reshape(-1)[0]
            gshards.append(shard)
        if use_rails:
            metrics.inc_counter(
                "sched.pipeline.overlap_windows", max(pipe_overlaps - 1, 0)
            )
        if pre_update is not None:
            gshards = pre_update(gshards)

        # Phase 2: shard update + all-gather per bucket; only the
        # post-update gather re-quantizes on a quantized bucket.
        uleaves = [None] * len(gleaves)
        new_states = []
        for lay, shard, state, r_new in zip(
            layouts, gshards, opt_states, new_residuals
        ):
            tx_state = state["tx"] if _ef_on(lay) else state
            pflat = _bucket_flat(pleaves, lay)
            pshard = lax.dynamic_slice(
                pflat, (_shard_index(lay, idx) * lay.shard_len,),
                (lay.shard_len,),
            )
            ushard, tx_state = tx.update(
                shard.astype(lay.dtype), tx_state, pshard
            )
            if _ef_on(lay):
                new_states.append({"tx": tx_state, "ef": r_new})
            else:
                new_states.append(tx_state)
            if lay.lowering in ("hier", "hier_adasum"):
                # ICI-only gather: every slice holds the full shard
                # set, so the updated parameters reassemble without
                # touching DCN (dense — the wire compressed only the
                # gradient's cross-slice hop).
                uflat = lax.all_gather(
                    ushard, axis, tiled=True, axis_index_groups=intra
                )[:lay.n]
            elif lay.wire in ("int8", "fp8"):
                uflat = quantized_all_gather(
                    ushard, axis, wire=lay.wire
                )[:lay.n].astype(lay.dtype)
            else:
                uflat = lax.all_gather(ushard, axis, tiled=True)[:lay.n]
            for i, u in zip(lay.indices, _bucket_unflat(uflat, lay)):
                uleaves[i] = u
        updates = jax.tree.unflatten(treedef, uleaves)
        params = optax.apply_updates(params, updates)
        return params, tuple(new_states), lax.pmean(loss, axis)

    def state_spec():
        def abstract_init():
            states = []
            for lay in meta["layouts"]:
                st = tx.init(jnp.zeros((lay.shard_len,), lay.dtype))
                if _ef_on(lay):
                    st = {
                        "tx": st,
                        "ef": jnp.zeros((lay.padded,), jnp.float32),
                    }
                states.append(st)
            return tuple(states)

        return _state_spec(jax.eval_shape(abstract_init), axis)

    def _record():
        from .execute import record_wire_metrics

        sched = meta["schedule"]
        metrics.set_gauge("sched.buckets_per_step", len(sched))
        metrics.set_gauge("sched.bytes_per_step", sched.total_bytes)
        metrics.inc_counter("sched.zero_steps_built")
        record_wire_metrics(sched)

    class _Step:
        def __init__(self):
            self._fn = None

        @property
        def schedule(self) -> BucketSchedule:
            return meta["schedule"]

        def init(self, params):
            _set_layout(params)
            _record()
            f = jax.shard_map(
                init_body, mesh=mesh, in_specs=(P(),),
                out_specs=state_spec(), check_vma=False,
            )
            return jax.jit(f)(params)

        def __call__(self, params, opt_states, batch):
            if "layouts" not in meta:
                raise RuntimeError(
                    "bucketed_zero_step: call init(params) first"
                )
            if self._fn is None:
                specs = _state_spec(opt_states, axis)
                batch_spec = jax.tree.map(lambda _: P(axis), batch)
                self._fn = jax.jit(jax.shard_map(
                    step_body, mesh=mesh,
                    in_specs=(P(), specs, batch_spec),
                    out_specs=(P(), specs, P()),
                    check_vma=False,
                ), donate_argnums=(0, 1))
            return self._fn(params, opt_states, batch)

    return _Step()
