"""Persistent schedule store: winning tuner configs survive the job.

The reference ``ParameterManager`` re-learns the fusion knobs from
scratch every run — exploration cost is paid per *job*, even for the
10,000th identical one.  This store makes the converged answer durable:
a JSON file (``HVD_TPU_TUNE_DB``) mapping

    key = sha256(schedule ``signature()``, topology spec, jax version,
                 ``HVD_TPU_SCHED*/WIRE*/TOPO*`` knob fingerprint)

to the winning ``(bucket_bytes, wire, lowering)`` tuple and its window
score.  :class:`~horovod_tpu.sched.tune.ScheduleTuner` warm-starts
from a hit (``converged`` at window 0, zero exploration windows) and
writes back on convergence, so exploration is paid once per
(model, pod) pair — and the elastic driver serves the same entries
fleet-wide over ``GET/POST /schedules`` plus the rendezvous KV
(``runner/telemetry_http.py`` / ``elastic_driver.py``).

Staleness: every entry records the cost model's price for its choice
at write time.  On lookup the *current* (possibly re-fitted —
``topo/fit.py``) model re-prices it; disagreement beyond
``HVD_TPU_TUNE_STALE_FACTOR`` (default 4x, either direction) treats
the entry as a miss, so a pod whose measured links drifted re-explores
instead of trusting a schedule tuned for different hardware.

A corrupted or unreadable DB file is *never* fatal: it is ignored with
one warning and treated as empty (the file is rewritten on the next
converged run).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, Optional, Set

from .. import metrics
from ..utils import env
from ..utils.logging import get_logger

SCHEMA_VERSION = 1
DEFAULT_STALE_FACTOR = 4.0

# Env prefixes whose values change what a tuned schedule means: the
# scheduler/wire knobs, the topology model, and quantization block
# size.  Both spellings (HVD_TPU_ / legacy HOROVOD_) participate.
_KNOB_PREFIXES = ("SCHED", "WIRE", "TOPO", "QUANT")

# log-once guard for corrupted DB files (per path, process-wide)
_warned_paths: Set[str] = set()
_warn_lock = threading.Lock()


def knob_fingerprint(include_svc: bool = True) -> str:
    """Stable digest of every ``HVD_TPU_SCHED*/WIRE*/TOPO*/QUANT*``
    env knob (and its legacy ``HOROVOD_`` spelling): two processes with
    the same fingerprint plan identical schedules from identical
    metadata, so stored winners are only shared between them.

    The *resolved* quantized-wire backend is folded in explicitly (not
    just the raw env var): an unset ``HVD_TPU_QUANT_BACKEND`` and an
    explicit ``phase`` mean the same schedules and must share entries,
    while ``fused`` winners — whose exchange wall time has different
    constants — must never collide with phase ones.  The resolved
    service-fusion pair (``HVD_TPU_SVC_CYCLE_TIME`` /
    ``HVD_TPU_SVC_FUSION_THRESHOLD``, svc/fuse.py + svc/params.py)
    folds in the same resolved form — schedules tuned under different
    coalescing regimes have different wall-clock constants —
    EXCEPT when ``include_svc=False``: the service tuner's own DB
    entry records the pair as its *payload* and must stay addressable
    after pinning its winner into those very knobs."""
    items = []
    for k in sorted(os.environ):
        for head in ("HVD_TPU_", "HOROVOD_"):
            if k.startswith(head):
                tail = k[len(head):]
                # QUANT_BACKEND joins below in resolved form only, so
                # "unset" and an explicit default spelling agree.
                if (tail.startswith(_KNOB_PREFIXES)
                        and tail not in ("TUNE_DB", "QUANT_BACKEND")):
                    items.append((k, os.environ[k]))
                break
    try:
        from ..ops.quantized import quant_backend

        items.append(("HVD_TPU_QUANT_BACKEND(resolved)", quant_backend()))
    except Exception:
        pass
    try:
        # The RESOLVED accelerator backend family folds in the same
        # way, but only when it is not "tpu": every pre-registry DB
        # entry was tuned on the tpu family, so unset ≡ tpu must keep
        # the existing keys byte-identical, while "gpu" winners —
        # priced over NVLink/IB constants and the mosaic ring — must
        # never warm-start a TPU mesh (or vice versa).
        from ..backend import registry as _backend_registry

        fam = _backend_registry.family()
        if fam != "tpu":
            items.append(("HVD_TPU_BACKEND(resolved)", fam))
    except Exception:
        pass
    try:
        # The rail-pipeliner knob joins in resolved form for the same
        # reason as the backend: an unset HVD_TPU_XIR_PIPELINE and an
        # explicit "auto" plan identical schedules and share entries,
        # while "on" — whose split points come from the per-rail
        # bandwidths — keys distinctly.
        from ..xir import pipeline as _railpipe

        items.append(("HVD_TPU_XIR_PIPELINE(resolved)", _railpipe.mode()))
    except Exception:
        pass
    try:
        # Whole-step emission mode, resolved for the same reason:
        # "off" entries (per-unit dispatch wall clocks) must never
        # cross with "on"/"auto" ones (single-dispatch constants), and
        # unset/"auto" must agree with an explicit "auto".
        from ..xir import interp as _xinterp

        items.append(("HVD_TPU_ONESTEP(resolved)", _xinterp.onestep_mode()))
    except Exception:
        pass
    if include_svc:
        try:
            from ..svc import fuse as _svc_fuse, params as _svc_params

            items.append((
                "HVD_TPU_SVC_FUSION(resolved)",
                f"{_svc_fuse.fusion_threshold()}"
                f":{_svc_params.cycle_time_ms()!r}",
            ))
        except Exception:
            pass
    return hashlib.sha256(
        json.dumps(items, sort_keys=True).encode()
    ).hexdigest()[:16]


def topology_spec(topo=None) -> str:
    """Compact topology identity for the store key."""
    if topo is None:
        from ..topo import model as topo_model

        topo = topo_model.current()
    shape = "x".join(str(d) for d in topo.ici_shape)
    return f"{topo.num_slices}x{topo.slice_size}({shape})"


def jax_version() -> str:
    try:
        import jax

        return getattr(jax, "__version__", "unknown")
    except Exception:
        return "unknown"


def make_key(signature: Any,
             topo_spec: Optional[str] = None,
             jaxver: Optional[str] = None,
             knobs: Optional[str] = None,
             kind: str = "dense_grad") -> str:
    """The store key: sha256 over the five identity components.
    ``signature`` is any deterministic hashable — canonically a
    :meth:`~horovod_tpu.sched.plan.BucketSchedule.signature` tuple
    (``repr`` of nested int/str tuples is stable across processes) or
    an :meth:`~horovod_tpu.xir.ir.ExchangeProgram.signature`.

    ``kind`` is the workload discriminator (``xir.KINDS``): two
    different exchange shapes — say a dense-DP bucket schedule and a
    MoE all_to_all program — that happen to produce equal payload
    signatures must never share a DB entry, because their tuned
    (bucket_bytes, wire, lowering) answers mean different things."""
    payload = json.dumps({
        "sig": repr(signature),
        "kind": str(kind),
        "topo": topology_spec() if topo_spec is None else topo_spec,
        "jax": jax_version() if jaxver is None else jaxver,
        "knobs": knob_fingerprint() if knobs is None else knobs,
    }, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()


class ScheduleStore:
    """JSON-on-disk (or in-memory when ``path`` is None) map from store
    keys to winning schedule configs.  All mutating operations re-read
    the file and merge keep-best before writing, so concurrent workers
    sharing one DB converge on the best-scored entry instead of
    clobbering each other."""

    # Minimum entry shape accepted from disk / peer merges; subclasses
    # storing a different record kind (prof/baseline.py's
    # PerfBaselineStore) override this instead of re-implementing the
    # load/merge machinery.
    REQUIRED_KEYS = ("bucket_bytes", "wire", "lowering")

    @classmethod
    def _valid_entry(cls, e: Any) -> bool:
        return isinstance(e, dict) and all(k in e for k in cls.REQUIRED_KEYS)

    def __init__(self, path: Optional[str],
                 stale_factor: Optional[float] = None):
        self.path = path
        self.stale_factor = (
            env.get_float(env.TUNE_STALE_FACTOR, DEFAULT_STALE_FACTOR)
            if stale_factor is None else float(stale_factor)
        )
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict[str, Any]] = {}
        if path:
            self._entries = self._load()

    @classmethod
    def from_env(cls) -> Optional["ScheduleStore"]:
        """The store at ``HVD_TPU_TUNE_DB``, or None when unset — the
        unset behavior must be bit-identical to no store at all."""
        path = env.get_env(env.TUNE_DB)
        if not path:
            return None
        return cls(path)

    # ------------------------------------------------------------- io
    def _load(self) -> Dict[str, Dict[str, Any]]:
        try:
            with open(self.path) as fh:
                data = json.load(fh)
            entries = data.get("entries")
            if not isinstance(entries, dict):
                raise ValueError("missing 'entries' object")
            # shape-check each entry; drop garbage rather than crash
            good = {}
            for k, e in entries.items():
                if self._valid_entry(e):
                    good[str(k)] = e
            return good
        except FileNotFoundError:
            return {}
        except Exception as e:
            with _warn_lock:
                if self.path not in _warned_paths:
                    _warned_paths.add(self.path)
                    get_logger().warning(
                        "schedule store %s is unreadable (%s: %s); "
                        "ignoring it and starting empty",
                        self.path, type(e).__name__, e,
                    )
            metrics.inc_counter("sched.tune.db_corrupt")
            return {}

    def _save(self) -> None:
        if not self.path:
            return
        try:
            # merge keep-best with whatever landed on disk since load
            on_disk = self._load()
            with self._lock:
                for k, e in on_disk.items():
                    mine = self._entries.get(k)
                    if mine is None or (
                            e.get("score", 0.0) > mine.get("score", 0.0)):
                        self._entries[k] = e
                snap = dict(self._entries)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(
                    {"version": SCHEMA_VERSION, "entries": snap},
                    fh, sort_keys=True, indent=1,
                )
            os.replace(tmp, self.path)
        except Exception as e:
            get_logger().warning(
                "schedule store write to %s failed: %s", self.path, e
            )

    # ----------------------------------------------------------- api
    def entries(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return dict(self._entries)

    def lookup(self, key: str) -> Optional[Dict[str, Any]]:
        """The stored entry for ``key`` after stale validation, or
        None.  A stale entry (cost model now disagrees with the
        recorded price by more than ``stale_factor``) is dropped so
        the next convergence overwrites it."""
        with self._lock:
            entry = self._entries.get(key)
        if entry is None:
            return None
        if self._stale(entry):
            metrics.inc_counter("sched.tune.db_stale")
            get_logger().info(
                "schedule store: entry %s.. invalidated (cost model "
                "disagrees with recorded price beyond %.1fx)",
                key[:12], self.stale_factor,
            )
            with self._lock:
                self._entries.pop(key, None)
            return None
        entry = dict(entry)
        entry["hits"] = int(entry.get("hits", 0)) + 1
        with self._lock:
            self._entries[key] = entry
        return entry

    def _stale(self, entry: Dict[str, Any]) -> bool:
        recorded = entry.get("pred_cost_s")
        if not recorded or recorded <= 0 or self.stale_factor <= 0:
            return False
        current = self._price(entry)
        if current is None or current <= 0:
            return False
        ratio = max(current, recorded) / min(current, recorded)
        return ratio > self.stale_factor

    @staticmethod
    def _price(entry: Dict[str, Any]) -> Optional[float]:
        """Today's cost-model price of one stored choice (an allreduce
        of ``bucket_bytes`` under the stored lowering over the world
        axis) — the fitted model when one exists."""
        try:
            from ..topo import model as topo_model

            lowering = entry.get("lowering", "flat")
            if lowering not in ("flat", "hier", "hier_adasum"):
                lowering = "flat"
            return topo_model.current().estimate_cost(
                "all_reduce", int(entry["bucket_bytes"]), lowering,
            )
        except Exception:
            return None

    def record(self, key: str, *, bucket_bytes: int, wire: str,
               lowering: str, score: float,
               meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Insert/update the winner for ``key`` (keep-best by score
        against any concurrent writer) and persist."""
        entry = {
            "bucket_bytes": int(bucket_bytes),
            "wire": str(wire),
            "lowering": str(lowering),
            "score": float(score),
            "pred_cost_s": self._price({
                "bucket_bytes": bucket_bytes, "lowering": lowering,
            }),
            "topo": topology_spec(),
            "jax": jax_version(),
            "updated": time.time(),
            "hits": 0,
        }
        if meta:
            entry["meta"] = meta
        with self._lock:
            prev = self._entries.get(key)
            if prev is not None and (
                    prev.get("score", 0.0) > entry["score"]):
                entry = prev
            self._entries[key] = entry
        self._save()
        metrics.inc_counter("sched.tune.db_store")
        return entry

    def merge(self, entries: Dict[str, Dict[str, Any]]) -> int:
        """Fold another store's entries in (keep-best by score); the
        fleet-serving primitive behind ``POST /schedules`` and the
        driver's KV collection.  Returns how many keys changed."""
        if not isinstance(entries, dict):
            return 0
        changed = 0
        with self._lock:
            for k, e in entries.items():
                if not self._valid_entry(e):
                    continue
                mine = self._entries.get(k)
                if mine is None or (
                        e.get("score", 0.0) > mine.get("score", 0.0)):
                    self._entries[str(k)] = e
                    changed += 1
        if changed:
            self._save()
        return changed
