"""``jax.grad``-boundary hooks: observe the backward readiness order.

The reference learns gradient readiness at runtime — each parameter's
grad-accumulator hook enqueues an allreduce request the moment its
gradient materializes (``horovod/torch/optimizer.py:506``,
``tensorflow/__init__.py:759``).  Under XLA there is no runtime hook,
but the *trace* of the backward pass visits cotangents in backward
order: wrapping every parameter leaf in a ``custom_vjp`` identity whose
bwd rule records its leaf index reproduces the reference's readiness
order at trace time.  The plan stage consumes that order so buckets are
scheduled reverse-backward — the first bucket's collective can issue
while the backward for earlier layers is still running.

Trace-time, not run-time: the taps fire once per compile while
``jax.value_and_grad`` transposes the graph, cost nothing in the
compiled program (identity is folded away), and leave numerics
untouched.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Any, List, Optional

import jax

_state = threading.local()


def _orders() -> List[List[int]]:
    stack = getattr(_state, "stack", None)
    if stack is None:
        stack = _state.stack = []
    return stack


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tap(x, idx):
    return x


def _tap_fwd(x, idx):
    return x, None


def _tap_bwd(idx, res, ct):
    # Runs while the backward pass is being traced — in backward order.
    stack = _orders()
    if stack:
        stack[-1].append(idx)
    return (ct,)


_tap.defvjp(_tap_fwd, _tap_bwd)


def begin_capture() -> None:
    """Open a capture frame; nested captures (re-traces inside a trace)
    stack."""
    _orders().append([])


def end_capture(n_leaves: int) -> Optional[List[int]]:
    """Close the innermost frame; returns the observed backward order of
    leaf indices (first recorded = first gradient ready), or ``None``
    when the observation is incomplete (a leaf's cotangent never flowed
    through its tap — e.g. an unused parameter)."""
    stack = _orders()
    if not stack:
        return None
    seen = stack.pop()
    order = list(dict.fromkeys(seen))
    if len(order) != n_leaves:
        return None
    return order


def tap_params(params: Any) -> Any:
    """Wrap every leaf of ``params`` in an identity whose cotangent
    records the leaf's flatten index during the backward trace."""
    leaves, treedef = jax.tree.flatten(params)
    tapped = [_tap(leaf, i) for i, leaf in enumerate(leaves)]
    return jax.tree.unflatten(treedef, tapped)


def capturing_loss(loss_fn):
    """Wrap ``loss_fn(params, *rest)`` so a grad of the wrapped function
    records the backward order of ``params`` leaves.  The recorded order
    is published via :func:`consume_order` for the plan stage (same
    trace, later in the step body)."""

    def wrapped(params, *rest):
        leaves = jax.tree.leaves(params)
        begin_capture()
        _state.pending = (len(leaves), True)
        return loss_fn(tap_params(params), *rest)

    return wrapped


def consume_order(n_leaves: int) -> Optional[List[int]]:
    """Hand the most recent capture to the plan stage (clears it).
    Returns ``None`` when no capture is pending or it is incomplete /
    sized for a different pytree."""
    pending = getattr(_state, "pending", None)
    if pending is None:
        return None
    _state.pending = None
    expected, _ = pending
    order = end_capture(expected)
    if order is None or expected != n_leaves:
        return None
    return order


def reset() -> None:
    """Drop any un-consumed capture state (test isolation / aborted
    traces)."""
    _state.stack = []
    _state.pending = None
