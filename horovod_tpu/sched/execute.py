"""Execute stage: emit the planned per-bucket collectives.

Where the reference's background loop dispatches one fused NCCL call
per cycle tick (``operations.cc:381`` ``RunLoopOnce``), this stage
emits one XLA collective per bucket into the traced step, sequenced by
``lax.optimization_barrier``: bucket *k+1*'s inputs are barrier-tied to
a scalar carried out of bucket *k*'s collective, so XLA must issue the
collectives in schedule order — and, because each bucket depends only
on its own gradient leaves (plus that token), the latency-hiding
scheduler is free to overlap bucket *k*'s wire time with the backward
compute still producing bucket *k+1*'s gradients.

Observability: ``sched.*`` counters/gauges/histograms in the metrics
registry (see docs/observability.md) plus one ``SCHED_EXCHANGE``
timeline lane event per bucket.
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Sequence

import jax
from jax import lax

from .. import metrics
from ..ops import fusion
from .plan import BucketSchedule, SchedConfig, build_schedule, current_config


def _chain(tensors: List[jax.Array], token: Optional[jax.Array]):
    """Tie ``tensors`` to the previous bucket's ``token`` through an
    optimization barrier (identity on values; ordering-only edge)."""
    if token is None:
        return tensors, None
    out = lax.optimization_barrier(tuple(tensors) + (token,))
    return list(out[:-1]), out[-1]


def exchange(
    wire: Sequence[jax.Array],
    schedule: BucketSchedule,
    reduce_flat: Callable[[jax.Array], jax.Array],
    *,
    barriers: bool = True,
    timeline: Any = None,
) -> List[jax.Array]:
    """Run ``schedule`` over the ``wire`` leaves: per bucket, flatten ->
    one collective per dtype (via ``reduce_flat``) -> slice back out.
    Returns the reduced leaves in original flatten order.

    Values are independent of bucketing: XLA collectives are
    elementwise over the buffer, so concat order never changes a sum —
    the scheduler is numerics-identical to the single-fused-exchange
    legacy path by construction.
    """
    t0 = time.perf_counter()
    reduced: List[jax.Array] = list(wire)
    token: Optional[jax.Array] = None
    for bi, bucket in enumerate(schedule.buckets):
        ins = [wire[i] for i in bucket.indices]
        if barriers:
            ins, token = _chain(ins, token)
        if timeline is not None:
            timeline.record_op(
                f"bucket{bi}[n={len(bucket.indices)},"
                f"dtype={'+'.join(bucket.wire_dtypes)}]",
                "SCHED_EXCHANGE", bucket.nbytes,
            )
        with jax.named_scope(
            f"hvd_sched_bucket{bi}_{bucket.nbytes}B"
        ):
            flats, meta = fusion.flatten_group(ins)
            outs = [reduce_flat(f) for f in flats]
        if barriers:
            # Scalar carried out of this bucket's collective: the next
            # bucket's inputs are barrier-tied to it, enforcing issue
            # order without touching values.
            token = outs[0].reshape(-1)[0]
        for i, t in zip(bucket.indices, fusion.unflatten_group(outs, meta)):
            reduced[i] = t
        metrics.observe(
            "sched.bytes_per_bucket", bucket.nbytes,
            buckets=metrics.BYTES_BUCKETS,
        )
    metrics.inc_counter("sched.plans")
    metrics.inc_counter("sched.buckets", len(schedule))
    metrics.inc_counter("sched.exchange_bytes", schedule.total_bytes)
    metrics.set_gauge("sched.buckets_per_step", len(schedule))
    metrics.set_gauge("sched.bytes_per_step", schedule.total_bytes)
    # Emission cost of the exchange subgraph (trace-time under jit; the
    # device-side wire time is the profiler's/timeline's to attribute).
    metrics.observe("sched.exchange_seconds", time.perf_counter() - t0)
    return reduced


def reduce_scatter_flat(
    f: jax.Array,
    *,
    axis,
    average: bool,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    shard_update: Optional[Callable[[jax.Array], jax.Array]] = None,
) -> jax.Array:
    """One bucket's ``reduce_scatter + all_gather`` exchange
    (arXiv:2004.13336's weight-update sharding decomposition): each
    rank receives its 1/N shard of the reduced buffer, optionally runs
    ``shard_update`` on it (the ZeRO-1 hook — optimizer work on the
    slice), and all-gathers the result.  Total wire bytes equal one
    allreduce; with ``shard_update`` the optimizer state and update
    math shrink N-fold.
    """
    from ..ops.traced import _scale

    world = lax.axis_size(axis)
    n = f.shape[0]
    pad = (-n) % world
    g = _scale(f, prescale_factor)
    if pad:
        g = jax.numpy.pad(g, (0, pad))
    shard = lax.psum_scatter(g, axis, scatter_dimension=0, tiled=True)
    if average:
        postscale_factor = postscale_factor / world
    shard = _scale(shard, postscale_factor)
    if shard_update is not None:
        shard = shard_update(shard)
    out = lax.all_gather(shard, axis, tiled=True)
    return out[:n] if pad else out


def sync_gradients_bucketed(
    grads: Any,
    param_shard_axes: Any = None,
    axes: Sequence[str] = (),
    cfg: Optional[SchedConfig] = None,
) -> Any:
    """Scheduler-mode :func:`~horovod_tpu.parallel.grad_sync.sync_gradients`.

    Same per-parameter rule (pmean over every sync axis the parameter is
    NOT sharded over; divide by the axis size where it IS sharded), but
    the pmeans are exchanged as a bucketed pipeline: leaves are grouped
    by their mean-axes set (a hybrid mesh has one group per distinct
    ``param_shard_axes`` combination), each group planned into
    reverse-backward buckets, one fused ``pmean`` per bucket.  The
    divide-by-axis-size scaling stays per-leaf and local (no wire
    traffic), so hybrid-mesh semantics are respected exactly —
    bit-for-bit equal to the per-leaf path (pmean is elementwise).
    """
    from ..parallel.grad_sync import _parse
    from ..parallel.tensor import _axis_present

    if cfg is None:
        cfg = current_config()
    present = tuple(a for a in axes if _axis_present(a))
    leaves, treedef = jax.tree.flatten(grads)
    if param_shard_axes is None:
        shard_strs = [""] * len(leaves)
    else:
        shard_strs = jax.tree.flatten(param_shard_axes)[0]
        if len(shard_strs) != len(leaves):
            raise ValueError(
                "param_shard_axes structure does not match grads"
            )

    out = list(leaves)
    groups: dict = {}  # mean_over tuple -> [leaf indices]
    for i, s in enumerate(shard_strs):
        sharded = _parse(s)
        mean_over = tuple(a for a in present if a not in sharded)
        if mean_over:
            groups.setdefault(mean_over, []).append(i)

    for mean_over, idxs in groups.items():
        sizes = [
            int(leaves[i].size) * leaves[i].dtype.itemsize for i in idxs
        ]
        dtypes = [str(leaves[i].dtype) for i in idxs]
        schedule = build_schedule(sizes, dtypes, cfg)
        reduced = exchange(
            [leaves[i] for i in idxs], schedule,
            lambda f, _m=mean_over: lax.pmean(f, _m),
            barriers=cfg.barriers,
        )
        for i, t in zip(idxs, reduced):
            out[i] = t

    for i, s in enumerate(shard_strs):
        sharded = _parse(s)
        scale = 1
        for a in present:
            if a in sharded:
                scale *= lax.axis_size(a)
        if scale != 1:
            out[i] = out[i] / scale
    return jax.tree.unflatten(treedef, out)
