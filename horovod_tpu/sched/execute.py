"""Execute stage: emit the planned per-bucket collectives.

Where the reference's background loop dispatches one fused NCCL call
per cycle tick (``operations.cc:381`` ``RunLoopOnce``), this stage
emits one XLA collective per bucket into the traced step, sequenced by
``lax.optimization_barrier``: bucket *k+1*'s inputs are barrier-tied to
a scalar carried out of bucket *k*'s collective, so XLA must issue the
collectives in schedule order — and, because each bucket depends only
on its own gradient leaves (plus that token), the latency-hiding
scheduler is free to overlap bucket *k*'s wire time with the backward
compute still producing bucket *k+1*'s gradients.

Observability: ``sched.*`` counters/gauges/histograms in the metrics
registry (see docs/observability.md) plus one ``SCHED_EXCHANGE``
timeline lane event per bucket.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .. import metrics
from ..ops import fusion
from .plan import (
    Bucket,
    BucketSchedule,
    SchedConfig,
    build_schedule,
    current_config,
    wire_bytes,
)


def _chain(tensors: List[jax.Array], token: Optional[jax.Array]):
    """Tie ``tensors`` to the previous bucket's ``token`` through an
    optimization barrier (identity on values; ordering-only edge)."""
    if token is None:
        return tensors, None
    out = lax.optimization_barrier(tuple(tensors) + (token,))
    return list(out[:-1]), out[-1]


class _PhasedBucket:
    """One decomposable bucket's rail phases: ``rs`` (ICI
    reduce-scatter), ``mid`` (the DCN leg — hop, or RS + shard update +
    AG in reduce_scatter mode), ``ag`` (ICI all-gather back to the flat
    buffer).  Built per bucket by :func:`hier_phase_factory`; the three
    closures emit exactly the ops the serialized reducer would, so a
    phase-emitted bucket is bitwise identical to its serialized twin."""

    __slots__ = ("rs", "mid", "ag")

    def __init__(self, rs, mid, ag):
        self.rs, self.mid, self.ag = rs, mid, ag


def hier_phase_factory(
    *,
    axis,
    average: bool = False,
    rs_mode: bool = False,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    shard_update: Optional[Callable[[jax.Array], jax.Array]] = None,
    pmean: bool = False,
):
    """Phase decomposition of the hier bucket reducers for the rail
    pipeliner (``xir/pipeline.py``): returns ``factory(bucket) ->
    _PhasedBucket | None``.  ``None`` marks the bucket serialized (not
    ``hier``, mixed dtypes, or a non-factoring axis) and the exchange
    falls back to its ``reduce_flat`` for that bucket.

    Three flavors, each mirroring its serialized reducer op for op:

    * default — :func:`hier_allreduce_flat` (prescale → staged Sum →
      postscale/average);
    * ``rs_mode=True`` — :func:`hier_reduce_scatter_flat` on floating
      buckets (the RS+AG decomposition with the optional ZeRO
      ``shard_update`` riding the DCN leg), allreduce flavor otherwise;
    * ``pmean=True`` — ``hierarchical_all_reduce(op=Average)``, the
      ``sync_gradients_bucketed`` hier pmean.
    """
    from ..ops.traced import _scale
    from ..topo import (
        dcn_all_gather_phase,
        dcn_reduce_scatter_phase,
        dcn_sum_phase,
        ici_all_gather_phase,
        ici_reduce_scatter_phase,
        phase_context,
    )

    def factory(bucket: Bucket) -> Optional[_PhasedBucket]:
        from ..xir import pipeline as railpipe

        if not railpipe.decomposable(bucket):
            return None
        ctx = phase_context(axis)
        if ctx is None:
            return None
        wire = bucket.wire
        k, s = ctx["k"], ctx["s"]
        n_axis = k * s
        cell: dict = {}
        floating = bool(bucket.wire_dtypes) and jnp.issubdtype(
            jnp.dtype(bucket.wire_dtypes[0]), jnp.floating
        )

        if pmean:
            # hierarchical_all_reduce(op=Average, wire): slice sum →
            # DCN sum → gather, /(s*k) before the dtype cast.
            def rs(f):
                cell["V"], cell["dtype"] = f.size, f.dtype
                flat = f.reshape(-1)
                pad = (-f.size) % k
                if pad:
                    flat = jnp.pad(flat, (0, pad))
                return ici_reduce_scatter_phase(flat, ctx)

            def mid(shard):
                return dcn_sum_phase(shard, ctx, wire)

            def ag(shard):
                out = ici_all_gather_phase(shard, ctx)[: cell["V"]]
                out = out / (s * k)
                return out.astype(cell["dtype"])

            return _PhasedBucket(rs, mid, ag)

        if rs_mode and floating:
            # hier_reduce_scatter_flat: both DCN legs (and the shard
            # update between them) ride the DCN rail.
            quant = wire in ("int8", "fp8")
            unit = k * s
            if quant:
                from ..ops.quantized import quant_block

                unit *= quant_block()

            def rs(f):
                cell["n"] = f.shape[0]
                g = _scale(f, prescale_factor)
                flat = g.reshape(-1)
                pad = (-flat.shape[0]) % unit
                if pad:
                    flat = jnp.pad(flat, (0, pad))
                return ici_reduce_scatter_phase(flat, ctx)

            def mid(shard_k):
                shard = dcn_reduce_scatter_phase(shard_k, ctx, wire)
                post = (
                    postscale_factor / n_axis if average
                    else postscale_factor
                )
                shard = _scale(shard, post)
                if shard_update is not None:
                    shard = shard_update(shard)
                return dcn_all_gather_phase(shard, ctx, wire)

            def ag(out_k):
                return ici_all_gather_phase(out_k, ctx)[: cell["n"]]

            return _PhasedBucket(rs, mid, ag)

        # hier_allreduce_flat: prescale → staged Sum → postscale.
        def rs(f):
            cell["V"], cell["dtype"] = f.size, f.dtype
            g = _scale(f, prescale_factor)
            flat = g.reshape(-1)
            pad = (-g.size) % k
            if pad:
                flat = jnp.pad(flat, (0, pad))
            return ici_reduce_scatter_phase(flat, ctx)

        def mid(shard):
            return dcn_sum_phase(shard, ctx, wire)

        def ag(shard):
            out = ici_all_gather_phase(shard, ctx)[: cell["V"]]
            out = out.astype(cell["dtype"])
            post = (
                postscale_factor / n_axis if average else postscale_factor
            )
            return _scale(out, post)

        return _PhasedBucket(rs, mid, ag)

    return factory


def record_wire_metrics(schedule: BucketSchedule) -> None:
    """Publish the per-wire payload gauges for one planned exchange:
    ``sched.wire_bytes{wire=}`` (bytes/step on each wire format) and
    ``sched.compression_ratio`` (dense bytes / wire bytes — 1.0 when
    every bucket is dense)."""
    per_wire: dict = {}
    for b in schedule.buckets:
        per_wire[b.wire] = per_wire.get(b.wire, 0) + wire_bytes(b)
    total_wire = sum(per_wire.values())
    for w, nbytes in per_wire.items():
        metrics.set_gauge("sched.wire_bytes", nbytes, {"wire": w})
        metrics.inc_counter(f"sched.wire_bytes.{w}", nbytes)
    if total_wire > 0:
        metrics.set_gauge(
            "sched.compression_ratio", schedule.total_bytes / total_wire
        )
    record_topo_metrics(schedule)


def record_topo_metrics(
    schedule: BucketSchedule, axis_size: Optional[int] = None
) -> None:
    """Publish the network-class split of one planned exchange from the
    topology byte model: ``topo.dcn_bytes`` / ``topo.ici_bytes``
    (per-rank bytes/step over each network, gauges + running counters)
    and the per-lowering bucket counts.  A hier bucket's DCN figure is
    flat's divided by the ICI degree, so the gauge ratio reads the
    subsystem's savings directly."""
    from ..topo import model as topo_model

    topo = topo_model.current()
    dcn = ici = 0
    per_lower: dict = {}
    for b in schedule.buckets:
        by = topo.lowering_bytes(
            "all_reduce", b.nbytes, b.lowering, axis_size
        )
        dcn += by["dcn"]
        ici += by["ici"]
        per_lower[b.lowering] = per_lower.get(b.lowering, 0) + 1
    metrics.set_gauge("topo.dcn_bytes", dcn)
    metrics.set_gauge("topo.ici_bytes", ici)
    metrics.inc_counter("topo.dcn_bytes_total", dcn)
    metrics.inc_counter("topo.ici_bytes_total", ici)
    for lo, count in per_lower.items():
        metrics.set_gauge("topo.buckets", count, {"lowering": lo})


def _bucket_timeline(timeline, bi: int, bucket: Bucket) -> None:
    """One SCHED_EXCHANGE event per bucket plus TOPO_PHASE lane events
    for hierarchical buckets (shared by the serialized and pipelined
    emissions — a slow hop stays identifiable either way)."""
    timeline.record_op(
        f"bucket{bi}[n={len(bucket.indices)},"
        f"dtype={'+'.join(bucket.wire_dtypes)},"
        f"wire={bucket.wire},lower={bucket.lowering}]",
        "SCHED_EXCHANGE", wire_bytes(bucket),
    )
    if bucket.lowering in ("hier", "hier_adasum"):
        from ..topo import model as topo_model

        by = topo_model.current().lowering_bytes(
            "all_reduce", bucket.nbytes, bucket.lowering
        )
        dcn_phase = (
            "adasum_dcn" if bucket.lowering == "hier_adasum" else "ar_dcn"
        )
        for phase, nb in (
            ("rs_ici", by["ici"] // 2),
            (dcn_phase, by["dcn"]),
            ("ag_ici", by["ici"] // 2),
        ):
            timeline.record_op(f"bucket{bi}.{phase}", "TOPO_PHASE", nb)


def _exchange_pipelined(
    wire: Sequence[jax.Array],
    schedule: BucketSchedule,
    reduce_flat: Callable[[jax.Array, Bucket], jax.Array],
    phases: Callable[[Bucket], Optional[_PhasedBucket]],
    program: Any,
    timeline: Any,
) -> List[jax.Array]:
    """Rail-chained emission (``HVD_TPU_XIR_PIPELINE``): decomposable
    buckets split into ICI/DCN phases chained per rail — the ICI chain
    runs RS(i), RS(i+1), AG(i), RS(i+2), AG(i+1), … while each DCN hop
    chains only against the previous DCN hop, so bucket *i*'s
    cross-slice hop overlaps bucket *i+1*'s reduce-scatter and bucket
    *i−1*'s all-gather.  Non-decomposable buckets serialize against
    BOTH rails (full ordering, exactly their serialized behavior).
    Values are bitwise identical to the serialized emission: every
    barrier is identity and per-bucket op order never changes."""
    import dataclasses as _dc

    from .. import trace
    from ..xir import pipeline as railpipe

    reduced: List[jax.Array] = list(wire)
    rail = railpipe.RailChain()
    # (bi, bucket, meta, phased, dcn_out) — bucket i's ICI all-gather,
    # held back until bucket i+1's reduce-scatter has entered the ICI
    # chain (the overlap window the pipeline.overlap_windows counter
    # reads).
    deferred = None
    overlaps = 0

    def _flush():
        nonlocal deferred
        bi_, bucket_, meta_, pb_, mid_ = deferred
        deferred = None
        (mid_,) = rail.tie([mid_], ("ici",))
        with trace.span(
            f"bucket{bi_}.ag", "bucket", bucket=bi_,
            nbytes=bucket_.nbytes,
        ), jax.named_scope(
            f"hvd_sched_bucket{bi_}_{bucket_.nbytes}B_{bucket_.wire}"
            f"_{bucket_.lowering}_ag"
        ):
            out = pb_.ag(mid_)
        rail.bump(out, ("ici",))
        for i, t in zip(
            bucket_.indices, fusion.unflatten_group([out], meta_)
        ):
            reduced[i] = t

    for bi, bucket in enumerate(schedule.buckets):
        if program is not None:
            op = program.ops[bi]
            bucket = _dc.replace(
                bucket, wire=op.wire, lowering=op.lowering
            )
        pb = phases(bucket)
        ins = [wire[i] for i in bucket.indices]
        if timeline is not None:
            _bucket_timeline(timeline, bi, bucket)
        if pb is None:
            # Serialized bucket inside the pipeline: flush the pending
            # all-gather first, then order against both rails.
            if deferred is not None:
                _flush()
            ins = rail.tie(ins, ("ici", "dcn"))
            with trace.span(
                f"bucket{bi}", "bucket", bucket=bi,
                nbytes=bucket.nbytes, wire=bucket.wire,
                lowering=bucket.lowering,
            ), jax.named_scope(
                f"hvd_sched_bucket{bi}_{bucket.nbytes}B_{bucket.wire}"
                f"_{bucket.lowering}"
            ):
                flats, meta = fusion.flatten_group(ins)
                outs = [reduce_flat(f, bucket) for f in flats]
            rail.bump(outs[0], ("ici", "dcn"))
            for i, t in zip(
                bucket.indices, fusion.unflatten_group(outs, meta)
            ):
                reduced[i] = t
        else:
            ins = rail.tie(ins, ("ici",))
            flats, meta = fusion.flatten_group(ins)
            with trace.span(
                f"bucket{bi}.rs", "bucket", bucket=bi,
                nbytes=bucket.nbytes,
            ), jax.named_scope(
                f"hvd_sched_bucket{bi}_{bucket.nbytes}B_{bucket.wire}"
                f"_{bucket.lowering}_rs"
            ):
                shard = pb.rs(flats[0])
            rail.bump(shard, ("ici",))
            if deferred is not None:
                # Bucket i's RS is on the chain; bucket i-1's AG may
                # now follow it — its DCN hop already ran concurrently.
                _flush()
                overlaps += 1
            (shard,) = rail.tie([shard], ("dcn",))
            with trace.span(
                f"bucket{bi}.dcn", "bucket", bucket=bi,
                nbytes=bucket.nbytes, wire=bucket.wire,
            ), jax.named_scope(
                f"hvd_sched_bucket{bi}_{bucket.nbytes}B_{bucket.wire}"
                f"_{bucket.lowering}_dcn"
            ):
                mid = pb.mid(shard)
            rail.bump(mid, ("dcn",))
            deferred = (bi, bucket, meta, pb, mid)
        metrics.observe(
            "sched.bytes_per_bucket", bucket.nbytes,
            buckets=metrics.BYTES_BUCKETS,
        )
    if deferred is not None:
        _flush()
    metrics.inc_counter("sched.pipeline.overlap_windows", overlaps)
    metrics.set_gauge("sched.pipeline.overlap_windows_per_step", overlaps)
    return reduced


def exchange(
    wire: Sequence[jax.Array],
    schedule: BucketSchedule,
    reduce_flat: Callable[[jax.Array, Bucket], jax.Array],
    *,
    barriers: bool = True,
    timeline: Any = None,
    kind: str = "dense_grad",
    axis: Any = None,
    phases: Optional[Callable[[Bucket], Optional[_PhasedBucket]]] = None,
    epilogue: Optional[Callable[[List[jax.Array]], Any]] = None,
) -> Any:
    """Run ``schedule`` over the ``wire`` leaves: per bucket, flatten ->
    one collective per dtype (via ``reduce_flat(flat, bucket)``) ->
    slice back out.  Returns the reduced leaves in original flatten
    order.

    Under ``HVD_TPU_XIR=on`` (the default) the schedule is first
    expressed as an explicit exchange program
    (:func:`~horovod_tpu.xir.from_schedule` — one op per bucket
    carrying the (wire, lowering, bucket, ef) tuple that used to be
    implicit in ``Bucket`` fields), and this loop interprets that
    program: the op record is authoritative for the per-bucket
    dispatch.  The ops are constructed from the very bucket fields
    they replace, so the emitted collectives — and therefore f32
    dense losses — are bitwise identical with the IR on or off
    (tests/test_xir.py pins this).

    Values are independent of bucketing: XLA collectives are
    elementwise over the buffer, so concat order never changes a sum —
    with a dense wire the scheduler is numerics-identical to the
    single-fused-exchange legacy path by construction.  A bucket whose
    ``wire`` is quantized trades that identity for compressed wire
    bytes (the reducer routes it through ops/quantized.py).

    ``epilogue`` opts the schedule into whole-step emission
    (``HVD_TPU_ONESTEP``, docs/exchange_ir.md "Whole-step emission"):
    when :func:`~horovod_tpu.xir.interp.onestep_engaged` folds, the
    caller's post-exchange closure (decompress + optimizer update) is
    stitched onto the reduced leaves *inside* this traced emission via
    :func:`~horovod_tpu.xir.interp.emit_step`, so XLA compiles
    exchange + update as ONE program instead of two dispatch units.
    With ``epilogue`` the return value is ``(reduced, result)`` where
    ``result`` is the closure's output when the fold engaged and
    ``None`` when it did not — a ``None`` result means the caller must
    apply the epilogue itself, which keeps the ``off`` path's jaxpr
    construction literally identical to the epilogue-free call.  The
    fold is ordering-only (optimization_barrier ties), so f32 dense
    losses stay bitwise identical in every mode.

    ``phases`` (a :func:`hier_phase_factory`) opts the schedule into
    the rail pipeliner: when ``HVD_TPU_XIR_PIPELINE`` engages
    (``xir.pipeline.engaged``), decomposable hier buckets emit as
    ICI/DCN phases chained **per rail** instead of per bucket, so
    bucket *i*'s cross-slice DCN hop overlaps bucket *i+1*'s ICI
    reduce-scatter and bucket *i−1*'s ICI all-gather.  Ordering-only:
    f32 dense losses are bitwise identical to the serialized emission
    in every mode.
    """
    from .. import trace, xir
    from ..xir import interp as _xinterp
    from ..xir import pipeline as railpipe

    t0 = time.perf_counter()
    program = (
        xir.from_schedule(schedule, kind=kind, axis=axis)
        if xir.enabled() else None
    )
    if program is not None and program.trace is None and trace.enabled():
        # Trace correlation for the whole submission: the context rides
        # the program into the service (queue/negotiation/cache spans)
        # and back out to the rail-phase spans emitted below.  A caller
        # context that predates tenant tagging is back-filled with the
        # process tenant so the per-tenant phase attribution
        # (docs/multitenant.md) covers the dense-grad pipeline too.
        ctx = trace.current_context() or trace.new_context(f"sched.{kind}")
        if not ctx.tenant:
            default = trace.context.default_tenant()
            if default:
                import dataclasses as _dc

                ctx = _dc.replace(ctx, tenant=default)
        program = program.with_trace(ctx)
    if program is not None:
        # Async exchange service (svc/): the bucketed pipeline is a
        # *producer* — the program is submitted to the service at
        # trace time and the (ResponseCache-resolved) copy it hands
        # back drives the emission below.  A repeat signature costs
        # zero re-lowering; a dead service falls back to the local
        # program (svc.fallback_sync).  The ops are equal either way,
        # so HVD_TPU_SVC on/off stays bitwise identical on this path.
        from .. import svc as _svc

        if _svc.enabled():
            axis_size_hint = None
            if isinstance(axis, str):
                try:
                    axis_size_hint = lax.axis_size(axis)
                except Exception:
                    axis_size_hint = None
            program = _svc.get_service().submit_traced(
                program, producer=f"sched.{kind}",
                axis_size=axis_size_hint, store=False,
            )
        metrics.inc_counter("xir.programs")
        metrics.inc_counter(f"xir.programs.{kind}")
        metrics.inc_counter("xir.ops", len(program.ops))
        # Emission accounting for the profiling plane (trace-time, like
        # the counters above): how many collective programs — and ops —
        # one step's schedule emits, per source.
        from .. import prof

        prof.note_emission(f"sched.{kind}", len(program.ops))
    axis_size = None
    if isinstance(axis, str):
        try:
            axis_size = lax.axis_size(axis)
        except Exception:
            axis_size = None
    # Rail pipelining (xir/pipeline.py): needs barriers (the rails ARE
    # barrier chains), a phase factory from the caller, and an engaged
    # knob/cost-model verdict.  Values are bitwise identical either
    # way; the branch only changes ordering edges.
    pipelined = bool(
        barriers and phases is not None
        and railpipe.engaged(schedule, axis_size)
    )
    metrics.set_gauge(
        "sched.pipeline.engaged", 1.0 if pipelined else 0.0,
        {"mode": railpipe.mode()},
    )
    # Whole-step fold (xir/interp.py onestep): the update closure
    # counts as one more dispatch unit on top of the bucket chain, so
    # auto engages whenever there is anything to stitch it to.
    onestep_fold = bool(
        epilogue is not None
        and _xinterp.onestep_engaged(len(schedule) + 1)
    )
    metrics.set_gauge(
        "sched.onestep.engaged", 1.0 if onestep_fold else 0.0,
        {"mode": _xinterp.onestep_mode()},
    )
    epilogue_result = None
    with trace.span(
        f"exchange.{kind}", "exchange",
        ctx=program.trace if program is not None else None,
        kind=kind, buckets=len(schedule), pipelined=pipelined,
        onestep=int(onestep_fold),
    ):
        if pipelined:
            reduced = _exchange_pipelined(
                wire, schedule, reduce_flat, phases, program, timeline
            )
        else:
            reduced = list(wire)
            token: Optional[jax.Array] = None
            for bi, bucket in enumerate(schedule.buckets):
                if program is not None:
                    # Interpret the program: the op record drives the
                    # bucket's dispatch (equal to the plan's fields by
                    # construction).
                    op = program.ops[bi]
                    bucket = dataclasses.replace(
                        bucket, wire=op.wire, lowering=op.lowering
                    )
                ins = [wire[i] for i in bucket.indices]
                if barriers:
                    ins, token = _chain(ins, token)
                if timeline is not None:
                    _bucket_timeline(timeline, bi, bucket)
                with trace.span(
                    f"bucket{bi}", "bucket", bucket=bi,
                    nbytes=bucket.nbytes, wire=bucket.wire,
                    lowering=bucket.lowering,
                ), jax.named_scope(
                    f"hvd_sched_bucket{bi}_{bucket.nbytes}B_{bucket.wire}"
                    f"_{bucket.lowering}"
                ):
                    flats, meta = fusion.flatten_group(ins)
                    outs = [reduce_flat(f, bucket) for f in flats]
                if barriers:
                    # Scalar carried out of this bucket's collective:
                    # the next bucket's inputs are barrier-tied to it,
                    # enforcing issue order without touching values.
                    token = outs[0].reshape(-1)[0]
                for i, t in zip(
                    bucket.indices, fusion.unflatten_group(outs, meta)
                ):
                    reduced[i] = t
                metrics.observe(
                    "sched.bytes_per_bucket", bucket.nbytes,
                    buckets=metrics.BYTES_BUCKETS,
                )
        if onestep_fold:
            # Stitch the caller's decompress+update closure onto the
            # reduced leaves INSIDE this emission: one traced region,
            # one dispatch unit (the exec span prof/hostgap.py counts
            # once under onestep).
            epilogue_result = _xinterp.emit_step(
                reduced, epilogue, src=f"sched.{kind}"
            )
    metrics.inc_counter("sched.plans")
    metrics.inc_counter("sched.buckets", len(schedule))
    metrics.inc_counter("sched.exchange_bytes", schedule.total_bytes)
    metrics.set_gauge("sched.buckets_per_step", len(schedule))
    metrics.set_gauge("sched.bytes_per_step", schedule.total_bytes)
    record_wire_metrics(schedule)
    # Emission cost of the exchange subgraph (trace-time under jit; the
    # device-side wire time is the profiler's/timeline's to attribute).
    metrics.observe("sched.exchange_seconds", time.perf_counter() - t0)
    if epilogue is not None:
        return reduced, epilogue_result
    return reduced


def quantized_exchange_flat(
    f: jax.Array,
    *,
    axis,
    average: bool,
    wire: str,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    shard_update: Optional[Callable[[jax.Array], jax.Array]] = None,
    residual: Optional[jax.Array] = None,
    process_set=None,
):
    """One bucket's quantized ``reduce_scatter + all_gather`` exchange
    (the ops/quantized.py phase primitives on a flat buffer): blockwise
    quantize → ``all_to_all`` wire → fp32 dequant-accumulate shard →
    optional ``shard_update`` (the ZeRO-1 hook, fed **fp32**) →
    re-quantize → tiled ``all_gather`` → dequant.

    ``residual`` engages error feedback: the wire carries
    ``quantize(f·prescale + residual)`` and the new residual
    ``e − dequant(q)`` is returned alongside (None ⇒ no EF, returns
    ``(out, None)``).  Serves both scheduler modes — for a quantized
    bucket the RS+AG decomposition *is* the allreduce.
    """
    from ..ops.quantized import (
        quantized_all_gather,
        quantized_reduce_scatter,
    )
    from ..ops.traced import Sum, _scale

    n = f.shape[0]
    g = _scale(f.astype(jnp.float32), prescale_factor)
    if residual is not None:
        g = g + residual.astype(jnp.float32)
        shard, r_new = quantized_reduce_scatter(
            g, axis, op=Sum, process_set=process_set, wire=wire, ef=True,
        )
    else:
        shard = quantized_reduce_scatter(
            g, axis, op=Sum, process_set=process_set, wire=wire,
        )
        r_new = None
    world = lax.axis_size(axis) if process_set is None else None
    if world is None:
        from ..ops.quantized import _axis_groups

        world = _axis_groups(axis, process_set)[1]
    if average:
        postscale_factor = postscale_factor / world
    shard = _scale(shard, postscale_factor)
    if shard_update is not None:
        shard = shard_update(shard)
    out = quantized_all_gather(
        shard, axis, process_set=process_set, wire=wire
    )[:n]
    return out.astype(f.dtype), r_new


def bf16_wire(reduce_dense: Callable[[jax.Array], jax.Array]):
    """Wrap a dense flat reducer with a bf16 cast around the wire (the
    per-bucket ``wire="bf16"`` lowering — same scheme as
    ``Compression.bf16`` but chosen per bucket by the plan/tuner).  The
    casts run as single VMEM-tiled kernels
    (``ops/pallas_kernels.cast_buffer``, the reference's ScaleBuffer
    device kernel) instead of separate astype + multiply HLOs; values
    are identical to a plain astype pair."""

    def reduce(f: jax.Array) -> jax.Array:
        if not jnp.issubdtype(f.dtype, jnp.floating) \
                or f.dtype == jnp.bfloat16:
            return reduce_dense(f)
        from ..ops.pallas_kernels import cast_buffer

        return cast_buffer(reduce_dense(cast_buffer(f, jnp.bfloat16)),
                           f.dtype)

    return reduce


def reduce_scatter_flat(
    f: jax.Array,
    *,
    axis,
    average: bool,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    shard_update: Optional[Callable[[jax.Array], jax.Array]] = None,
) -> jax.Array:
    """One bucket's ``reduce_scatter + all_gather`` exchange
    (arXiv:2004.13336's weight-update sharding decomposition): each
    rank receives its 1/N shard of the reduced buffer, optionally runs
    ``shard_update`` on it (the ZeRO-1 hook — optimizer work on the
    slice), and all-gathers the result.  Total wire bytes equal one
    allreduce; with ``shard_update`` the optimizer state and update
    math shrink N-fold.
    """
    from ..ops.traced import _scale

    world = lax.axis_size(axis)
    n = f.shape[0]
    pad = (-n) % world
    g = _scale(f, prescale_factor)
    if pad:
        g = jax.numpy.pad(g, (0, pad))
    shard = lax.psum_scatter(g, axis, scatter_dimension=0, tiled=True)
    if average:
        postscale_factor = postscale_factor / world
    shard = _scale(shard, postscale_factor)
    if shard_update is not None:
        shard = shard_update(shard)
    out = lax.all_gather(shard, axis, tiled=True)
    return out[:n] if pad else out


def hier_allreduce_flat(
    f: jax.Array,
    *,
    axis,
    average: bool,
    wire: str = "off",
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> jax.Array:
    """One bucket's hierarchical allreduce (the ``lowering="hier"``
    exchange in ``HVD_TPU_SCHED_MODE=allreduce``): intra-slice
    reduce_scatter over ICI → cross-slice all_reduce over DCN on the
    1/k shard → intra-slice all_gather (topo/hierarchical.py).  A
    quantized/bf16 ``wire`` compresses only the DCN hop."""
    from ..ops.traced import Sum as _Sum, _scale
    from ..topo import hierarchical_all_reduce

    n = lax.axis_size(axis)
    g = _scale(f, prescale_factor)
    out = hierarchical_all_reduce(g, axis, op=_Sum, wire=wire)
    if average:
        postscale_factor = postscale_factor / n
    return _scale(out, postscale_factor)


def hier_adasum_flat(
    f: jax.Array,
    *,
    axis,
    average: bool,
    wire: str = "off",
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> jax.Array:
    """One bucket's hierarchical-Adasum exchange (the
    ``lowering="hier_adasum"`` bucket in either ``HVD_TPU_SCHED_MODE``):
    intra-slice sum over ICI → Adasum's adaptive combination across
    slices on the 1/k DCN shard → intra-slice all_gather
    (topo/hierarchical.py).  ``average=True`` combines per-slice *mean*
    gradients (the reference postscale semantics); a quantized/bf16
    ``wire`` compresses only the DCN gather, EF-free like ``hier``."""
    from ..ops.traced import Average as _Avg, Sum as _Sum, _scale
    from ..topo import hierarchical_adasum_all_reduce

    g = _scale(f, prescale_factor)
    out = hierarchical_adasum_all_reduce(
        g, axis, op=(_Avg if average else _Sum), wire=wire
    )
    return _scale(out, postscale_factor)


def hier_reduce_scatter_flat(
    f: jax.Array,
    *,
    axis,
    average: bool,
    wire: str = "off",
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    shard_update: Optional[Callable[[jax.Array], jax.Array]] = None,
) -> jax.Array:
    """One bucket's hierarchical ``reduce_scatter + all_gather``
    exchange (``HVD_TPU_SCHED_MODE=reduce_scatter`` under
    ``lowering="hier"``): both phases stage through the ICI/DCN
    hierarchy, ``shard_update`` (the ZeRO-1 hook) runs on the
    1/(s·k) shard between them, and only the cross-slice hops carry a
    compressed ``wire``.  The shard layout is the hierarchy's own and
    is inverted exactly by the matching all_gather, so the composed
    result equals the flat decomposition elementwise."""
    from ..ops.traced import Sum as _Sum, _scale
    from ..topo import (
        hierarchical_all_gather,
        hierarchical_reduce_scatter,
    )

    n = f.shape[0]
    world = lax.axis_size(axis)
    g = _scale(f, prescale_factor)
    shard = hierarchical_reduce_scatter(g, axis, op=_Sum, wire=wire)
    if average:
        postscale_factor = postscale_factor / world
    shard = _scale(shard, postscale_factor)
    if shard_update is not None:
        shard = shard_update(shard)
    out = hierarchical_all_gather(shard, axis, wire=wire)
    return out[:n]


def sync_gradients_bucketed(
    grads: Any,
    param_shard_axes: Any = None,
    axes: Sequence[str] = (),
    cfg: Optional[SchedConfig] = None,
    *,
    residuals: Any = None,
):
    """Scheduler-mode :func:`~horovod_tpu.parallel.grad_sync.sync_gradients`.

    Same per-parameter rule (pmean over every sync axis the parameter is
    NOT sharded over; divide by the axis size where it IS sharded), but
    the pmeans are exchanged as a bucketed pipeline: leaves are grouped
    by their mean-axes set (a hybrid mesh has one group per distinct
    ``param_shard_axes`` combination), each group planned into
    reverse-backward buckets, one fused ``pmean`` per bucket.  The
    divide-by-axis-size scaling stays per-leaf and local (no wire
    traffic), so hybrid-mesh semantics are respected exactly —
    bit-for-bit equal to the per-leaf path (pmean is elementwise) when
    the wire is dense.

    ``cfg.wire`` (``HVD_TPU_SCHED_WIRE``): quantized buckets whose
    mean-axes set is a *single* axis route through the quantized RS+AG
    primitives; multi-axis pmean groups stay dense (the all_to_all
    phase has no multi-axis form).  ``residuals`` — a pytree matching
    ``grads`` — engages error feedback on those quantized buckets; the
    call then returns ``(synced, new_residuals)`` for the caller's
    state (see docs/quantization.md).
    """
    from ..parallel.grad_sync import _parse
    from ..parallel.tensor import _axis_present

    if cfg is None:
        cfg = current_config()
    present = tuple(a for a in axes if _axis_present(a))
    leaves, treedef = jax.tree.flatten(grads)
    res_leaves = None
    if residuals is not None:
        res_leaves = jax.tree.flatten(residuals)[0]
        if len(res_leaves) != len(leaves):
            raise ValueError("residuals structure does not match grads")
    if param_shard_axes is None:
        shard_strs = [""] * len(leaves)
    else:
        shard_strs = jax.tree.flatten(param_shard_axes)[0]
        if len(shard_strs) != len(leaves):
            raise ValueError(
                "param_shard_axes structure does not match grads"
            )

    out = list(leaves)
    res_out = list(res_leaves) if res_leaves is not None else None
    groups: dict = {}  # mean_over tuple -> [leaf indices]
    for i, s in enumerate(shard_strs):
        sharded = _parse(s)
        mean_over = tuple(a for a in present if a not in sharded)
        if mean_over:
            groups.setdefault(mean_over, []).append(i)

    for mean_over, idxs in groups.items():
        sizes = [
            int(leaves[i].size) * leaves[i].dtype.itemsize for i in idxs
        ]
        dtypes = [str(leaves[i].dtype) for i in idxs]
        # Quantized wire needs one named axis for its all_to_all phase;
        # so does the hierarchical lowering (its groups factor one
        # axis) — multi-axis pmean groups stay flat and dense.
        wire_req = cfg.wire
        if wire_req in ("int8", "fp8") and len(mean_over) != 1:
            wire_req = "off"
        lower_req = cfg.lowering if len(mean_over) == 1 else "flat"
        schedule = build_schedule(
            sizes, dtypes, cfg, wire=wire_req, lowering=lower_req,
            axis_size=(
                lax.axis_size(mean_over[0]) if len(mean_over) == 1
                else None
            ),
        )

        def reduce_flat(f, bucket, _m=mean_over, _idxs=idxs):
            # bucket.indices are positions in this group's leaf list;
            # _idxs maps them back to global flatten indices.
            if bucket.lowering == "hier_adasum" and len(_m) == 1:
                # Hierarchical Adasum pmean: slice means combined
                # adaptively across slices; the bucket's wire rides
                # only the DCN gather, EF-free like hier.
                from ..ops.traced import Average as _Avg
                from ..topo import hierarchical_adasum_all_reduce

                return hierarchical_adasum_all_reduce(
                    f, _m[0], op=_Avg, wire=bucket.wire
                )
            if bucket.lowering == "hier" and len(_m) == 1:
                # Hierarchical pmean: the ICI/DCN staging with the
                # bucket's wire on the DCN hop only.  EF residuals do
                # not apply here — the quantization error lives on the
                # slice-summed 1/k shard, not the gradient — so hier
                # quantized buckets run EF-free (docs/topology.md).
                from ..ops.traced import Average as _Avg
                from ..topo import hierarchical_all_reduce

                return hierarchical_all_reduce(
                    f, _m[0], op=_Avg, wire=bucket.wire
                )
            if bucket.wire in ("int8", "fp8"):
                res_flat = None
                if res_out is not None:
                    bucket_res = [res_out[_idxs[j]] for j in bucket.indices]
                    rf, rmeta = fusion.flatten_group(bucket_res)
                    res_flat = rf[0]
                red, r_new = quantized_exchange_flat(
                    f, axis=_m[0], average=True, wire=bucket.wire,
                    residual=res_flat,
                )
                if r_new is not None:
                    for j, r in zip(
                        bucket.indices,
                        fusion.unflatten_group([r_new], rmeta),
                    ):
                        res_out[_idxs[j]] = r.astype(
                            res_out[_idxs[j]].dtype
                        )
                return red
            if bucket.wire == "bf16":
                return bf16_wire(lambda x: lax.pmean(x, _m))(f)
            return lax.pmean(f, _m)

        reduced = exchange(
            [leaves[i] for i in idxs], schedule, reduce_flat,
            barriers=cfg.barriers,
            axis=mean_over[0] if len(mean_over) == 1 else tuple(mean_over),
            # Rail pipelining for hier pmean buckets: the factory's
            # pmean flavor replicates hierarchical_all_reduce(Average)
            # phase for phase, so engaged == serialized bitwise.
            phases=(
                hier_phase_factory(axis=mean_over[0], pmean=True)
                if len(mean_over) == 1 else None
            ),
        )
        for i, t in zip(idxs, reduced):
            out[i] = t

    for i, s in enumerate(shard_strs):
        sharded = _parse(s)
        scale = 1
        for a in present:
            if a in sharded:
                scale *= lax.axis_size(a)
        if scale != 1:
            out[i] = out[i] / scale
    synced = jax.tree.unflatten(treedef, out)
    if res_out is not None:
        return synced, jax.tree.unflatten(treedef, res_out)
    return synced
