"""Plan stage: build a :class:`BucketSchedule` from gradient metadata.

The reference's scheduling state lives in the controller loop: tensors
become ready in backward order, ``FuseResponses`` fuses consecutive
ready responses (``controller.cc:793``), and the cycle dispatches one
fused collective per tick.  Under XLA the whole step is one program, so
the plan is computed host-side at trace time and *is* the schedule: an
ordered tuple of buckets, each a set of gradient-leaf indices that
share one wire collective.

Ordering: buckets are emitted in **reverse-backward** order — the order
gradients become available during the backward pass (last layer first),
observed by the ``hooks`` module's grad-boundary taps when available,
else assumed to be the reversed pytree flatten order.  Combined with
``lax.optimization_barrier`` sequencing in the execute stage, this hands
XLA's latency-hiding scheduler a chain of collectives it can overlap
with the remaining backward compute.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from ..ops import fusion
from ..utils import env


# Per-bucket wire formats the plan stage can assign.  "off" keeps the
# bucket on the dense (or compressor-cast) wire; "bf16" casts the
# bucket's flat buffer around the collective; "int8"/"fp8" route the
# bucket through the quantized phase primitives (ops/quantized.py).
WIRE_CHOICES = ("off", "bf16", "int8", "fp8")

# Per-bucket lowerings the plan stage can assign.  "flat" is today's
# single-collective exchange; "hier" stages it as intra-slice
# reduce_scatter (ICI) -> cross-slice all_reduce (DCN, 1/k payload) ->
# intra-slice all_gather (topo/hierarchical.py); "hier_adasum" keeps
# hier's staging but combines across slices with Adasum's adaptive
# summation (arXiv:2006.02924) — float buckets on cross-slice
# topologies only, and never picked by "auto" (it changes the
# reduction algorithm; it is requested by knob / tuner / the Adasum
# optimizer preset).  The sum-preserving pair is chosen per bucket by
# the topology cost model under HVD_TPU_TOPO_LOWER=auto.
LOWER_CHOICES = ("flat", "hier", "hier_adasum")


def _canon_lowering(lowering: str) -> str:
    lo = (lowering or "auto").strip().lower()
    if lo in ("off", "none", "0", "false", "no", ""):
        lo = "flat"
    if lo in ("on", "1", "true", "yes", "hierarchical"):
        lo = "hier"
    if lo == "adasum":
        lo = "hier_adasum"
    if lo not in LOWER_CHOICES + ("auto",):
        raise ValueError(
            f"HVD_TPU_TOPO_LOWER must be auto|flat|hier|hier_adasum, "
            f"got {lowering!r}"
        )
    return lo


def _canon_wire_choice(wire: str) -> str:
    w = (wire or "off").strip().lower()
    if w in ("none", "0", "false", "no", ""):
        w = "off"
    if w == "e4m3":
        w = "fp8"
    if w not in WIRE_CHOICES:
        raise ValueError(
            f"HVD_TPU_SCHED_WIRE must be one of {WIRE_CHOICES}, "
            f"got {wire!r}"
        )
    return w


@dataclasses.dataclass(frozen=True)
class SchedConfig:
    """Knobs of the bucketed overlap scheduler (``HVD_TPU_SCHED*``)."""

    enabled: bool = True
    mode: str = "allreduce"  # "allreduce" | "reduce_scatter"
    bucket_bytes: Optional[int] = None  # None -> fusion threshold knob
    look_ahead: int = 3
    barriers: bool = True
    capture_order: bool = True
    wire: str = "off"  # "off" | "bf16" | "int8" | "fp8"
    wire_ef: bool = True  # error-feedback residuals for quantized wires
    # "auto" | "flat" | "hier" | "hier_adasum" (HVD_TPU_TOPO_LOWER)
    lowering: str = "auto"

    def __post_init__(self):
        if self.mode not in ("allreduce", "reduce_scatter"):
            raise ValueError(
                f"HVD_TPU_SCHED_MODE must be 'allreduce' or "
                f"'reduce_scatter', got {self.mode!r}"
            )
        object.__setattr__(self, "wire", _canon_wire_choice(self.wire))
        object.__setattr__(self, "lowering", _canon_lowering(self.lowering))

    @classmethod
    def from_env(cls) -> "SchedConfig":
        raw = (env.get_env(env.SCHED, "on") or "on").strip().lower()
        enabled = raw not in ("off", "0", "false", "no")
        bucket_bytes = env.get_int(env.SCHED_BUCKET_BYTES, -1)
        return cls(
            enabled=enabled,
            mode=(env.get_env(env.SCHED_MODE, "allreduce") or "allreduce")
            .strip().lower(),
            bucket_bytes=None if bucket_bytes < 0 else bucket_bytes,
            look_ahead=env.get_int(env.SCHED_LOOK_AHEAD, 3),
            barriers=env.get_bool(env.SCHED_BARRIERS, True),
            capture_order=env.get_bool(env.SCHED_CAPTURE_ORDER, True),
            wire=env.get_env(env.SCHED_WIRE, "off") or "off",
            wire_ef=env.get_bool(env.SCHED_WIRE_EF, True),
            lowering=env.get_env(env.TOPO_LOWER, "auto") or "auto",
        )


# Trace-time config override (the fusion-threshold override pattern):
# tests and probe variants pin a config without touching the env.
_config_override: Optional[SchedConfig] = None


def set_config_override(cfg: Optional[SchedConfig]) -> None:
    global _config_override
    _config_override = cfg


def current_config() -> SchedConfig:
    return (
        _config_override if _config_override is not None
        else SchedConfig.from_env()
    )


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One fused exchange: leaf ``indices`` (original flatten order)
    sharing a wire collective of ``nbytes`` total.  ``wire`` is the
    bucket's wire format (``WIRE_CHOICES``): the plan requests it, the
    execute stage lowers it (quantized formats through the
    ops/quantized.py phase primitives)."""

    indices: Tuple[int, ...]
    nbytes: int
    wire_dtypes: Tuple[str, ...]  # distinct dtypes, flatten order
    pinned: bool = False  # from an explicit user group
    wire: str = "off"
    # Exchange lowering (LOWER_CHOICES): "flat" = one collective,
    # "hier" = the ICI/DCN two-level staging.  The plan requests it
    # from the topology cost model; the execute stage lowers it (and
    # downgrades to flat where the reduction shape cannot factor).
    lowering: str = "flat"


@dataclasses.dataclass(frozen=True)
class BucketSchedule:
    """Ordered exchange plan for one gradient pytree."""

    buckets: Tuple[Bucket, ...]
    mode: str
    total_bytes: int

    def __len__(self) -> int:
        return len(self.buckets)

    def signature(self) -> Tuple:
        """Hashable identity: two schedules with equal signatures emit
        identical exchange programs (determinism tests key on this)."""
        return (
            self.mode,
            tuple((b.indices, b.nbytes, b.wire_dtypes, b.pinned, b.wire,
                   b.lowering)
                  for b in self.buckets),
        )


def build_schedule(
    sizes_bytes: Sequence[int],
    dtypes: Sequence[str],
    cfg: Optional[SchedConfig] = None,
    *,
    order: Optional[Sequence[int]] = None,
    pinned: Sequence[Sequence[int]] = (),
    wire: Optional[str] = None,
    lowering: Optional[str] = None,
    axis_size: Optional[int] = None,
) -> BucketSchedule:
    """Plan the exchange for leaves of ``sizes_bytes``/``dtypes``.

    ``order`` is the backward-readiness order of leaf indices (first
    element = first gradient available); ``None`` assumes the reversed
    flatten order (parameters registered last finish their backward
    first).  ``pinned`` buckets (explicit user groups,
    ``DistributedOptimizer(groups=...)``) fuse atomically and are
    emitted where their *earliest-ready* member falls in the order.

    ``wire`` overrides ``cfg.wire`` as the requested per-bucket wire
    format; each bucket gets it only when eligible
    (:func:`eligible_wire` — quantized wires need a single floating
    dtype), else falls back to ``"off"`` for that bucket.

    ``lowering`` overrides ``cfg.lowering`` (``HVD_TPU_TOPO_LOWER``):
    ``"auto"`` asks the topology cost model per bucket — large buckets
    on a multi-slice topology go ``"hier"``, sub-threshold ones stay
    ``"flat"`` (``axis_size`` sizes the reduction axis for the model;
    None prices the full world).  On a single-slice topology every
    bucket is ``"flat"``, so the schedule — and the emitted program —
    is identical to the pre-topology one.

    Pure function of its arguments plus the process-wide topology
    (identical on every rank — env-forced or discovered from the same
    ``jax.devices()`` order): same metadata + config -> identical
    schedule (plan determinism is load-bearing — every SPMD rank must
    emit the same collectives in the same order).
    """
    if cfg is None:
        cfg = current_config()
    wire = _canon_wire_choice(cfg.wire if wire is None else wire)
    lowering = _canon_lowering(
        cfg.lowering if lowering is None else lowering
    )
    if cfg.bucket_bytes is None and lowering in ("auto", "hier"):
        # Rail pipeliner split points (HVD_TPU_XIR_PIPELINE=on only —
        # "auto" is reorder-only so the plan stays identical): pick the
        # bucket size whose equal-split schedule the max-of-rails model
        # prices cheapest under the fitted per-rail bandwidths.
        from ..xir import pipeline as railpipe

        pipe_bytes = railpipe.plan_bucket_bytes(
            sum(int(s) for s in sizes_bytes), axis_size
        )
        if pipe_bytes is not None:
            cfg = dataclasses.replace(cfg, bucket_bytes=pipe_bytes)
    n = len(sizes_bytes)
    if order is None:
        order = range(n - 1, -1, -1)
    order = [i for i in order if 0 <= i < n]
    if len(set(order)) != n:
        # Incomplete / duplicated observation: fall back to the assumed
        # reverse-backward order rather than dropping leaves.
        order = list(range(n - 1, -1, -1))

    pinned_set = set()
    pinned_buckets: List[Tuple[int, Bucket]] = []
    rank_of = {leaf: pos for pos, leaf in enumerate(order)}
    for group in pinned:
        idx = tuple(int(i) for i in group)
        if not idx:
            continue
        pinned_set.update(idx)
        pinned_buckets.append((
            min(rank_of[i] for i in idx),
            _make_bucket(idx, sizes_bytes, dtypes, pinned=True,
                         wire=wire, lowering=lowering,
                         axis_size=axis_size),
        ))

    free = [i for i in order if i not in pinned_set]
    planned = fusion.bucket_plan(
        [sizes_bytes[i] for i in free],
        [dtypes[i] for i in free],
        cfg.bucket_bytes,
        look_ahead=cfg.look_ahead,
    )
    planned_buckets: List[Tuple[int, Bucket]] = []
    for b in planned:
        idx = tuple(sorted(free[j] for j in b))
        planned_buckets.append((
            min(rank_of[i] for i in idx),
            _make_bucket(idx, sizes_bytes, dtypes, wire=wire,
                         lowering=lowering, axis_size=axis_size),
        ))

    ordered = [
        b for _, b in sorted(
            pinned_buckets + planned_buckets, key=lambda p: p[0]
        )
    ]
    return BucketSchedule(
        buckets=tuple(ordered),
        mode=cfg.mode,
        total_bytes=sum(b.nbytes for b in ordered),
    )


def eligible_wire(wire: str, wire_dtypes: Sequence[str]) -> str:
    """Downgrade a requested wire format to what the bucket supports.

    Quantized wires (int8/fp8) need one floating dtype per bucket (the
    residual/scale bookkeeping tracks a single flat buffer); bf16 needs
    floating leaves.  Ineligible buckets fall back to ``"off"`` — the
    dense (or compressor) wire — never to a half-applied quantization.
    """
    if wire == "off":
        return wire
    import jax.numpy as jnp

    floating = all(
        jnp.issubdtype(jnp.dtype(d), jnp.floating) for d in wire_dtypes
    )
    if not floating:
        return "off"
    if wire in ("int8", "fp8") and len(set(wire_dtypes)) != 1:
        return "off"
    return wire


def resolve_lowering(
    requested: str, nbytes: int, axis_size: Optional[int] = None,
    wire_dtypes: Sequence[str] = (),
) -> str:
    """Resolve a requested lowering ("auto"/"flat"/"hier"/
    "hier_adasum") to the concrete per-bucket choice.  "auto" asks the
    topology cost model (flat vs hier only — it never switches the
    reduction algorithm to hier_adasum); a single-slice topology (or
    non-factorable axis) always resolves flat, so the pre-topology
    schedule is reproduced exactly — including for a hier_adasum
    request, which must be bitwise-identical to flat there.  A
    hier_adasum request on a non-floating bucket (``wire_dtypes``)
    also resolves flat: the adaptive coefficients divide by norms."""
    if requested == "flat":
        return "flat"
    from ..topo import model as topo_model

    topo = topo_model.current()
    n = topo.world if axis_size is None else axis_size
    s, _ = topo.factor_axis(n)
    if s == 1:
        return "flat"
    if requested == "hier_adasum":
        import jax.numpy as jnp

        floating = all(
            jnp.issubdtype(jnp.dtype(d), jnp.floating)
            for d in wire_dtypes
        )
        if wire_dtypes and not floating:
            return "flat"
        return "hier_adasum"
    if requested == "hier":
        return "hier"
    return topo.choose_lowering("all_reduce", nbytes, n)


def _make_bucket(
    indices: Tuple[int, ...],
    sizes_bytes: Sequence[int],
    dtypes: Sequence[str],
    pinned: bool = False,
    wire: str = "off",
    lowering: str = "auto",
    axis_size: Optional[int] = None,
) -> Bucket:
    wire_dtypes = tuple(dict.fromkeys(dtypes[i] for i in indices))
    nbytes = sum(int(sizes_bytes[i]) for i in indices)
    return Bucket(
        indices=indices,
        nbytes=nbytes,
        wire_dtypes=wire_dtypes,
        pinned=pinned,
        wire=eligible_wire(wire, wire_dtypes),
        lowering=resolve_lowering(lowering, nbytes, axis_size,
                                  wire_dtypes),
    )


def wire_bytes(bucket: Bucket, block: Optional[int] = None) -> int:
    """One-phase wire payload bytes of a bucket under its wire format
    (the apples-to-apples number behind ``sched.wire_bytes{wire=}`` and
    the compression-ratio gauge): dense bytes for ``off``, 2
    bytes/element for ``bf16``, 1 byte/element + fp32 block scales for
    the quantized formats."""
    if bucket.wire == "off":
        return bucket.nbytes
    import jax.numpy as jnp

    itemsize = jnp.dtype(bucket.wire_dtypes[0]).itemsize
    elems = bucket.nbytes // itemsize
    if bucket.wire == "bf16":
        return elems * 2
    if block is None:
        from ..ops.quantized import quant_block

        block = quant_block()
    return elems + 4 * (-(-elems // block))
