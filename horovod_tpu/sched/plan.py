"""Plan stage: build a :class:`BucketSchedule` from gradient metadata.

The reference's scheduling state lives in the controller loop: tensors
become ready in backward order, ``FuseResponses`` fuses consecutive
ready responses (``controller.cc:793``), and the cycle dispatches one
fused collective per tick.  Under XLA the whole step is one program, so
the plan is computed host-side at trace time and *is* the schedule: an
ordered tuple of buckets, each a set of gradient-leaf indices that
share one wire collective.

Ordering: buckets are emitted in **reverse-backward** order — the order
gradients become available during the backward pass (last layer first),
observed by the ``hooks`` module's grad-boundary taps when available,
else assumed to be the reversed pytree flatten order.  Combined with
``lax.optimization_barrier`` sequencing in the execute stage, this hands
XLA's latency-hiding scheduler a chain of collectives it can overlap
with the remaining backward compute.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from ..ops import fusion
from ..utils import env


@dataclasses.dataclass(frozen=True)
class SchedConfig:
    """Knobs of the bucketed overlap scheduler (``HVD_TPU_SCHED*``)."""

    enabled: bool = True
    mode: str = "allreduce"  # "allreduce" | "reduce_scatter"
    bucket_bytes: Optional[int] = None  # None -> fusion threshold knob
    look_ahead: int = 3
    barriers: bool = True
    capture_order: bool = True

    def __post_init__(self):
        if self.mode not in ("allreduce", "reduce_scatter"):
            raise ValueError(
                f"HVD_TPU_SCHED_MODE must be 'allreduce' or "
                f"'reduce_scatter', got {self.mode!r}"
            )

    @classmethod
    def from_env(cls) -> "SchedConfig":
        raw = (env.get_env(env.SCHED, "on") or "on").strip().lower()
        enabled = raw not in ("off", "0", "false", "no")
        bucket_bytes = env.get_int(env.SCHED_BUCKET_BYTES, -1)
        return cls(
            enabled=enabled,
            mode=(env.get_env(env.SCHED_MODE, "allreduce") or "allreduce")
            .strip().lower(),
            bucket_bytes=None if bucket_bytes < 0 else bucket_bytes,
            look_ahead=env.get_int(env.SCHED_LOOK_AHEAD, 3),
            barriers=env.get_bool(env.SCHED_BARRIERS, True),
            capture_order=env.get_bool(env.SCHED_CAPTURE_ORDER, True),
        )


# Trace-time config override (the fusion-threshold override pattern):
# tests and probe variants pin a config without touching the env.
_config_override: Optional[SchedConfig] = None


def set_config_override(cfg: Optional[SchedConfig]) -> None:
    global _config_override
    _config_override = cfg


def current_config() -> SchedConfig:
    return (
        _config_override if _config_override is not None
        else SchedConfig.from_env()
    )


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One fused exchange: leaf ``indices`` (original flatten order)
    sharing a wire collective of ``nbytes`` total."""

    indices: Tuple[int, ...]
    nbytes: int
    wire_dtypes: Tuple[str, ...]  # distinct dtypes, flatten order
    pinned: bool = False  # from an explicit user group


@dataclasses.dataclass(frozen=True)
class BucketSchedule:
    """Ordered exchange plan for one gradient pytree."""

    buckets: Tuple[Bucket, ...]
    mode: str
    total_bytes: int

    def __len__(self) -> int:
        return len(self.buckets)

    def signature(self) -> Tuple:
        """Hashable identity: two schedules with equal signatures emit
        identical exchange programs (determinism tests key on this)."""
        return (
            self.mode,
            tuple((b.indices, b.nbytes, b.wire_dtypes, b.pinned)
                  for b in self.buckets),
        )


def build_schedule(
    sizes_bytes: Sequence[int],
    dtypes: Sequence[str],
    cfg: Optional[SchedConfig] = None,
    *,
    order: Optional[Sequence[int]] = None,
    pinned: Sequence[Sequence[int]] = (),
) -> BucketSchedule:
    """Plan the exchange for leaves of ``sizes_bytes``/``dtypes``.

    ``order`` is the backward-readiness order of leaf indices (first
    element = first gradient available); ``None`` assumes the reversed
    flatten order (parameters registered last finish their backward
    first).  ``pinned`` buckets (explicit user groups,
    ``DistributedOptimizer(groups=...)``) fuse atomically and are
    emitted where their *earliest-ready* member falls in the order.

    Pure function of its arguments: same metadata + config -> identical
    schedule (plan determinism is load-bearing — every SPMD rank must
    emit the same collectives in the same order).
    """
    if cfg is None:
        cfg = current_config()
    n = len(sizes_bytes)
    if order is None:
        order = range(n - 1, -1, -1)
    order = [i for i in order if 0 <= i < n]
    if len(set(order)) != n:
        # Incomplete / duplicated observation: fall back to the assumed
        # reverse-backward order rather than dropping leaves.
        order = list(range(n - 1, -1, -1))

    pinned_set = set()
    pinned_buckets: List[Tuple[int, Bucket]] = []
    rank_of = {leaf: pos for pos, leaf in enumerate(order)}
    for group in pinned:
        idx = tuple(int(i) for i in group)
        if not idx:
            continue
        pinned_set.update(idx)
        pinned_buckets.append((
            min(rank_of[i] for i in idx),
            _make_bucket(idx, sizes_bytes, dtypes, pinned=True),
        ))

    free = [i for i in order if i not in pinned_set]
    planned = fusion.bucket_plan(
        [sizes_bytes[i] for i in free],
        [dtypes[i] for i in free],
        cfg.bucket_bytes,
        look_ahead=cfg.look_ahead,
    )
    planned_buckets: List[Tuple[int, Bucket]] = []
    for b in planned:
        idx = tuple(sorted(free[j] for j in b))
        planned_buckets.append((
            min(rank_of[i] for i in idx),
            _make_bucket(idx, sizes_bytes, dtypes),
        ))

    ordered = [
        b for _, b in sorted(
            pinned_buckets + planned_buckets, key=lambda p: p[0]
        )
    ]
    return BucketSchedule(
        buckets=tuple(ordered),
        mode=cfg.mode,
        total_bytes=sum(b.nbytes for b in ordered),
    )


def _make_bucket(
    indices: Tuple[int, ...],
    sizes_bytes: Sequence[int],
    dtypes: Sequence[str],
    pinned: bool = False,
) -> Bucket:
    return Bucket(
        indices=indices,
        nbytes=sum(int(sizes_bytes[i]) for i in indices),
        wire_dtypes=tuple(dict.fromkeys(dtypes[i] for i in indices)),
        pinned=pinned,
    )
