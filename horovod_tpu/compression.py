"""Gradient wire compression (reference ``horovod/torch/compression.py``,
``horovod/tensorflow/compression.py``).

The reference casts gradients to fp16 before the allreduce and back
after.  On TPU the native low-precision wire format is bfloat16 (ICI
collectives run at full rate in bf16 and it needs no loss-scaling); fp16
is kept for API parity.  Compression happens *inside* the jit program so
XLA fuses the casts into the collective.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Compressor:
    """A pair of compress/decompress transforms around the wire format."""

    @staticmethod
    def compress(tensor: jax.Array):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: jax.Array, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference ``NoneCompressor``)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 on the wire (reference
    ``FP16Compressor``)."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating) and tensor.dtype != jnp.float16:
            return tensor.astype(jnp.float16), ctx
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None and tensor.dtype != ctx else tensor


class BF16Compressor(Compressor):
    """TPU-native wire compression: bfloat16 shares fp32's exponent range
    so gradients need no loss scale, and ICI moves bf16 at 2x fp32
    throughput."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating) and tensor.dtype != jnp.bfloat16:
            return tensor.astype(jnp.bfloat16), ctx
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None and tensor.dtype != ctx else tensor


from .ops.quantized import Fp8Compressor, Int8Compressor  # noqa: E402


class Compression:
    """Namespace matching ``hvd.Compression`` exactly, extended with the
    TPU-native ``bf16`` and the EQuARX-style quantized wires ``int8``
    and ``fp8`` (float8_e4m3fn — see ``ops/quantized.py`` and
    docs/quantization.md)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    fp8 = Fp8Compressor
