"""Worker-side consumer of the driver's ``__slo__`` remediation scope.

The elastic driver's remediation actuators (``elastic_driver._build_slo``)
publish every rung's action on the rendezvous KV store — ``preempt``,
``degrade``, ``placement`` — but a published action heals nothing until
a worker enacts it.  This module is that enactment: the worker's
heartbeat thread (``elastic_worker.WorkerNotificationManager``) polls
the scope once per beat and applies each new action in-process:

``preempt``
    gate lower-priority lanes on this worker's in-process exchange
    service (:meth:`~horovod_tpu.svc.arbiter.Arbiter.request_preempt`)
    — the same call the driver makes against its own service, now on
    every rank that actually dispatches exchanges;
``degrade``
    apply the published knob changes (``HVD_TPU_SVC_STALENESS`` bump,
    ``HVD_TPU_TOPO_LOWER=flat``) to this process's environment — the
    staleness/lowering knobs are read live per window/emission, so the
    flip takes effect at the next exchange.  A revert (published by
    :meth:`~horovod_tpu.elastic.remediate.Remediator.reset` on SLO
    recovery) rides the same channel with the restored values; ``null``
    means unset;
``placement``
    enact the new tenant→slice placement through the arbiter's live
    weight knob (``HVD_TPU_SVC_TENANT_WEIGHTS`` — DRR deficits refill
    by ``quantum × weight``, so rail shares shift to the new placement
    at the next scheduling cycle) and hand the placement to the
    notification manager's registered states
    (``on_placement_updated``), so a state that shards per tenant can
    reshard at its next commit boundary.

Every applied action is acknowledged back on the KV store
(``__slo__/ack_<action>_<seq>_rank_<rank>``); the driver folds the ack
counts into ``GET /slo`` so the remediation history reports what
workers *enacted*, not just what the driver published.  Actions are
deduplicated on payload bytes — a heartbeat re-reading the same
publication is a no-op — and a failure applying one action never
reaches the heartbeat loop.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional

from .. import metrics
from ..utils.logging import get_logger

SCOPE = "__slo__"
ACTIONS = ("preempt", "degrade", "placement")


def ack_key(action: str, seq: Any, rank: int) -> str:
    """The KV key one rank acknowledges one published action under."""
    return f"ack_{action}_{seq}_rank_{rank}"


def weights_spec(placement: Dict[str, Any]) -> str:
    """Render a tenant→slice placement as the
    ``HVD_TPU_SVC_TENANT_WEIGHTS`` syntax (slice counts are the DRR
    weights: a tenant's rail share is its slice share)."""
    return ",".join(
        f"{t}:{int(n)}" for t, n in sorted(placement.items())
        if isinstance(n, (int, float)) and n > 0
    )


def apply_env_changes(changes: Dict[str, Optional[str]]) -> None:
    """Apply a published knob-change dict to this process: full env
    names mapped to their new value, ``None`` = unset (the revert
    path's way of restoring a knob that was never set)."""
    for name, value in changes.items():
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = str(value)


class SLOActionConsumer:
    """Polls ``__slo__`` and enacts each new action in this process.

    ``rank_fn`` returns the worker's *current* rank (it changes across
    an in-process remesh); ``on_placement`` receives every newly
    published placement dict (the notification manager fans it out to
    registered states)."""

    def __init__(self, rank_fn: Callable[[], int],
                 on_placement: Optional[Callable[[Dict[str, int]], None]]
                 = None):
        self._rank_fn = rank_fn
        self._on_placement = on_placement
        self._seen: Dict[str, bytes] = {}

    # ------------------------------------------------------------ poll
    def poll(self, client: Any) -> int:
        """One pass over the scope; returns how many new actions were
        applied.  Never raises — heartbeats must survive any KV or
        enactment failure."""
        applied = 0
        for action in ACTIONS:
            try:
                raw = client.get(SCOPE, action, timeout_ms=0)
            except Exception:
                continue
            if raw is None or self._seen.get(action) == raw:
                continue
            try:
                payload = json.loads(raw.decode())
            except Exception:
                self._seen[action] = raw  # malformed: never retry it
                continue
            ok = False
            try:
                self._apply(action, payload)
                ok = True
                applied += 1
                metrics.inc_counter(f"slo.worker.{action}")
            except Exception as e:
                get_logger().warning(
                    "SLO action %s failed to apply on rank %s: %s",
                    action, self._rank_fn(), e,
                )
            # consumed either way: a failing action must not be
            # re-attempted every heartbeat (the driver's retry policy
            # owns republication), but only a *successful* apply acks.
            self._seen[action] = raw
            if ok:
                self._ack(client, action, payload)
        return applied

    # ----------------------------------------------------------- apply
    def _apply(self, action: str, payload: Dict[str, Any]) -> None:
        if action == "preempt":
            self._apply_preempt(payload)
        elif action == "degrade":
            apply_env_changes(payload.get("changes") or {})
            get_logger().info(
                "SLO degrade %s applied on rank %s: %s",
                "revert" if payload.get("revert") else "action",
                self._rank_fn(), payload.get("changes"),
            )
        elif action == "placement":
            self._apply_placement(payload)

    def _apply_preempt(self, payload: Dict[str, Any]) -> None:
        from ..svc import service as service_mod

        tenant = payload.get("tenant")
        if not tenant:
            return
        svc = service_mod.get_service_or_none()
        if svc is not None:
            svc.arbiter.request_preempt(tenant)

    def _apply_placement(self, payload: Dict[str, Any]) -> None:
        placement = payload.get("placement") or {}
        spec = weights_spec(placement)
        if spec:
            os.environ["HVD_TPU_SVC_TENANT_WEIGHTS"] = spec
        if self._on_placement is not None:
            self._on_placement(dict(placement))
        get_logger().info(
            "SLO placement %s enacted on rank %s: %s",
            "rollback" if payload.get("rollback") else "handoff",
            self._rank_fn(), placement,
        )

    # ------------------------------------------------------------- ack
    def _ack(self, client: Any, action: str,
             payload: Dict[str, Any]) -> None:
        seq = payload.get("seq")
        if seq is None:
            return
        try:
            client.put(SCOPE, ack_key(action, seq, self._rank_fn()),
                       b"1")
        except Exception:
            pass  # the ack is telemetry; losing one is not a failure
