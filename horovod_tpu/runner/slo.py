"""Per-tenant SLO specs + the driver-side breach watchdog.

The observability stack can already *name* a problem — ``/tenants``
shows share-vs-usage (svc/arbiter.py), ``trace.tenant_seconds``
histograms attribute slow phases to tenants, and the straggler detector
names the slow rank — but through PR 15 an SLO violation was a gauge,
not an action.  This module is the sensing half of the self-healing
loop (ROADMAP item 2): parse per-tenant targets from
``HVD_TPU_SLO_SPEC``, fold the three signals above into per-window
breach verdicts, and confirm a breach only after
``HVD_TPU_SLO_WINDOWS`` *consecutive* breaching windows — hysteresis,
so one noisy sample never triggers a remediation.  The acting half is
:mod:`horovod_tpu.elastic.remediate` (the escalation ladder);
:class:`SLOController` pairs the two for the elastic driver, which
ticks it from the round watch loop and serves its state as ``GET /slo``
(``runner/telemetry_http.py``).

Spec syntax (``HVD_TPU_SLO_SPEC``)::

    tenantA:step=0.5,p99=0.05;tenantB:p99=0.1

``step``
    target per-step exchange seconds — compared against the sum of the
    tenant's per-phase p50s from its ``trace.tenant_seconds.<t>.*``
    histograms, worst rank (``trace/straggler.tenant_observed``);
``p99``
    target served-latency p99 seconds — compared against the tenant's
    ``svc.tenant.wait_seconds`` p99 (the ``/tenants`` aggregation),
    falling back to the worst tenant-phase p99 when no arbiter wait
    histogram exists (arbiter off / untagged world).

Malformed entries are warned and skipped — a bad spec must not kill
the driver.  See docs/multitenant.md for the endpoint and
docs/fault_tolerance.md for the remediation ladder downstream.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import events, metrics
from ..utils import env
from ..utils.logging import get_logger

DEFAULT_WINDOWS = 3
DEFAULT_CHECK_INTERVAL_S = 5.0

# Breach kinds (the ``kind`` field of every breach record/event).
KIND_STEP = "step"
KIND_P99 = "p99"


def slo_windows() -> int:
    """``HVD_TPU_SLO_WINDOWS``: consecutive breaching windows before a
    breach is confirmed (default 3, floor 1)."""
    return max(1, env.get_int(env.SLO_WINDOWS, DEFAULT_WINDOWS))


def check_interval_s() -> float:
    """``HVD_TPU_SLO_CHECK_INTERVAL``: seconds between driver-side
    evaluations (default 5)."""
    return max(0.0, env.get_float(env.SLO_CHECK_INTERVAL,
                                  DEFAULT_CHECK_INTERVAL_S))


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One tenant's targets; ``None`` = that dimension unconstrained."""

    tenant: str
    step_s: Optional[float] = None
    p99_s: Optional[float] = None

    def targets(self) -> List[Tuple[str, float]]:
        out: List[Tuple[str, float]] = []
        if self.step_s is not None:
            out.append((KIND_STEP, self.step_s))
        if self.p99_s is not None:
            out.append((KIND_P99, self.p99_s))
        return out


def parse_slo_spec(raw: str) -> Dict[str, SLOSpec]:
    """Parse the ``HVD_TPU_SLO_SPEC`` syntax; malformed entries are
    skipped with a warning (same forgiveness as the tenant-weights
    knob — a bad spec degrades to "unwatched", never to a dead
    driver)."""
    out: Dict[str, SLOSpec] = {}
    for entry in (raw or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        tenant, sep, body = entry.partition(":")
        tenant = tenant.strip()
        if not sep or not tenant:
            get_logger().warning("bad SLO spec entry %r (skipped): "
                                 "want 'tenant:key=val[,...]'", entry)
            continue
        fields: Dict[str, float] = {}
        ok = True
        for kv in body.split(","):
            if not kv.strip():
                continue
            key, sep2, val = kv.partition("=")
            key = key.strip()
            try:
                num = float(val)
            except ValueError:
                num = -1.0
            if not sep2 or key not in (KIND_STEP, KIND_P99) or num <= 0:
                get_logger().warning(
                    "bad SLO target %r for tenant %s (entry skipped)",
                    kv, tenant,
                )
                ok = False
                break
            fields[key] = num
        if ok and fields:
            out[tenant] = SLOSpec(
                tenant=tenant,
                step_s=fields.get(KIND_STEP),
                p99_s=fields.get(KIND_P99),
            )
    return out


def specs_from_env() -> Dict[str, SLOSpec]:
    return parse_slo_spec(env.get_env(env.SLO_SPEC, "") or "")


def observe_tenants(
    per_rank: Dict[int, Dict[str, Any]]
) -> Dict[str, Dict[str, Any]]:
    """One evaluation window's observed values per tenant, folded from
    the three existing signals: the tenant phase histograms
    (``step_s`` / fallback ``phase_p99_s``), the ``/tenants``
    aggregation (``p99_s`` from the wait histogram, plus share/usage),
    and the straggler verdicts that name the tenant."""
    from ..svc.arbiter import tenants_payload
    from ..trace import straggler

    observed = straggler.tenant_observed(per_rank)
    tenants = tenants_payload(per_rank).get("tenants", {})
    verdicts = straggler.detect(per_rank)
    out: Dict[str, Dict[str, Any]] = {}
    for tenant in sorted(set(observed) | set(tenants)):
        obs = observed.get(tenant, {})
        agg = tenants.get(tenant, {})
        p99 = agg.get("wait_p99_s")
        if p99 is None:
            p99 = obs.get("phase_p99_s") or None
        out[tenant] = {
            "step_s": obs.get("step_s"),
            "p99_s": p99,
            "share": agg.get("share"),
            "usage": agg.get("usage"),
            "stragglers": [
                {"rank": v["rank"], "phase": v["phase"],
                 "ratio": v["ratio"]}
                for v in verdicts if v.get("tenant") == tenant
            ],
        }
    return out


class SLOWatchdog:
    """Breach detection with N-consecutive-window hysteresis.

    Each :meth:`evaluate` call is one window: every (tenant, kind)
    target is compared against its observed value; a target must
    breach for ``windows`` consecutive calls before it lands in the
    confirmed list (and emits :data:`~horovod_tpu.events.SLO_BREACH`).
    A confirmed breach whose metric goes green emits
    :data:`~horovod_tpu.events.SLO_RECOVERED` and re-arms the counter
    — never one noisy sample in either direction beyond the first.

    A *missing* observation is not green: a tenant whose ranks stop
    reporting (workers died, histograms gone) HOLDS its streak and its
    confirmed state — the window that cannot see the tenant must never
    declare it recovered.  ``no_data`` kinds are flagged per tenant in
    the status body and as the ``slo.no_data`` gauge.
    """

    def __init__(self, specs: Dict[str, SLOSpec],
                 windows: Optional[int] = None):
        self.specs = dict(specs)
        self.windows = slo_windows() if windows is None else max(1, windows)
        self._lock = threading.Lock()
        self._consec: Dict[Tuple[str, str], int] = {}
        self._confirmed: set = set()

    def confirmed(self) -> List[Tuple[str, str]]:
        with self._lock:
            return sorted(self._confirmed)

    def evaluate(self, per_rank: Dict[int, Dict[str, Any]]
                 ) -> Dict[str, Any]:
        """Run one window; returns the ``/slo`` status body:
        ``{"specs", "tenants", "breaches"}`` where ``breaches`` holds
        only CONFIRMED breaches (>= ``windows`` consecutive)."""
        metrics.inc_counter("slo.windows")
        observed = observe_tenants(per_rank)
        breaches: List[Dict[str, Any]] = []
        recovered: List[Dict[str, Any]] = []
        tenants_out: Dict[str, Any] = {}
        for tenant, spec in sorted(self.specs.items()):
            obs = observed.get(tenant, {})
            entry: Dict[str, Any] = {
                "observed": {k: obs.get(k) for k in
                             ("step_s", "p99_s", "share", "usage")},
                "stragglers": obs.get("stragglers", []),
                "targets": {}, "windows": {}, "no_data": [],
            }
            for kind, target in spec.targets():
                value = obs.get(f"{kind}_s")
                no_data = value is None
                breaching = (not no_data) and value > target
                key = (tenant, kind)
                with self._lock:
                    if no_data:
                        # hold the streak: no observation is neither a
                        # breach nor a recovery.
                        consec = self._consec.get(key, 0)
                    elif breaching:
                        self._consec[key] = self._consec.get(key, 0) + 1
                        consec = self._consec[key]
                    else:
                        self._consec[key] = consec = 0
                    was_confirmed = key in self._confirmed
                    now_confirmed = consec >= self.windows
                    if now_confirmed:
                        self._confirmed.add(key)
                    elif was_confirmed and not breaching and not no_data:
                        self._confirmed.discard(key)
                entry["targets"][kind] = target
                entry["windows"][kind] = consec
                if no_data:
                    entry["no_data"].append(kind)
                if breaching:
                    metrics.inc_counter("slo.breach_windows")
                metrics.set_gauge(
                    "slo.breached", 1.0 if now_confirmed else 0.0,
                    {"tenant": tenant, "kind": kind},
                )
                metrics.set_gauge(
                    "slo.no_data", 1.0 if no_data else 0.0,
                    {"tenant": tenant, "kind": kind},
                )
                if now_confirmed and not was_confirmed:
                    metrics.inc_counter("slo.breaches")
                    metrics.inc_counter(f"slo.breaches.{tenant}.{kind}")
                    events.emit(
                        events.SLO_BREACH, tenant=tenant, kind=kind,
                        observed=value, target=target, windows=consec,
                    )
                    get_logger().warning(
                        "SLO breach confirmed: tenant %s %s %.4fs > "
                        "target %.4fs for %d consecutive windows",
                        tenant, kind, value, target, consec,
                    )
                elif was_confirmed and not breaching and not no_data:
                    metrics.inc_counter("slo.recoveries")
                    events.emit(
                        events.SLO_RECOVERED, tenant=tenant, kind=kind,
                        observed=value, target=target,
                    )
                    recovered.append({"tenant": tenant, "kind": kind,
                                      "observed": value,
                                      "target": target})
                if now_confirmed:
                    breaches.append({
                        "tenant": tenant, "kind": kind,
                        "observed": value, "target": target,
                        "ratio": ((value / target)
                                  if target and value is not None
                                  else None),
                        "windows": consec,
                        "no_data": no_data,
                        "share": obs.get("share"),
                        "usage": obs.get("usage"),
                        "stragglers": obs.get("stragglers", []),
                    })
            tenants_out[tenant] = entry
        return {
            "specs": {
                t: {"step_s": s.step_s, "p99_s": s.p99_s}
                for t, s in sorted(self.specs.items())
            },
            "hysteresis_windows": self.windows,
            "tenants": tenants_out,
            "breaches": breaches,
            "recovered": recovered,
        }


class SLOController:
    """The watchdog + remediator pair the elastic driver ticks.

    ``maybe_tick`` rate-limits to ``HVD_TPU_SLO_CHECK_INTERVAL``
    seconds, evaluates one window from the per-rank KV snapshots, and
    hands every confirmed breach to the remediation policy
    (:class:`~horovod_tpu.elastic.remediate.Remediator`); ``payload``
    is the ``GET /slo`` body — current status plus the bounded
    remediation history."""

    def __init__(self, watchdog: SLOWatchdog, remediator=None,
                 check_interval_s_: Optional[float] = None):
        self.watchdog = watchdog
        self.remediator = remediator
        self.check_interval_s = (
            check_interval_s() if check_interval_s_ is None
            else max(0.0, check_interval_s_)
        )
        self._lock = threading.Lock()
        self._last_tick = 0.0
        self._last_status: Optional[Dict[str, Any]] = None

    @classmethod
    def from_env(cls, remediator=None) -> Optional["SLOController"]:
        """Build the controller when ``HVD_TPU_SLO_SPEC`` names any
        tenant; None (no watchdog, no endpoint) otherwise."""
        specs = specs_from_env()
        if not specs:
            return None
        return cls(SLOWatchdog(specs), remediator=remediator)

    def maybe_tick(
        self,
        per_rank_fn: Callable[[], Dict[int, Dict[str, Any]]],
        now: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """One rate-limited window; returns the fresh status dict, or
        None when inside the check interval.  Never raises — the SLO
        loop must not take down the round it watches."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if now - self._last_tick < self.check_interval_s:
                return None
            self._last_tick = now
        try:
            status = self.watchdog.evaluate(per_rank_fn())
            if status["breaches"]:
                # Profiling-plane capture hook: a confirmed breach is
                # exactly the moment a device-level profiler trace is
                # worth its cost (prof/capture.py bounds how many).
                from .. import prof

                prof.maybe_capture(
                    "slo_breach:" + ",".join(sorted(
                        str(b.get("tenant", "?"))
                        for b in status["breaches"]
                    ))
                )
            if self.remediator is not None:
                for breach in status["breaches"]:
                    self.remediator.consider(breach)
                # Recovery re-arms the ladder: once EVERY kind for a
                # tenant is green again, reset() walks it back to the
                # cheapest rung and reverts degraded mode (the knob
                # flips are a round trip, not a ratchet).  A tenant
                # with another kind still confirmed keeps its rung.
                recovered_tenants = {
                    r["tenant"] for r in status.get("recovered", [])
                }
                if recovered_tenants:
                    still = {t for t, _kind in self.watchdog.confirmed()}
                    for tenant in sorted(recovered_tenants - still):
                        self.remediator.reset(tenant)
            with self._lock:
                self._last_status = status
            return status
        except Exception as e:  # pragma: no cover - defensive
            get_logger().warning("SLO tick failed: %s", e)
            return None

    def payload(self) -> Dict[str, Any]:
        with self._lock:
            status = dict(self._last_status or {
                "specs": {
                    t: {"step_s": s.step_s, "p99_s": s.p99_s}
                    for t, s in sorted(self.watchdog.specs.items())
                },
                "hysteresis_windows": self.watchdog.windows,
                "tenants": {}, "breaches": [],
            })
        status["check_interval_s"] = self.check_interval_s
        if self.remediator is not None:
            status["remediations"] = self.remediator.history()
            status["placement"] = self.remediator.placement()
        return status
