"""Per-worker shim for the MPI launch path.

Translates MPI launcher rank env (Open MPI ``OMPI_COMM_WORLD_*``, PMI
``PMI_RANK``/``PMI_SIZE``, PMIx, Slurm ``SLURM_PROCID``) into this
framework's worker env contract (the variables ``make_worker_env``
sets, ``runner/launch.py:40``), then execs the user command.  The
reference reads the same variables inside its MPI context
(``horovod/runner/mpi_run.py`` env plumbing + ``common/basics.py``);
here MPI is launcher-only, so the mapping happens once up front.
"""

from __future__ import annotations

import os
import sys


def resolve_mpi_env(environ=None) -> dict:
    """Return the HVD_TPU_* entries derived from the MPI-provided env
    (pure function, unit-testable)."""
    e = environ if environ is not None else os.environ
    out = {}

    def first(*names):
        for n in names:
            if n in e:
                return e[n]
        return None

    rank = first("OMPI_COMM_WORLD_RANK", "PMIX_RANK", "PMI_RANK",
                 "SLURM_PROCID")
    size = first("OMPI_COMM_WORLD_SIZE", "PMI_SIZE", "SLURM_NTASKS")
    local_rank = first("OMPI_COMM_WORLD_LOCAL_RANK", "MPI_LOCALRANKID",
                       "SLURM_LOCALID")
    local_size = first("OMPI_COMM_WORLD_LOCAL_SIZE", "MPI_LOCALNRANKS")
    if local_size is None and "SLURM_TASKS_PER_NODE" in e:
        # Slurm run-length syntax: "2(x3)" or "4,2" — this node's count
        # is the first segment's value (homogeneous layouts; the env
        # contract wants a plain integer).
        seg = e["SLURM_TASKS_PER_NODE"].split(",")[0]
        local_size = seg.split("(")[0]
    if rank is not None:
        out["HVD_TPU_CROSS_RANK"] = rank
    if size is not None:
        out["HVD_TPU_CROSS_SIZE"] = size
    if local_rank is not None:
        out["HVD_TPU_LOCAL_RANK"] = local_rank
    if local_size is not None:
        out["HVD_TPU_LOCAL_SIZE"] = local_size
    return out


def main() -> int:
    os.environ.update(resolve_mpi_env())
    cmd = sys.argv[1:]
    if not cmd:
        print("usage: python -m horovod_tpu.runner.mpi_worker cmd...",
              file=sys.stderr)
        return 2
    os.execvp(cmd[0], cmd)
    return 127  # unreachable


if __name__ == "__main__":
    sys.exit(main())
