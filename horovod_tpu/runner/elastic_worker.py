"""Worker-side elastic plumbing.

Reference: ``horovod/runner/elastic/worker.py`` — a
``WorkerNotificationManager`` listens for driver host-update
notifications and flags registered ``State`` objects, whose next
``commit()``/``check_host_updates()`` raises ``HostsUpdatedInterrupt``.

Here the notification channel is the launcher KV store: the driver sets
``__elastic__/hosts_updated_<round>``; a poller thread flags states.
State persistence across worker restarts also lives here (the driver
respawns processes on membership change — see elastic_driver.py).
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, List, Optional

from . import controller_py

RESTART_CODE = 73
_POLL_PERIOD_S = 0.5
_HEARTBEAT_PERIOD_S = 1.0

_manager: Optional["WorkerNotificationManager"] = None
_manager_lock = threading.Lock()


def in_elastic_job() -> bool:
    return os.environ.get("HVD_TPU_ELASTIC") == "1"


def get_notification_manager() -> Optional["WorkerNotificationManager"]:
    global _manager
    if not in_elastic_job():
        return None
    with _manager_lock:
        if _manager is None:
            _manager = WorkerNotificationManager()
        return _manager


class WorkerNotificationManager:
    def __init__(self):
        self._listeners: List[Any] = []
        self._lock = threading.Lock()
        self._client = None
        self._thread: Optional[threading.Thread] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.round = int(os.environ.get("HVD_TPU_ELASTIC_ROUND", "0"))
        self.rank = int(os.environ.get("HVD_TPU_CROSS_RANK", "0"))

    def init(self) -> None:
        if self._client is not None:
            return
        from ..faults import inject
        from ..utils.retry import RetryPolicy

        def connect():
            inject("worker.connect", rank=self.rank, round=self.round)
            return controller_py.make_client(
                os.environ["HVD_TPU_RENDEZVOUS_ADDR"],
                int(os.environ["HVD_TPU_RENDEZVOUS_PORT"]),
                os.environ["HVD_TPU_SECRET"],
                self.rank,
            )

        # the KV server may still be mid-bind when an early worker dials
        self._client = RetryPolicy(
            max_attempts=3, base_delay_s=0.2, name="worker.connect"
        ).call(connect)
        self._thread = threading.Thread(target=self._poll, daemon=True)
        self._thread.start()
        # Heartbeat: the driver's health monitor distinguishes a hung
        # worker (process alive, heartbeat stalled) from a crashed one
        # (process gone) — see ElasticDriver._find_hung_worker.
        self._hb_thread = threading.Thread(target=self._heartbeat,
                                           daemon=True)
        self._hb_thread.start()

    def _heartbeat(self) -> None:
        from .. import metrics
        from ..faults import inject

        seq = 0
        key = f"hb_{self.round}_{self.rank}"
        while not self._stop.is_set():
            seq += 1
            try:
                client = self._client
                if client is None:
                    return
                client.put("__elastic__", key, str(seq).encode())
                # Piggyback the telemetry push on the heartbeat: the
                # driver's /metrics endpoint folds the latest snapshot
                # per rank into its scrape (telemetry_http.py).
                client.put(
                    "__metrics__", f"rank_{self.rank}",
                    metrics.render_json().encode(),
                )
            except Exception:
                pass  # KV blips must never kill the worker
            # a 'hang' fault here freezes the heartbeat AFTER it
            # registered, without touching the training thread — the
            # scripted stand-in for a wedged worker the driver's health
            # monitor must catch
            inject("worker.heartbeat", rank=self.rank, round=self.round)
            self._stop.wait(_HEARTBEAT_PERIOD_S)

    def _poll(self) -> None:
        key = f"hosts_updated_{self.round}"
        while not self._stop.is_set():
            try:
                val = self._client.get("__elastic__", key, timeout_ms=0)
            except Exception:
                val = None
            if val is not None:
                with self._lock:
                    for state in self._listeners:
                        state.on_hosts_updated(time.time(), "updated")
                return  # one notification per round
            self._stop.wait(_POLL_PERIOD_S)

    def register_listener(self, state) -> None:
        with self._lock:
            self._listeners.append(state)

    def remove_listener(self, state) -> None:
        with self._lock:
            if state in self._listeners:
                self._listeners.remove(state)

    # -- state persistence across rounds (rank 0 writes) ----------------
    # Blobs are chunked: the controller protocol caps one frame at 64MB
    # (native hvd_ctrl_get also truncates reads at its buffer cap), so a
    # model+optimizer snapshot ships as <=16MB pieces with a manifest.
    _CHUNK = 16 << 20

    def save_state_blob(self, blob: bytes) -> None:
        if self.rank != 0 or self._client is None:
            return
        import hashlib

        n = max(1, (len(blob) + self._CHUNK - 1) // self._CHUNK)
        for i in range(n):
            self._client.put(
                "__elastic_state__", f"chunk_{i}",
                blob[i * self._CHUNK : (i + 1) * self._CHUNK],
            )
        manifest = f"{n}:{len(blob)}:{hashlib.sha256(blob).hexdigest()}"
        self._client.put("__elastic_state__", "manifest", manifest.encode())

    def load_state_blob(self) -> Optional[bytes]:
        if self._client is None:
            return None
        import hashlib

        manifest = self._client.get("__elastic_state__", "manifest", timeout_ms=0)
        if manifest is None:
            return None
        n, total, digest = manifest.decode().split(":")
        parts = []
        for i in range(int(n)):
            chunk = self._client.get(
                "__elastic_state__", f"chunk_{i}", timeout_ms=5000
            )
            if chunk is None:
                return None
            parts.append(chunk)
        blob = b"".join(parts)[: int(total)]
        if hashlib.sha256(blob).hexdigest() != digest:
            return None  # torn write (a newer commit is in flight)
        return blob

    def close(self) -> None:
        self._stop.set()
        if self._client is not None:
            self._client.close()
            self._client = None
