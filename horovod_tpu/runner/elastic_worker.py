"""Worker-side elastic plumbing.

Reference: ``horovod/runner/elastic/worker.py`` — a
``WorkerNotificationManager`` listens for driver host-update
notifications and flags registered ``State`` objects, whose next
``commit()``/``check_host_updates()`` raises ``HostsUpdatedInterrupt``.

Here the notification channel is the launcher KV store: the driver sets
``__elastic__/hosts_updated_<round>``; a poller thread flags states.
State persistence across worker restarts also lives here (the driver
respawns processes on membership change — see elastic_driver.py).
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, List, Optional

from . import controller_py

RESTART_CODE = 73
_POLL_PERIOD_S = 0.5
_HEARTBEAT_PERIOD_S = 1.0

_manager: Optional["WorkerNotificationManager"] = None
_manager_lock = threading.Lock()


def in_elastic_job() -> bool:
    return os.environ.get("HVD_TPU_ELASTIC") == "1"


def get_notification_manager() -> Optional["WorkerNotificationManager"]:
    global _manager
    if not in_elastic_job():
        return None
    with _manager_lock:
        if _manager is None:
            _manager = WorkerNotificationManager()
        return _manager


class WorkerNotificationManager:
    def __init__(self):
        self._listeners: List[Any] = []
        self._lock = threading.Lock()
        self._client = None
        self._thread: Optional[threading.Thread] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.round = int(os.environ.get("HVD_TPU_ELASTIC_ROUND", "0"))
        self.rank = int(os.environ.get("HVD_TPU_CROSS_RANK", "0"))
        # SLO remediation consumer (runner/slo_consumer.py): the
        # heartbeat polls __slo__ so the driver's preempt/degrade/
        # placement actions are enacted in THIS process, not just
        # published.
        from . import slo_consumer

        self._slo_consumer = slo_consumer.SLOActionConsumer(
            rank_fn=lambda: self.rank,
            on_placement=self._notify_placement,
        )

    def _notify_placement(self, placement) -> None:
        """Fan a newly enacted tenant→slice placement out to registered
        states: a state that shards per tenant reacts at its next
        commit boundary (``State.on_placement_updated``)."""
        with self._lock:
            listeners = list(self._listeners)
        for state in listeners:
            notify = getattr(state, "on_placement_updated", None)
            if notify is not None:
                notify(placement)

    def init(self) -> None:
        if self._client is not None:
            return
        from ..faults import inject
        from ..utils.retry import RetryPolicy

        def connect():
            inject("worker.connect", rank=self.rank, round=self.round)
            return controller_py.make_client(
                os.environ["HVD_TPU_RENDEZVOUS_ADDR"],
                int(os.environ["HVD_TPU_RENDEZVOUS_PORT"]),
                os.environ["HVD_TPU_SECRET"],
                self.rank,
            )

        # the KV server may still be mid-bind when an early worker dials
        self._client = RetryPolicy(
            max_attempts=3, base_delay_s=0.2, name="worker.connect"
        ).call(connect)
        # Schedule-DB seeding: merge the driver-published entries into
        # the local store BEFORE training starts, so a ScheduleTuner
        # built later in this process warm-starts from fleet state.
        self._fetch_schedules()
        self._thread = threading.Thread(target=self._poll, daemon=True)
        self._thread.start()
        # Heartbeat: the driver's health monitor distinguishes a hung
        # worker (process alive, heartbeat stalled) from a crashed one
        # (process gone) — see ElasticDriver._find_hung_worker.
        self._hb_thread = threading.Thread(target=self._heartbeat,
                                           daemon=True)
        self._hb_thread.start()

    def _fetch_schedules(self) -> None:
        """Pull the driver-published schedule DB (``__schedules__/db``)
        into the local ``HVD_TPU_TUNE_DB`` store.  No-op without a
        configured store; any failure is advisory (a worker must start
        without fleet state)."""
        import json

        from .. import metrics
        from ..sched.store import ScheduleStore

        store = ScheduleStore.from_env()
        if store is None or self._client is None:
            return
        try:
            raw = self._client.get("__schedules__", "db", timeout_ms=1000)
            if not raw:
                return
            merged = store.merge(json.loads(raw).get("entries", {}))
            if merged:
                metrics.inc_counter("sched.tune.kv_seeded", merged)
        except Exception:
            pass

    def _push_schedules(self, client) -> None:
        """Push the local schedule DB to the driver when it changed
        (piggybacked on the heartbeat like the metrics snapshot, but
        gated on file mtime — convergence is rare, heartbeats are
        not)."""
        import json

        from ..utils import env as hvd_env

        path = hvd_env.get_env(hvd_env.TUNE_DB)
        if not path:
            return
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return
        if mtime == getattr(self, "_sched_db_mtime", None):
            return
        self._sched_db_mtime = mtime
        with open(path) as fh:
            data = json.load(fh)
        client.put(
            "__schedules__", f"rank_{self.rank}",
            json.dumps(
                {"entries": data.get("entries", {})}
            ).encode(),
        )

    def _heartbeat(self) -> None:
        from .. import metrics
        from ..faults import inject

        seq = 0
        while not self._stop.is_set():
            seq += 1
            # key recomputed per tick: an in-process remesh can change
            # this worker's rank mid-round (elastic/remesh.py), and the
            # driver's hang monitor then watches the NEW key
            key = f"hb_{self.round}_{self.rank}"
            try:
                client = self._client
                if client is None:
                    return
                client.put("__elastic__", key, str(seq).encode())
                # Piggyback the telemetry push on the heartbeat: the
                # driver's /metrics endpoint folds the latest snapshot
                # per rank into its scrape (telemetry_http.py).
                client.put(
                    "__metrics__", f"rank_{self.rank}",
                    metrics.render_json().encode(),
                )
                self._push_schedules(client)
                # Enact any newly published SLO remediation action
                # (poll() never raises — see slo_consumer.py).
                self._slo_consumer.poll(client)
            except Exception:
                pass  # KV blips must never kill the worker
            # a 'hang' fault here freezes the heartbeat AFTER it
            # registered, without touching the training thread — the
            # scripted stand-in for a wedged worker the driver's health
            # monitor must catch
            inject("worker.heartbeat", rank=self.rank, round=self.round)
            self._stop.wait(_HEARTBEAT_PERIOD_S)

    def _poll(self) -> None:
        key = f"hosts_updated_{self.round}"
        remesh_key = f"begin_{self.round}"
        notified_remesh = None
        while not self._stop.is_set():
            # Remesh authorization first: when the driver chose the
            # in-process reshard path it publishes __remesh__/begin_*
            # INSTEAD of the restart signal; listeners get a
            # RemeshInterrupt at their next commit (elastic/remesh.py).
            try:
                raw = self._client.get(
                    "__remesh__", remesh_key, timeout_ms=0
                )
            except Exception:
                raw = None
            if raw is not None:
                try:
                    from ..elastic.remesh import RemeshRequest

                    req = RemeshRequest.from_json(raw.decode())
                except Exception:
                    req = None
                if req is not None and req.remesh_id != notified_remesh:
                    notified_remesh = req.remesh_id
                    with self._lock:
                        for state in self._listeners:
                            notify = getattr(
                                state, "on_remesh_requested", None
                            )
                            if notify is not None:
                                notify(req)
            try:
                val = self._client.get("__elastic__", key, timeout_ms=0)
            except Exception:
                val = None
            if val is not None:
                with self._lock:
                    for state in self._listeners:
                        state.on_hosts_updated(time.time(), "updated")
                return  # one notification per round
            self._stop.wait(_POLL_PERIOD_S)

    def register_listener(self, state) -> None:
        with self._lock:
            self._listeners.append(state)

    def remove_listener(self, state) -> None:
        with self._lock:
            if state in self._listeners:
                self._listeners.remove(state)

    # -- in-process remesh plumbing (elastic/remesh.py) -----------------
    def kv_client(self):
        """The rendezvous KV client (shard transport of the remesh
        state exchange)."""
        self.init()
        return self._client

    def remesh_ack(self, remesh_id: int, phase: str) -> None:
        """Acknowledge one remesh phase to the driver:
        ``__remesh__/<phase>_<id>_<rank>``.  ``pause`` and ``snapshot``
        acks carry the OLD rank, ``done`` the NEW one (the manager's
        rank is updated by :meth:`on_world_changed` in between)."""
        self.kv_client().put(
            "__remesh__", f"{phase}_{int(remesh_id)}_{self.rank}", b"1"
        )

    def remesh_wait_go(self, remesh_id: int,
                       timeout_s: float = 60.0) -> None:
        """Block until the driver flips ``go`` (every survivor
        published its shards) — or raise on ``abort``/timeout so the
        caller falls back to the restart path instead of wedging."""
        from ..exceptions import RemeshError

        deadline = time.monotonic() + max(timeout_s, 1.0)
        client = self.kv_client()
        while True:
            try:
                if client.get("__remesh__", f"abort_{int(remesh_id)}",
                              timeout_ms=0) is not None:
                    raise RemeshError(
                        f"driver aborted remesh {remesh_id}"
                    )
                if client.get("__remesh__", f"go_{int(remesh_id)}",
                              timeout_ms=0) is not None:
                    return
            except RemeshError:
                raise
            except Exception:
                pass  # KV blip: keep polling until the deadline
            if time.monotonic() > deadline:
                raise RemeshError(
                    f"remesh {remesh_id}: no go/abort from the driver "
                    f"within {timeout_s:.0f}s"
                )
            if self._stop.wait(0.1):
                raise RemeshError("worker shutting down mid-remesh")

    def on_world_changed(self, new_rank: int) -> None:
        """Adopt the post-remesh rank: heartbeats and later acks key on
        it (``reinit_world`` already rewrote the env triple)."""
        self.rank = int(new_rank)

    def remesh_join_request(self):
        """The :class:`~horovod_tpu.elastic.remesh.RemeshRequest` this
        worker was spawned to JOIN (``HVD_TPU_REMESH_JOIN=<id>`` in the
        spawn env), or None for a normal round worker."""
        raw_id = os.environ.get("HVD_TPU_REMESH_JOIN")
        if not raw_id:
            return None
        from ..elastic.remesh import RemeshRequest

        raw = self.kv_client().get(
            "__remesh__", f"begin_{self.round}", timeout_ms=10000
        )
        if raw is None:
            return None
        req = RemeshRequest.from_json(raw.decode())
        if req.remesh_id != int(raw_id):
            return None
        return req

    # -- state persistence across rounds (rank 0 writes) ----------------
    # Blobs are chunked: the controller protocol caps one frame at 64MB
    # (native hvd_ctrl_get also truncates reads at its buffer cap), so a
    # model+optimizer snapshot ships as <=16MB pieces with a manifest.
    _CHUNK = 16 << 20

    def save_state_blob(self, blob: bytes) -> None:
        if self.rank != 0 or self._client is None:
            return
        import hashlib

        n = max(1, (len(blob) + self._CHUNK - 1) // self._CHUNK)
        for i in range(n):
            self._client.put(
                "__elastic_state__", f"chunk_{i}",
                blob[i * self._CHUNK : (i + 1) * self._CHUNK],
            )
        manifest = f"{n}:{len(blob)}:{hashlib.sha256(blob).hexdigest()}"
        self._client.put("__elastic_state__", "manifest", manifest.encode())

    def load_state_blob(self) -> Optional[bytes]:
        if self._client is None:
            return None
        import hashlib

        manifest = self._client.get("__elastic_state__", "manifest", timeout_ms=0)
        if manifest is None:
            return None
        n, total, digest = manifest.decode().split(":")
        parts = []
        for i in range(int(n)):
            chunk = self._client.get(
                "__elastic_state__", f"chunk_{i}", timeout_ms=5000
            )
            if chunk is None:
                return None
            parts.append(chunk)
        blob = b"".join(parts)[: int(total)]
        if hashlib.sha256(blob).hexdigest() != digest:
            return None  # torn write (a newer commit is in flight)
        return blob

    def close(self) -> None:
        self._stop.set()
        if self._client is not None:
            self._client.close()
            self._client = None
