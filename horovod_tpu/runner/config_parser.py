"""Config-file → CLI-arg mapping for ``hvdrun``.

Reference: ``horovod/runner/common/util/config_parser.py`` — horovodrun
accepts ``--config-file`` (YAML) whose sections set the same knobs as
the CLI flags, with CLI flags winning on conflict.  PyYAML is not baked
into this image, so the parser accepts JSON or a two-level YAML subset
(``section:`` headers + indented ``key: value`` pairs — exactly the
shape the reference's config files use).
"""

from __future__ import annotations

import json
from typing import Any, Dict


def parse_config_file(path: str) -> Dict[str, Any]:
    """Load a JSON or simple-YAML config into a nested dict."""
    with open(path) as fh:
        text = fh.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        return json.loads(text)
    return _parse_simple_yaml(text)


def _parse_scalar(s: str) -> Any:
    s = s.strip()
    if s.lower() in ("true", "yes", "on"):
        return True
    if s.lower() in ("false", "no", "off"):
        return False
    if s.lower() in ("null", "none", "~", ""):
        return None
    for cast in (int, float):
        try:
            return cast(s)
        except ValueError:
            pass
    return s.strip("'\"")


def _strip_comment(line: str) -> str:
    """Drop a trailing ``# comment`` without corrupting values that
    contain '#': the hash must be outside quotes and either start the
    line or follow whitespace (YAML's rule).  A quote only opens a
    quoted scalar when it is the first character of the value — a lone
    apostrophe mid-word (``user's``) is plain text, per YAML."""
    colon = line.find(":")
    value_start = None
    if colon != -1:
        rest = line[colon + 1:]
        offset = len(rest) - len(rest.lstrip())
        if colon + 1 + offset < len(line):
            value_start = colon + 1 + offset
    in_quote = None
    for i, ch in enumerate(line):
        if in_quote:
            if ch == in_quote:
                in_quote = None
        elif ch in ("'", '"') and i == value_start:
            in_quote = ch
        elif ch == "#" and (i == 0 or line[i - 1] in " \t"):
            return line[:i]
    return line


def _parse_simple_yaml(text: str) -> Dict[str, Any]:
    """Two-level ``section:`` / ``  key: value`` parser (no lists,
    anchors, or multi-line scalars — enough for hvdrun config files)."""
    root: Dict[str, Any] = {}
    section: Dict[str, Any] | None = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = _strip_comment(raw).rstrip()
        if not line.strip():
            continue
        indented = line[0] in (" ", "\t")
        if ":" not in line:
            raise ValueError(f"line {lineno}: expected 'key: value' in {raw!r}")
        key, _, value = line.partition(":")
        key = key.strip()
        if not indented:
            if value.strip() == "":
                section = {}
                root[key] = section
            else:
                root[key] = _parse_scalar(value)
                section = None
        else:
            if section is None:
                raise ValueError(
                    f"line {lineno}: indented key {key!r} outside a section"
                )
            section[key] = _parse_scalar(value)
    return root


# config key → (argparse dest, transform). Mirrors the reference's
# sections: params / timeline / autotune / logging / elastic.
_MAPPING = {
    ("params", "fusion_threshold_mb"): "fusion_threshold_mb",
    ("timeline", "filename"): "timeline_filename",
    ("autotune", "enabled"): "autotune",
    ("autotune", "log_file"): "autotune_log_file",
    ("logging", "level"): "log_level",
    ("elastic", "min_np"): "min_np",
    ("elastic", "max_np"): "max_np",
    ("elastic", "discovery_script"): "discovery_script",
    ("ssh", "port"): "ssh_port",
    ("ssh", "identity_file"): "ssh_identity_file",
}


def apply_config_to_args(args, config: Dict[str, Any]) -> None:
    """Fill unset argparse fields from the config (CLI wins on conflict,
    matching the reference's override order)."""
    for (section, key), dest in _MAPPING.items():
        value = config.get(section, {})
        if not isinstance(value, dict):
            continue
        # Identity check, not ==: an explicit CLI 0 must not read as
        # "unset" (0 == False in Python).
        current = getattr(args, dest, None)
        if key in value and (current is None or current is False):
            setattr(args, dest, value[key])
