"""Lightweight HTTP ``/metrics`` + ``/health`` endpoint for the elastic
driver.

The reference driver has no health or metrics surface at all — the only
way to know an elastic job's state is to grep its stderr.  This server
gives the driver process a scrapeable surface:

* ``GET /metrics`` — Prometheus text format: the driver's own registry
  (``horovod_tpu.metrics``) plus, via ``workers_fn``, the latest
  snapshot each worker pushed through the existing KV store
  (``__metrics__/rank_<r>``, pushed by the heartbeat thread in
  ``elastic_worker.py``), every worker series labeled ``rank="<r>"``.
* ``GET /health`` — JSON from ``health_fn`` (round number, live
  workers, blacklist, available slots), HTTP 200/503 by its
  ``"status"`` field.
* ``GET /trace`` — the cross-rank exchange-tracing summary
  (``trace/straggler.py``): per-rank phase p50/p99 from the
  ``trace.phase_seconds.*`` histograms each worker's heartbeat
  pushed, the straggler verdicts (which rank is slow, in which
  phase), and each rank's flight-recorder anomaly-dump index.  One
  detection pass per scrape; the verdicts also publish as
  ``trace.straggler{rank=,phase=}`` gauges so a Prometheus scrape of
  ``/metrics`` sees them too (docs/tracing.md).
* ``GET /tenants`` — per-tenant accounting for the multi-tenant
  exchange arbiter (``svc/arbiter.py``): queue depth, in-flight count,
  ICI/DCN rail bytes, admission/queue wait p50/p99, and configured
  share vs observed usage per tenant, aggregated from the same worker
  KV metric pushes ``/metrics`` renders (docs/multitenant.md).
* ``GET /slo`` — the SLO watchdog's view (``runner/slo.py``): the
  per-tenant specs parsed from ``HVD_TPU_SLO_SPEC``, the latest
  observed step-time/p99 per tenant with breach hysteresis state, and
  the remediation history the self-healing ladder
  (``elastic/remediate.py``) has taken — which rung, which phases,
  outcome, and current slice placement (docs/fault_tolerance.md).
* ``GET /prof`` — the device-time profiling plane (``prof/``):
  compiled-program introspection (XLA cost/memory analysis + compile
  cost per signature), per-step host-gap and dispatches-per-step, MFU
  per workload/tenant, capture-window state, and the perf-regression
  sentinel's last stored-vs-observed verdict — aggregated per rank
  from the same worker KV pushes ``/metrics`` renders
  (docs/observability.md).  ``/health`` additionally carries the
  staged device-probe doctor's verdict (``tools/probe_doctor.py``)
  under a ``probe`` field, so a dead device layer is visible from the
  driver without grepping bench records.
* ``GET /serve`` — the inference serving plane (``serve/``):
  requests/sec and tokens/sec per replica, queue depth, prefill /
  decode / TTFT p50/p99, KV-pool occupancy, per-replica MFU, and the
  latest serve bench record — aggregated from the same worker KV
  pushes (docs/serving.md).
* ``GET/POST /schedules`` — the persistent autotuning database
  (``sched/store.py``): GET returns every stored (bucket_bytes, wire,
  lowering) winner (``?key=<hex>`` filters to one), POST merges a
  ``{"entries": {...}}`` payload keep-best — how a tuned worker
  anywhere in the fleet seeds every later identical job
  (docs/autotune.md).

Built on ``http.server.ThreadingHTTPServer`` — stdlib only, daemon
threads, zero hot-path cost (everything is rendered at scrape time).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import metrics
from ..utils.logging import get_logger

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    server_version = "hvd-tpu-telemetry/1.0"

    def log_message(self, fmt, *args):  # stderr silence: we have logging
        get_logger().debug("telemetry http: " + fmt, *args)

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server API)
        srv: "TelemetryServer" = self.server.telemetry  # type: ignore[attr-defined]
        try:
            route = self.path.split("?")[0]
            if route == "/metrics":
                self._send(200, srv.render_metrics().encode(),
                           PROMETHEUS_CONTENT_TYPE)
            elif route == "/health":
                payload = srv.render_health()
                code = 200 if payload.get("status", "ok") == "ok" else 503
                self._send(code, json.dumps(payload).encode(),
                           "application/json")
            elif route == "/schedules":
                payload = srv.render_schedules(self._query_key())
                code = 200 if payload is not None else 404
                self._send(code, json.dumps(
                    payload if payload is not None
                    else {"error": "no schedule store"}
                ).encode(), "application/json")
            elif route == "/trace":
                payload = srv.render_trace()
                code = 200 if payload is not None else 404
                self._send(code, json.dumps(
                    payload if payload is not None
                    else {"error": "no trace summary"}
                ).encode(), "application/json")
            elif route == "/tenants":
                payload = srv.render_tenants()
                code = 200 if payload is not None else 404
                self._send(code, json.dumps(
                    payload if payload is not None
                    else {"error": "no tenant accounting"}
                ).encode(), "application/json")
            elif route == "/slo":
                payload = srv.render_slo()
                code = 200 if payload is not None else 404
                self._send(code, json.dumps(
                    payload if payload is not None
                    else {"error": "no SLO watchdog"}
                ).encode(), "application/json")
            elif route == "/prof":
                self._send(200, json.dumps(
                    srv.render_prof(), default=str
                ).encode(), "application/json")
            elif route == "/serve":
                self._send(200, json.dumps(
                    srv.render_serve(), default=str
                ).encode(), "application/json")
            else:
                self._send(
                    404,
                    b"not found: try /metrics, /health, /schedules, "
                    b"/trace, /tenants, /slo, /prof or /serve\n",
                    "text/plain")
        except Exception as e:  # a scrape must never kill the server
            self._send(500, f"telemetry error: {e}\n".encode(),
                       "text/plain")

    def do_POST(self):  # noqa: N802 (http.server API)
        srv: "TelemetryServer" = self.server.telemetry  # type: ignore[attr-defined]
        try:
            if self.path.split("?")[0] != "/schedules":
                self._send(404, b"not found: POST /schedules\n",
                           "text/plain")
                return
            if srv.schedule_store is None:
                self._send(404, json.dumps(
                    {"error": "no schedule store"}).encode(),
                    "application/json")
                return
            length = int(self.headers.get("Content-Length") or 0)
            if not 0 < length <= 16 << 20:  # bound a hostile payload
                self._send(400, b"bad Content-Length\n", "text/plain")
                return
            try:
                body = json.loads(self.rfile.read(length))
                entries = body.get("entries", body)
                if not isinstance(entries, dict):
                    raise ValueError("entries must be an object")
            except (ValueError, UnicodeDecodeError) as e:
                self._send(400, f"bad schedules payload: {e}\n".encode(),
                           "text/plain")
                return
            merged = srv.schedule_store.merge(entries)
            self._send(200, json.dumps({"merged": merged}).encode(),
                       "application/json")
        except Exception as e:  # a push must never kill the server
            self._send(500, f"telemetry error: {e}\n".encode(),
                       "text/plain")

    def _query_key(self):
        from urllib.parse import parse_qs, urlsplit

        qs = parse_qs(urlsplit(self.path).query)
        return (qs.get("key") or [None])[0]


class _QuietHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def handle_error(self, request, client_address):
        # A scraper disconnecting mid-response (timeout, page reload)
        # is routine — log it instead of stack-tracing to stderr.
        import sys

        get_logger().debug(
            "telemetry http client error from %s: %s",
            client_address, sys.exc_info()[1],
        )


class TelemetryServer:
    """Owns the listening socket; ``health_fn`` and ``workers_fn`` are
    called per scrape (both optional)."""

    def __init__(
        self,
        port: int = 0,
        bind_host: str = "0.0.0.0",
        health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        workers_fn: Optional[
            Callable[[], List[Tuple[int, Dict[str, Any]]]]
        ] = None,
        schedule_store=None,
        trace_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        tenants_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        slo_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        prof_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        probe_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        serve_fn: Optional[Callable[[], Dict[str, Any]]] = None,
    ):
        self.health_fn = health_fn
        self.workers_fn = workers_fn
        self.schedule_store = schedule_store
        self.trace_fn = trace_fn
        self.tenants_fn = tenants_fn
        self.slo_fn = slo_fn
        self.prof_fn = prof_fn
        self.probe_fn = probe_fn
        self.serve_fn = serve_fn
        self._server = _QuietHTTPServer((bind_host, port), _Handler)
        self._server.telemetry = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="hvd_tpu_telemetry_http",
        )
        self._thread.start()
        get_logger().info(
            "telemetry endpoint on :%d (/metrics, /health)", self.port
        )

    def render_metrics(self) -> str:
        parts = [metrics.render_prometheus()]
        if self.workers_fn is not None:
            for rank, snap in self.workers_fn():
                try:
                    parts.append(metrics.render_prometheus(
                        snap, extra_labels={"rank": str(rank)}
                    ))
                except Exception as e:
                    get_logger().warning(
                        "bad worker metrics push from rank %s: %s",
                        rank, e,
                    )
        return "".join(parts)

    def render_health(self) -> Dict[str, Any]:
        payload = (
            {"status": "ok"} if self.health_fn is None
            else self.health_fn()
        )
        # Device-probe doctor verdict (satellite: the staged probe used
        # to live only inside bench skip records).  Additive field —
        # a sick probe does not flip the health status: the driver
        # process itself is fine, its device layer is what's sick.
        if self.probe_fn is not None:
            try:
                payload = dict(payload)
                payload["probe"] = self.probe_fn()
            except Exception as e:  # pragma: no cover - defensive
                payload["probe"] = {"status": "error", "error": str(e)}
        return payload

    def render_trace(self) -> Optional[Dict[str, Any]]:
        """``GET /trace`` payload: an explicit ``trace_fn`` (the
        elastic driver installs the straggler-detection pass), else —
        when worker snapshots are reachable — a detection pass run
        right here, so any server with ``workers_fn`` serves the
        summary.  None when neither exists (-> 404)."""
        if self.trace_fn is not None:
            return self.trace_fn()
        if self.workers_fn is None:
            return None
        from ..trace import straggler

        per_rank = {rank: snap for rank, snap in self.workers_fn()}
        return straggler.trace_payload(per_rank)

    def render_tenants(self) -> Optional[Dict[str, Any]]:
        """``GET /tenants`` payload: an explicit ``tenants_fn`` (the
        elastic driver installs one with round context), else — when
        worker snapshots are reachable — the aggregation run right
        here; a driver-less process serves its OWN registry snapshot so
        a single-process job still has the surface.  None only when
        nothing can be aggregated (-> 404)."""
        if self.tenants_fn is not None:
            return self.tenants_fn()
        from ..svc.arbiter import tenants_payload

        if self.workers_fn is not None:
            per_rank = {rank: snap for rank, snap in self.workers_fn()}
            if per_rank:
                return tenants_payload(per_rank)
        return tenants_payload({0: metrics.snapshot()})

    def render_prof(self) -> Dict[str, Any]:
        """``GET /prof`` payload: an explicit ``prof_fn`` (the elastic
        driver installs one with round context), else the local
        profiling-plane payload — with the per-rank digest folded in
        when worker snapshots are reachable.  Always a dict: an empty
        profiling plane still answers 200 with its (empty) structure."""
        if self.prof_fn is not None:
            return self.prof_fn()
        from .. import prof

        if self.workers_fn is not None:
            per_rank = {rank: snap for rank, snap in self.workers_fn()}
            if per_rank:
                return prof.prof_payload(per_rank)
        return prof.prof_payload()

    def render_serve(self) -> Dict[str, Any]:
        """``GET /serve`` payload: an explicit ``serve_fn`` (a serving
        deployment installs one with its own context), else the
        serving-plane aggregation (``serve/frontend.serve_payload``) —
        over worker snapshots when reachable, the local registry
        otherwise.  Always a dict: a pod with no serving replicas
        still answers 200 with (empty) structure."""
        if self.serve_fn is not None:
            return self.serve_fn()
        from ..serve.frontend import serve_payload

        if self.workers_fn is not None:
            per_rank = {rank: snap for rank, snap in self.workers_fn()}
            if per_rank:
                return serve_payload(per_rank)
        return serve_payload()

    def render_slo(self) -> Optional[Dict[str, Any]]:
        """``GET /slo`` payload: whatever ``slo_fn`` renders (the
        elastic driver installs the SLO controller's ``payload()``).
        None when no watchdog is wired — no ``HVD_TPU_SLO_SPEC``
        means no SLO surface (-> 404)."""
        if self.slo_fn is None:
            return None
        return self.slo_fn()

    def render_schedules(
        self, key: Optional[str] = None
    ) -> Optional[Dict[str, Any]]:
        """``GET /schedules`` payload: the whole store, or one entry
        (stale-validated via ``lookup``) when ``?key=`` is given.
        None when the server has no store (-> 404)."""
        store = self.schedule_store
        if store is None:
            return None
        if key:
            entry = store.lookup(key)
            return {"entries": ({key: entry} if entry else {})}
        return {"entries": store.entries()}

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# ------------------------------------------------------- probe doctor

_probe_lock = threading.Lock()
_probe_result: Optional[Dict[str, Any]] = None
_probe_thread: Optional[threading.Thread] = None


def _load_probe_doctor():
    """Import ``tools/probe_doctor.py`` — as a module when ``tools`` is
    importable (repo-root runs), else by file path relative to the
    package root.  None when neither works (an installed wheel without
    the tools tree)."""
    try:
        from tools import probe_doctor  # type: ignore[import-not-found]

        return probe_doctor
    except Exception:
        pass
    try:
        import importlib.util
        import os

        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.path.join(root, "tools", "probe_doctor.py")
        spec = importlib.util.spec_from_file_location(
            "hvd_tpu_probe_doctor", path)
        if spec is None or spec.loader is None:
            return None
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


def _run_probe() -> None:
    global _probe_result
    doctor = _load_probe_doctor()
    if doctor is None:
        result: Dict[str, Any] = {"status": "unavailable", "verdict": None}
    else:
        try:
            d = doctor.diagnose()
            failing = next(
                (s for s in d.get("stages", [])
                 if s.get("status") != "ok"), None,
            )
            result = {
                "status": d.get("status"),
                "verdict": d.get("verdict"),
                "failing_stage": failing.get("stage") if failing else None,
                "stderr_tail": (
                    failing.get("stderr_tail") if failing else None
                ),
            }
        except Exception as e:
            result = {"status": "error",
                      "verdict": {"stage": "doctor", "cause": str(e)}}
    with _probe_lock:
        _probe_result = result


def probe_payload() -> Dict[str, Any]:
    """The ``probe`` field of ``GET /health``: the staged device-probe
    doctor's verdict (import -> backend init -> first compute).  The
    probe runs worker subprocesses with their own timeouts, so the
    first scrape kicks it off on a background daemon thread and answers
    ``pending`` until the verdict lands (then it's cached — the probe
    diagnoses a boot-time condition, not a live signal)."""
    global _probe_thread
    with _probe_lock:
        if _probe_result is not None:
            return dict(_probe_result)
        if _probe_thread is None or not _probe_thread.is_alive():
            _probe_thread = threading.Thread(
                target=_run_probe, daemon=True,
                name="hvd_tpu_probe_doctor",
            )
            _probe_thread.start()
    return {"status": "pending", "verdict": None}


def reset_probe_cache() -> None:
    """Forget the cached probe verdict (test isolation)."""
    global _probe_result, _probe_thread
    with _probe_lock:
        _probe_result = None
        _probe_thread = None
