"""Pure-Python controller speaking the native controller's protocol.

Fallback for environments without a C++ toolchain: wire-compatible with
``cpp/src/controller.cc`` (frame = 'HVDC' | opcode | len | payload |
HMAC-SHA256), so a Python server can serve native clients and vice
versa.  Reference analog: the HTTP KV store
(``horovod/runner/http/http_server.py``) + HMAC'd RPC
(``runner/common/util/secret.py``).
"""

from __future__ import annotations

import hashlib
import hmac
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, Optional

OP_PUT, OP_GET, OP_COUNT, OP_DELSCOPE, OP_PING = 1, 2, 3, 4, 5
ST_OK, ST_NOTFOUND, ST_AUTH, ST_BAD = 0, 1, 2, 3
MAX_PAYLOAD = 64 << 20


def _mac(secret: bytes, data: bytes) -> bytes:
    return hmac.new(secret, data, hashlib.sha256).digest()


def _recv_all(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _put_str(parts: list, s: str) -> None:
    b = s.encode()
    parts.append(struct.pack(">I", len(b)))
    parts.append(b)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        server: PyControllerServer = self.server.controller  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            hdr = _recv_all(sock, 9)
            if hdr is None or hdr[:4] != b"HVDC":
                return
            op = hdr[4]
            (length,) = struct.unpack(">I", hdr[5:9])
            if length > MAX_PAYLOAD:
                return
            payload = _recv_all(sock, length) if length else b""
            mac = _recv_all(sock, 32)
            if payload is None or mac is None:
                return
            authed = bytes([op]) + struct.pack(">I", length) + payload
            status, out = ST_OK, b""
            if not hmac.compare_digest(_mac(server.secret, authed), mac):
                status = ST_AUTH
            else:
                status, out = server.dispatch(op, payload)
            reply = bytes([status]) + struct.pack(">I", len(out)) + out
            sock.sendall(reply + _mac(server.secret, reply))


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PyControllerServer:
    """Protocol-compatible with native ``hvd_ctrl_server_*``."""

    def __init__(self, secret: str, world: int, bind_host: str = "0.0.0.0",
                 port: int = 0):
        self.secret = secret.encode()
        self.world = world
        self._lock = threading.Lock()
        self._store: Dict[str, Dict[str, bytes]] = {}
        self._server = _TCPServer((bind_host, port), _Handler)
        self._server.controller = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def dispatch(self, op: int, payload: bytes):
        pos = 0

        def get_str():
            nonlocal pos
            (n,) = struct.unpack_from(">I", payload, pos)
            pos += 4
            s = payload[pos : pos + n]
            pos += n
            return s.decode()

        try:
            if op == OP_PUT:
                scope, key = get_str(), get_str()
                (n,) = struct.unpack_from(">I", payload, pos)
                pos += 4
                val = payload[pos : pos + n]
                with self._lock:
                    self._store.setdefault(scope, {})[key] = val
                return ST_OK, b""
            if op == OP_GET:
                scope, key = get_str(), get_str()
                with self._lock:
                    val = self._store.get(scope, {}).get(key)
                return (ST_OK, val) if val is not None else (ST_NOTFOUND, b"")
            if op == OP_COUNT:
                scope = get_str()
                with self._lock:
                    n = len(self._store.get(scope, {}))
                return ST_OK, struct.pack(">I", n)
            if op == OP_DELSCOPE:
                scope = get_str()
                with self._lock:
                    self._store.pop(scope, None)
                return ST_OK, b""
            if op == OP_PING:
                return ST_OK, b"pong"
        except Exception:
            pass
        return ST_BAD, b""

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class PyControllerClient:
    """Protocol-compatible with native ``hvd_ctrl_client_*``."""

    def __init__(self, host: str, port: int, secret: str, rank: int):
        self.secret = secret.encode()
        self.rank = rank
        self._lock = threading.Lock()
        self._sock = socket.create_connection((host, port), timeout=60)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _request(self, op: int, payload: bytes):
        with self._lock:
            frame = b"HVDC" + bytes([op]) + struct.pack(">I", len(payload)) + payload
            authed = bytes([op]) + struct.pack(">I", len(payload)) + payload
            self._sock.sendall(frame + _mac(self.secret, authed))
            hdr = _recv_all(self._sock, 5)
            if hdr is None:
                raise OSError("controller connection lost")
            status = hdr[0]
            (length,) = struct.unpack(">I", hdr[1:5])
            body = _recv_all(self._sock, length) if length else b""
            mac = _recv_all(self._sock, 32)
            reply = bytes([status]) + struct.pack(">I", length) + (body or b"")
            if mac is None or not hmac.compare_digest(
                _mac(self.secret, reply), mac
            ):
                raise OSError("controller reply auth failed")
            return status, body or b""

    def put(self, scope: str, key: str, value: bytes) -> None:
        parts: list = []
        _put_str(parts, scope)
        _put_str(parts, key)
        parts.append(struct.pack(">I", len(value)))
        parts.append(value)
        status, _ = self._request(OP_PUT, b"".join(parts))
        if status != ST_OK:
            raise OSError("controller put failed")

    def get(self, scope: str, key: str, timeout_ms: int = -1) -> Optional[bytes]:
        parts: list = []
        _put_str(parts, scope)
        _put_str(parts, key)
        payload = b"".join(parts)
        deadline = time.monotonic() + timeout_ms / 1000 if timeout_ms >= 0 else None
        while True:
            status, body = self._request(OP_GET, payload)
            if status == ST_OK:
                return body
            if status != ST_NOTFOUND:
                raise OSError("controller get failed")
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.05)

    def delete_scope(self, scope: str) -> None:
        parts: list = []
        _put_str(parts, scope)
        self._request(OP_DELSCOPE, b"".join(parts))

    def barrier(self, name: str, count: int, timeout_ms: int = -1) -> bool:
        scope = f"__barrier__/{name}"
        self.put(scope, str(self.rank), b"1")
        parts: list = []
        _put_str(parts, scope)
        payload = b"".join(parts)
        deadline = time.monotonic() + timeout_ms / 1000 if timeout_ms >= 0 else None
        while True:
            status, body = self._request(OP_COUNT, payload)
            if status != ST_OK or len(body) != 4:
                return False
            (n,) = struct.unpack(">I", body)
            if n >= count:
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def close(self) -> None:
        self._sock.close()


def make_server(secret: str, world: int, bind_host: str = "0.0.0.0",
                port: int = 0, prefer_native: bool = True):
    """Native server when built, Python otherwise (same protocol)."""
    if prefer_native:
        from .. import native

        if native.available():
            return native.ControllerServer(secret, world, bind_host, port)
    return PyControllerServer(secret, world, bind_host, port)


def make_client(host: str, port: int, secret: str, rank: int,
                prefer_native: bool = True):
    if prefer_native:
        from .. import native

        if native.available():
            return native.ControllerClient(host, port, secret, rank)
    return PyControllerClient(host, port, secret, rank)
