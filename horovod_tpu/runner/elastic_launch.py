"""Elastic launch glue for the CLI (reference ``_run_elastic`` +
``launch_gloo_elastic``, ``horovod/runner/launch.py:621`` /
``gloo_run.py:287``)."""

from __future__ import annotations

import argparse

from ..elastic.discovery import FixedHosts, HostDiscoveryScript, HostManager
from . import hosts as hosts_mod
from .elastic_driver import ElasticDriver
from .launch import env_from_args


def launch_elastic(args: argparse.Namespace) -> int:
    if args.discovery_script:
        discovery = HostDiscoveryScript(args.discovery_script)
    elif args.hosts:
        discovery = FixedHosts(
            {h.hostname: h.slots for h in hosts_mod.parse_hosts(args.hosts)}
        )
    else:
        raise SystemExit(
            "elastic mode needs --host-discovery-script or -H hosts"
        )
    min_np = args.min_np or args.np
    driver = ElasticDriver(
        HostManager(discovery), min_np=min_np, max_np=args.max_np,
        telemetry_port=getattr(args, "telemetry_port", None),
    )
    driver.start_discovery()
    return driver.run_rounds(args.command, extra_env=env_from_args(args))
