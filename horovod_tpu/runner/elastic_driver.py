"""Elastic driver: membership rounds with full worker respawn.

Reference: ``horovod/runner/elastic/driver.py`` — background discovery
loop, rank reassignment preserving surviving workers, worker respawn on
new slots, blacklist on failure, ``reset_limit`` bound on membership
changes.

TPU redesign rationale: XLA compiles for a fixed mesh, and a plain
``jax.distributed`` re-``initialize()`` in-process fails once the
backend exists (probe artifact: ``tools/probe_remesh_findings.json``,
case B).  An in-process survivor path DOES exist through a full backend
reset (case B2, exposed as the experimental
``hvd.elastic.reinit_world``), but this driver defaults to restarting
*all* worker processes per round: the respawn path is validated on
every backend (live-TPU PJRT teardown via ``clear_backends`` is not),
invalidates no in-flight host state, and recompilation — the dominant
restart cost either way — is bounded by the persistent compilation
cache, not by process reuse.  Training state survives rounds through
the launcher KV store / checkpoints (``elastic/state.py`` persists
commits when elastic env is present), which also covers the
all-workers-lost case the reference cannot (its in-memory state dies
with the last survivor).

Worker exit-code contract (read by this driver):
  0                    job finished -> round succeeds, driver exits
  73 (RESTART_CODE)    host update acknowledged -> respawn a new round
  anything else        failure -> blacklist the worker's host, new round
"""

from __future__ import annotations

import os
import secrets as pysecrets
import threading
import time
from typing import Callable, Dict, List, Optional

from ..elastic.discovery import HostManager
from ..utils.logging import get_logger
from . import controller_py, exec_utils
from . import hosts as hosts_mod
from .launch import free_port, make_worker_env

RESTART_CODE = 73

DISCOVERY_PERIOD_S = 1.0  # reference driver.py:30


def _with_compilation_cache(extra_env):
    """Default a job-scoped persistent XLA compilation cache into the
    worker env (recompilation dominates respawn-per-round restart cost
    on TPU; measured in tests/integration/test_elastic.py::
    test_elastic_restart_cost_bounded).

    Precedence: HVD_TPU_NO_COMPILATION_CACHE=1 disables; an explicit
    extra_env dir wins; a driver-environment dir is COPIED into the
    worker env (remote ssh workers never inherit the driver
    environment); otherwise a fresh temp dir is created and returned
    for end-of-job cleanup.  Returns (env, created_dir_or_None).
    """
    env = dict(extra_env or {})
    if (os.environ.get("HVD_TPU_NO_COMPILATION_CACHE", "") == "1"
            or "JAX_COMPILATION_CACHE_DIR" in env):
        return env, None
    if "JAX_COMPILATION_CACHE_DIR" in os.environ:
        env["JAX_COMPILATION_CACHE_DIR"] = (
            os.environ["JAX_COMPILATION_CACHE_DIR"]
        )
        return env, None
    import tempfile

    created = tempfile.mkdtemp(prefix="hvd_tpu_xla_cache_")
    env["JAX_COMPILATION_CACHE_DIR"] = created
    return env, created


class ElasticDriver:
    def __init__(
        self,
        host_manager: HostManager,
        min_np: int,
        max_np: Optional[int] = None,
        reset_limit: Optional[int] = None,
        cooldown_s: float = 0.5,
    ):
        self.host_manager = host_manager
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.cooldown_s = cooldown_s
        self.rounds = 0
        self._shutdown = threading.Event()
        self._membership_changed = threading.Event()
        self._discovery_thread: Optional[threading.Thread] = None

    # -- discovery loop (reference driver.py:181) ------------------------
    def start_discovery(self) -> None:
        def loop():
            while not self._shutdown.is_set():
                try:
                    if self.host_manager.update_available_hosts():
                        self._membership_changed.set()
                except Exception as e:  # discovery script hiccup
                    get_logger().warning("host discovery failed: %s", e)
                self._shutdown.wait(DISCOVERY_PERIOD_S)

        self.host_manager.update_available_hosts()
        self._membership_changed.clear()
        self._discovery_thread = threading.Thread(target=loop, daemon=True)
        self._discovery_thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._discovery_thread:
            self._discovery_thread.join(timeout=5)

    def wait_for_available_slots(
        self, min_np: int, timeout_s: Optional[float] = None
    ) -> bool:
        """Block until the discovered world can host min_np workers
        (reference ``wait_for_available_slots``; timeout from
        ``HVD_TPU_ELASTIC_TIMEOUT`` / ``HOROVOD_ELASTIC_TIMEOUT``,
        default 600 s like reference ``ELASTIC_TIMEOUT_SECS``)."""
        if timeout_s is None:
            from ..utils import env as hvd_env

            timeout_s = hvd_env.get_float(hvd_env.ELASTIC_TIMEOUT, 600.0)
        deadline = time.monotonic() + timeout_s
        while True:
            # slots first, deadline second: a zero timeout must still
            # succeed immediately when capacity is already there
            if self.host_manager.available_slots() >= min_np:
                return True
            remaining = deadline - time.monotonic()
            if self._shutdown.is_set() or remaining <= 0:
                return False
            # shutdown-responsive sleep, clipped so fractional timeouts
            # are honored instead of overshooting by a full period
            self._shutdown.wait(min(DISCOVERY_PERIOD_S, remaining))

    def current_assignments(self) -> List[hosts_mod.SlotInfo]:
        hosts = [
            hosts_mod.HostInfo(h, s)
            for h, s in sorted(self.host_manager.current_hosts.items())
        ]
        total = sum(h.slots for h in hosts)
        np_ = min(total, self.max_np) if self.max_np else total
        if np_ < self.min_np:
            raise RuntimeError(
                f"only {total} slot(s) available, need min_np={self.min_np}"
            )
        return hosts_mod.get_host_assignments(hosts, np_, max_np=np_)

    # -- main loop -------------------------------------------------------
    def run_rounds(
        self,
        command: List[str],
        *,
        extra_env: Optional[Dict[str, str]] = None,
        ssh_port: Optional[int] = None,
        ssh_identity_file: Optional[str] = None,
        publish: Optional[Dict[tuple, bytes]] = None,
        worker_factory: Optional[Callable] = None,
        rendezvous_addr: Optional[str] = None,
        result_collector: Optional[Callable] = None,
    ) -> int:
        """Spawn worker rounds until success, failure beyond limits, or
        reset_limit exhausted.  Returns the job exit code.

        ``publish`` entries ({(scope, key): blob}) are put into the
        rendezvous KV before the first round — how function payloads
        reach workers (e.g. ``task_runner`` fetches ``__run__/func``),
        mirroring ``horovod.run``'s KV-store func delivery.

        ``worker_factory`` replaces the ssh/local exec
        (``exec_utils.WorkerProcess``) with another transport that
        spawns ``command`` on a slot's host — e.g. the Spark task-agent
        dispatch (``spark/elastic.py``).  ``rendezvous_addr`` overrides
        the NIC probe when the caller already knows the address workers
        can dial (Spark agents dialed it to register).
        ``result_collector(control, np, round_id)`` runs on success
        before the KV server closes — how ``spark.run_elastic`` fetches
        the winning round's per-rank results.
        """
        # Respawn-per-round makes recompilation the dominant restart
        # cost on TPU; a job-scoped persistent XLA compilation cache
        # turns round-2+ compiles into cache reads (measured in
        # tests/integration/test_elastic.py::test_elastic_restart_cost
        # _bounded).  Opt out with HVD_TPU_NO_COMPILATION_CACHE=1 or by
        # setting JAX_COMPILATION_CACHE_DIR yourself.
        extra_env, created_cache_dir = _with_compilation_cache(extra_env)
        secret = pysecrets.token_hex(16)
        server = controller_py.make_server(secret, self.min_np)
        control = controller_py.make_client(
            "127.0.0.1", server.port, secret, rank=-1
        )
        for (scope, key), blob in (publish or {}).items():
            control.put(scope, key, blob)
        try:
            while True:
                if not self.wait_for_available_slots(self.min_np):
                    return 1
                try:
                    assignments = self.current_assignments()
                except RuntimeError as e:
                    get_logger().warning("%s", e)
                    time.sleep(DISCOVERY_PERIOD_S)
                    continue
                self.rounds += 1
                round_id = self.rounds
                self._membership_changed.clear()
                control.put("__elastic__", "round", str(round_id).encode())
                control.put("__elastic__", f"round_{round_id}_np",
                            str(len(assignments)).encode())
                get_logger().warning(
                    "elastic round %d: %d worker(s) on %d host(s)",
                    round_id, len(assignments), assignments[-1].cross_size,
                )
                coordinator_host = (
                    "127.0.0.1"
                    if exec_utils.is_local(assignments[0].hostname)
                    else assignments[0].hostname
                )
                coordinator_addr = f"{coordinator_host}:{free_port()}"
                # The rendezvous KV runs in this driver process: remote
                # workers must dial our routable address, not loopback —
                # mutually verified via the NIC probe on multi-NIC hosts
                # (unless the caller's transport already knows it).
                round_rdv_addr = rendezvous_addr
                if round_rdv_addr is None:
                    round_rdv_addr = exec_utils.probe_routable_addr(
                        assignments, ssh_port=ssh_port,
                        ssh_identity_file=ssh_identity_file,
                    )
                make_worker = worker_factory or exec_utils.WorkerProcess
                begin = getattr(make_worker, "begin_round", None)
                if begin is not None:
                    begin(round_id)
                workers = []
                spawn_failed_host = None
                for slot in assignments:
                    env = make_worker_env(
                        slot, coordinator_addr, round_rdv_addr, server.port,
                        secret, extra_env,
                    )
                    env["HVD_TPU_ELASTIC"] = "1"
                    env["HVD_TPU_ELASTIC_ROUND"] = str(round_id)
                    try:
                        workers.append(
                            make_worker(
                                slot.rank, slot.hostname, command, env,
                                ssh_port=ssh_port,
                                ssh_identity_file=ssh_identity_file,
                            )
                        )
                    except Exception as e:
                        # A host lost between assignment and spawn (e.g.
                        # a Spark executor death in the discovery
                        # staleness window) fails the ROUND, not the
                        # job: blacklist and go again.
                        get_logger().warning(
                            "worker spawn on %s failed: %s",
                            slot.hostname, e,
                        )
                        spawn_failed_host = slot.hostname
                        break
                if spawn_failed_host is not None:
                    for w in workers:
                        w.terminate()
                    for w in workers:
                        w.wait()
                    self.host_manager.blacklist(spawn_failed_host)
                    if self.host_manager.available_slots() >= self.min_np:
                        time.sleep(self.cooldown_s)
                        continue
                    return 1
                rc = self._watch_round(workers, assignments, control, round_id)
                if rc == 0:
                    if result_collector is not None:
                        result_collector(
                            control, len(assignments), round_id
                        )
                    return 0
                if rc == RESTART_CODE:
                    if (
                        self.reset_limit is not None
                        and self.rounds > self.reset_limit
                    ):
                        get_logger().error(
                            "reset_limit %d exceeded", self.reset_limit
                        )
                        return 1
                    time.sleep(self.cooldown_s)
                    continue
                # real failure: can we keep going?
                if self.host_manager.available_slots() >= self.min_np:
                    time.sleep(self.cooldown_s)
                    continue
                return rc
        finally:
            control.close()
            server.stop()
            self.stop()
            if created_cache_dir is not None:
                # job-scoped cache (a fresh dir per job): useless after
                # the job and easily GBs of XLA programs — remove it
                import shutil

                shutil.rmtree(created_cache_dir, ignore_errors=True)

    def _watch_round(
        self,
        workers: List[exec_utils.WorkerProcess],
        assignments: List[hosts_mod.SlotInfo],
        control,
        round_id: int,
    ) -> int:
        """Wait for the round to end.  Membership change -> signal workers
        (they exit RESTART_CODE at the next commit); failure -> blacklist
        and terminate; success of all -> 0."""
        pending = set(range(len(workers)))
        saw_failure = 0
        while pending:
            if self._membership_changed.is_set():
                control.put(
                    "__elastic__", f"hosts_updated_{round_id}", b"1"
                )
                self._membership_changed.clear()
            for i in sorted(pending):
                rc = workers[i].returncode
                if rc is None:
                    continue
                pending.discard(i)
                if rc == 0:
                    continue
                if rc == RESTART_CODE:
                    # graceful restart request: drain the others too
                    control.put(
                        "__elastic__", f"hosts_updated_{round_id}", b"1"
                    )
                    saw_failure = saw_failure or RESTART_CODE
                    continue
                saw_failure = rc
                self.host_manager.blacklist(assignments[i].hostname)
                # a dead peer wedges collectives: end the round
                for j in pending:
                    workers[j].terminate()
                for j in pending:
                    workers[j].wait()
                pending = set()
                break
            time.sleep(0.1)
        for w in workers:
            w.wait()
        if saw_failure == RESTART_CODE:
            return RESTART_CODE
        if saw_failure:
            return RESTART_CODE if self.host_manager.available_slots() >= self.min_np else saw_failure
        return 0
