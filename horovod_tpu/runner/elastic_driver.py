"""Elastic driver: membership rounds with full worker respawn.

Reference: ``horovod/runner/elastic/driver.py`` — background discovery
loop, rank reassignment preserving surviving workers, worker respawn on
new slots, blacklist on failure, ``reset_limit`` bound on membership
changes.

TPU redesign rationale: XLA compiles for a fixed mesh, and a plain
``jax.distributed`` re-``initialize()`` in-process fails once the
backend exists (probe artifact: ``tools/probe_remesh_findings.json``,
case B).  An in-process survivor path DOES exist through a full backend
reset (case B2, exposed as the experimental
``hvd.elastic.reinit_world``), but this driver defaults to restarting
*all* worker processes per round: the respawn path is validated on
every backend (live-TPU PJRT teardown via ``clear_backends`` is not),
invalidates no in-flight host state, and recompilation — the dominant
restart cost either way — is bounded by the persistent compilation
cache, not by process reuse.  Training state survives rounds through
the launcher KV store / checkpoints (``elastic/state.py`` persists
commits when elastic env is present), which also covers the
all-workers-lost case the reference cannot (its in-memory state dies
with the last survivor).

Worker exit-code contract (read by this driver):
  0                    job finished -> round succeeds, driver exits
  73 (RESTART_CODE)    host update acknowledged -> respawn a new round
  anything else        failure -> blacklist the worker's host, new round
"""

from __future__ import annotations

import os
import secrets as pysecrets
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import events, faults, metrics
from ..elastic.discovery import HostManager
from ..utils import env as hvd_env
from ..utils.logging import get_logger
from ..utils.retry import RetryPolicy
from . import controller_py, exec_utils
from . import hosts as hosts_mod
from .launch import free_port, make_worker_env

RESTART_CODE = 73
# A worker resharded AWAY by an in-process remesh exits with this code
# (elastic/remesh.py REMESH_SHED_CODE): a clean departure — its state
# was handed off through the KV store — not a failure, so its host is
# NOT blacklisted and the round keeps running with the survivors.
REMESH_SHED_CODE = 75

DISCOVERY_PERIOD_S = 1.0  # reference driver.py:30

# HTTP /metrics + /health endpoint (runner/telemetry_http.py): set
# HVD_TPU_TELEMETRY_PORT to enable (0 = OS-assigned port); unset
# disables.  Workers feed it by pushing metric snapshots through the
# rendezvous KV (__metrics__/rank_<r>, elastic_worker.py heartbeat).
TELEMETRY_PORT = "TELEMETRY_PORT"

# Health-monitor knobs (HVD_TPU_/HOROVOD_ prefixed via utils.env):
# a worker that registered a heartbeat and then went silent this long
# (while its process is still alive) is declared HUNG — terminated and
# blacklisted like a crash, but counted separately.  0 disables.
ELASTIC_HANG_TIMEOUT = "ELASTIC_HANG_TIMEOUT"
DEFAULT_HANG_TIMEOUT_S = 30.0
# Watchdog bound on one round's wall clock; 0 (default) disables.
ELASTIC_ROUND_TIMEOUT = "ELASTIC_ROUND_TIMEOUT"
# Transient worker-spawn failures (ssh flake, agent staleness) retry
# this many times before the host is blamed.
SPAWN_RETRIES = "SPAWN_RETRIES"
# In-process remesh (HVD_TPU_ELASTIC_REMESH=1): on a membership change
# the driver pauses survivors at a step boundary and coordinates a live
# state reshard (elastic/remesh.py) instead of a tear-down + restore
# round.  Off by default — the respawn path is validated on every
# backend; remesh is the opt-in fast path, and ANY remesh failure
# degrades to the respawn round automatically.
ELASTIC_REMESH = "ELASTIC_REMESH"
# Per-phase wall-clock bound on a remesh attempt (ack/exchange/reinit
# waits); past it the driver aborts the attempt and falls back.
REMESH_TIMEOUT = "REMESH_TIMEOUT"
DEFAULT_REMESH_TIMEOUT_S = 60.0


def _with_compilation_cache(extra_env):
    """Default a job-scoped persistent XLA compilation cache into the
    worker env (recompilation dominates respawn-per-round restart cost
    on TPU; measured in tests/integration/test_elastic.py::
    test_elastic_restart_cost_bounded).

    Precedence: HVD_TPU_NO_COMPILATION_CACHE=1 disables; an explicit
    extra_env dir wins; a driver-environment dir is COPIED into the
    worker env (remote ssh workers never inherit the driver
    environment); otherwise a fresh temp dir is created and returned
    for end-of-job cleanup.  Returns (env, created_dir_or_None).
    """
    env = dict(extra_env or {})
    if (os.environ.get("HVD_TPU_NO_COMPILATION_CACHE", "") == "1"
            or "JAX_COMPILATION_CACHE_DIR" in env):
        return env, None
    if "JAX_COMPILATION_CACHE_DIR" in os.environ:
        env["JAX_COMPILATION_CACHE_DIR"] = (
            os.environ["JAX_COMPILATION_CACHE_DIR"]
        )
        return env, None
    import tempfile

    created = tempfile.mkdtemp(prefix="hvd_tpu_xla_cache_")
    env["JAX_COMPILATION_CACHE_DIR"] = created
    return env, created


class ElasticDriver:
    def __init__(
        self,
        host_manager: HostManager,
        min_np: int,
        max_np: Optional[int] = None,
        reset_limit: Optional[int] = None,
        cooldown_s: float = 0.5,
        hang_timeout_s: Optional[float] = None,
        round_timeout_s: Optional[float] = None,
        spawn_retry: Optional[RetryPolicy] = None,
        telemetry_port: Optional[int] = None,
        remesh: Optional[bool] = None,
        remesh_timeout_s: Optional[float] = None,
    ):
        self.host_manager = host_manager
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.cooldown_s = cooldown_s
        if remesh is None:
            remesh = hvd_env.get_bool(ELASTIC_REMESH, False)
        self.remesh = remesh
        if remesh_timeout_s is None:
            remesh_timeout_s = hvd_env.get_float(
                REMESH_TIMEOUT, DEFAULT_REMESH_TIMEOUT_S
            )
        self.remesh_timeout_s = remesh_timeout_s
        self._remesh_seq = 0
        # round-scoped spawn context so a mid-round remesh can spawn
        # joiners with the same transport the round's workers used
        self._round_spawn = None
        if hang_timeout_s is None:
            hang_timeout_s = hvd_env.get_float(
                ELASTIC_HANG_TIMEOUT, DEFAULT_HANG_TIMEOUT_S
            )
        self.hang_timeout_s = hang_timeout_s
        if round_timeout_s is None:
            round_timeout_s = hvd_env.get_float(ELASTIC_ROUND_TIMEOUT, 0.0)
        self.round_timeout_s = round_timeout_s
        self.spawn_retry = spawn_retry or RetryPolicy(
            max_attempts=max(1, hvd_env.get_int(SPAWN_RETRIES, 2)),
            base_delay_s=0.2,
            max_delay_s=2.0,
            name="elastic.spawn",
        )
        if telemetry_port is None:
            raw = hvd_env.get_env(TELEMETRY_PORT)
            telemetry_port = int(raw) if raw not in (None, "") else None
        self.telemetry_port = telemetry_port
        self.rounds = 0
        self._shutdown = threading.Event()
        self._membership_changed = threading.Event()
        self._discovery_thread: Optional[threading.Thread] = None
        self._telemetry = None
        # Persistent schedule store (sched/store.py): backed by
        # HVD_TPU_TUNE_DB when set, in-memory otherwise, so the
        # /schedules endpoint + KV fan-out work either way.  Created
        # here (not per round) — entries outlive rounds by design.
        self._schedule_store = None
        # round state read by the /health endpoint
        self._last_assignments: List[hosts_mod.SlotInfo] = []
        self._round_active = False
        # SLO self-healing (runner/slo.py + elastic/remediate.py):
        # built with the telemetry server when HVD_TPU_SLO_SPEC names
        # any tenant, ticked from the round watch loop, served as /slo.
        self._slo = None
        self._slo_workers_fn = None
        self._slo_enactment_fn = None

    def schedule_store(self):
        """The driver-side schedule store (lazy: first use reads
        ``HVD_TPU_TUNE_DB``)."""
        if self._schedule_store is None:
            from ..sched.store import ScheduleStore

            self._schedule_store = (
                ScheduleStore.from_env() or ScheduleStore(None)
            )
        return self._schedule_store

    # -- discovery loop (reference driver.py:181) ------------------------
    def start_discovery(self) -> None:
        def loop():
            while not self._shutdown.is_set():
                try:
                    if self.host_manager.update_available_hosts():
                        self._membership_changed.set()
                        events.emit(
                            events.DISCOVERY_CHANGE,
                            hosts=self.host_manager.current_hosts,
                        )
                except Exception as e:  # discovery script hiccup
                    get_logger().warning("host discovery failed: %s", e)
                metrics.set_gauge(
                    "elastic.available_slots",
                    self.host_manager.available_slots(),
                )
                self._shutdown.wait(DISCOVERY_PERIOD_S)

        self.host_manager.update_available_hosts()
        self._membership_changed.clear()
        self._discovery_thread = threading.Thread(target=loop, daemon=True)
        self._discovery_thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._discovery_thread:
            self._discovery_thread.join(timeout=5)

    def wait_for_available_slots(
        self, min_np: int, timeout_s: Optional[float] = None
    ) -> bool:
        """Block until the discovered world can host min_np workers
        (reference ``wait_for_available_slots``; timeout from
        ``HVD_TPU_ELASTIC_TIMEOUT`` / ``HOROVOD_ELASTIC_TIMEOUT``,
        default 600 s like reference ``ELASTIC_TIMEOUT_SECS``)."""
        if timeout_s is None:
            from ..utils import env as hvd_env

            timeout_s = hvd_env.get_float(hvd_env.ELASTIC_TIMEOUT, 600.0)
        deadline = time.monotonic() + timeout_s
        while True:
            # slots first, deadline second: a zero timeout must still
            # succeed immediately when capacity is already there
            if self.host_manager.available_slots() >= min_np:
                return True
            remaining = deadline - time.monotonic()
            if self._shutdown.is_set() or remaining <= 0:
                return False
            # shutdown-responsive sleep, clipped so fractional timeouts
            # are honored instead of overshooting by a full period
            self._shutdown.wait(min(DISCOVERY_PERIOD_S, remaining))

    def current_assignments(self) -> List[hosts_mod.SlotInfo]:
        hosts = [
            hosts_mod.HostInfo(h, s)
            for h, s in sorted(self.host_manager.current_hosts.items())
        ]
        total = sum(h.slots for h in hosts)
        np_ = min(total, self.max_np) if self.max_np else total
        if np_ < self.min_np:
            raise RuntimeError(
                f"only {total} slot(s) available, need min_np={self.min_np}"
            )
        return hosts_mod.get_host_assignments(hosts, np_, max_np=np_)

    # -- main loop -------------------------------------------------------
    def run_rounds(
        self,
        command: List[str],
        *,
        extra_env: Optional[Dict[str, str]] = None,
        ssh_port: Optional[int] = None,
        ssh_identity_file: Optional[str] = None,
        publish: Optional[Dict[tuple, bytes]] = None,
        worker_factory: Optional[Callable] = None,
        rendezvous_addr: Optional[str] = None,
        result_collector: Optional[Callable] = None,
    ) -> int:
        """Spawn worker rounds until success, failure beyond limits, or
        reset_limit exhausted.  Returns the job exit code.

        ``publish`` entries ({(scope, key): blob}) are put into the
        rendezvous KV before the first round — how function payloads
        reach workers (e.g. ``task_runner`` fetches ``__run__/func``),
        mirroring ``horovod.run``'s KV-store func delivery.

        ``worker_factory`` replaces the ssh/local exec
        (``exec_utils.WorkerProcess``) with another transport that
        spawns ``command`` on a slot's host — e.g. the Spark task-agent
        dispatch (``spark/elastic.py``).  ``rendezvous_addr`` overrides
        the NIC probe when the caller already knows the address workers
        can dial (Spark agents dialed it to register).
        ``result_collector(control, np, round_id)`` runs on success
        before the KV server closes — how ``spark.run_elastic`` fetches
        the winning round's per-rank results.
        """
        # Respawn-per-round makes recompilation the dominant restart
        # cost on TPU; a job-scoped persistent XLA compilation cache
        # turns round-2+ compiles into cache reads (measured in
        # tests/integration/test_elastic.py::test_elastic_restart_cost
        # _bounded).  Opt out with HVD_TPU_NO_COMPILATION_CACHE=1 or by
        # setting JAX_COMPILATION_CACHE_DIR yourself.
        extra_env, created_cache_dir = _with_compilation_cache(extra_env)
        secret = pysecrets.token_hex(16)
        server = controller_py.make_server(secret, self.min_np)
        control = controller_py.make_client(
            "127.0.0.1", server.port, secret, rank=-1
        )
        for (scope, key), blob in (publish or {}).items():
            control.put(scope, key, blob)
        if self.telemetry_port is not None:
            self._telemetry = self._start_telemetry(control)
        try:
            while True:
                if not self.wait_for_available_slots(self.min_np):
                    return 1
                try:
                    assignments = self.current_assignments()
                except RuntimeError as e:
                    get_logger().warning("%s", e)
                    time.sleep(DISCOVERY_PERIOD_S)
                    continue
                self.rounds += 1
                round_id = self.rounds
                metrics.inc_counter("elastic.rounds")
                metrics.set_gauge("elastic.round", round_id)
                metrics.set_gauge("elastic.workers", len(assignments))
                self._last_assignments = assignments
                self._round_active = True
                events.emit(
                    events.ROUND_START, round=round_id,
                    np=len(assignments),
                    hosts=sorted({a.hostname for a in assignments}),
                )
                self._membership_changed.clear()
                control.put("__elastic__", "round", str(round_id).encode())
                control.put("__elastic__", f"round_{round_id}_np",
                            str(len(assignments)).encode())
                self._publish_schedules(control)
                get_logger().warning(
                    "elastic round %d: %d worker(s) on %d host(s)",
                    round_id, len(assignments), assignments[-1].cross_size,
                )
                coordinator_host = (
                    "127.0.0.1"
                    if exec_utils.is_local(assignments[0].hostname)
                    else assignments[0].hostname
                )
                coordinator_addr = f"{coordinator_host}:{free_port()}"
                # The rendezvous KV runs in this driver process: remote
                # workers must dial our routable address, not loopback —
                # mutually verified via the NIC probe on multi-NIC hosts
                # (unless the caller's transport already knows it).
                round_rdv_addr = rendezvous_addr
                if round_rdv_addr is None:
                    round_rdv_addr = exec_utils.probe_routable_addr(
                        assignments, ssh_port=ssh_port,
                        ssh_identity_file=ssh_identity_file,
                    )
                make_worker = worker_factory or exec_utils.WorkerProcess
                begin = getattr(make_worker, "begin_round", None)
                if begin is not None:
                    begin(round_id)
                # Round-scoped spawn context: a mid-round remesh spawns
                # JOINER workers through the same transport/env recipe.
                self._round_spawn = {
                    "command": command,
                    "extra_env": extra_env,
                    "rdv_addr": round_rdv_addr,
                    "rdv_port": server.port,
                    "secret": secret,
                    "make_worker": make_worker,
                    "ssh_port": ssh_port,
                    "ssh_identity_file": ssh_identity_file,
                }
                workers = []
                spawn_failed_host = None
                for slot in assignments:
                    env = make_worker_env(
                        slot, coordinator_addr, round_rdv_addr, server.port,
                        secret, extra_env,
                    )
                    env["HVD_TPU_ELASTIC"] = "1"
                    env["HVD_TPU_ELASTIC_ROUND"] = str(round_id)

                    def spawn(slot=slot, env=env):
                        faults.inject(
                            "driver.spawn", host=slot.hostname,
                            rank=slot.rank, round=round_id,
                        )
                        return make_worker(
                            slot.rank, slot.hostname, command, env,
                            ssh_port=ssh_port,
                            ssh_identity_file=ssh_identity_file,
                        )

                    try:
                        # transient spawn failures (ssh flake, agent
                        # staleness) retry before the host is blamed
                        workers.append(self.spawn_retry.call(spawn))
                    except Exception as e:
                        # A host lost between assignment and spawn (e.g.
                        # a Spark executor death in the discovery
                        # staleness window) fails the ROUND, not the
                        # job: blacklist and go again.
                        get_logger().warning(
                            "worker spawn on %s failed: %s",
                            slot.hostname, e,
                        )
                        events.emit(
                            events.SPAWN_FAILED, round=round_id,
                            host=slot.hostname, worker_rank=slot.rank,
                            error=str(e),
                        )
                        spawn_failed_host = slot.hostname
                        break
                if spawn_failed_host is not None:
                    for w in workers:
                        w.terminate()
                    for w in workers:
                        w.wait()
                    self.host_manager.blacklist(spawn_failed_host)
                    if self.host_manager.available_slots() >= self.min_np:
                        time.sleep(self.cooldown_s)
                        continue
                    return 1
                rc = self._watch_round(workers, assignments, control, round_id)
                self._round_active = False
                self._collect_schedules(control)
                events.emit(
                    events.ROUND_END, round=round_id, exit_code=rc,
                    restart=(rc == RESTART_CODE),
                )
                if rc == 0:
                    if result_collector is not None:
                        result_collector(
                            control, len(assignments), round_id
                        )
                    return 0
                if rc == RESTART_CODE:
                    events.emit(events.RESTART, round=round_id)
                    if (
                        self.reset_limit is not None
                        and self.rounds > self.reset_limit
                    ):
                        get_logger().error(
                            "reset_limit %d exceeded", self.reset_limit
                        )
                        return 1
                    time.sleep(self.cooldown_s)
                    continue
                # real failure: can we keep going?
                if self.host_manager.available_slots() >= self.min_np:
                    time.sleep(self.cooldown_s)
                    continue
                return rc
        finally:
            if self._telemetry is not None:
                self._telemetry.stop()
                self._telemetry = None
            control.close()
            server.stop()
            self.stop()
            if created_cache_dir is not None:
                # job-scoped cache (a fresh dir per job): useless after
                # the job and easily GBs of XLA programs — remove it
                import shutil

                shutil.rmtree(created_cache_dir, ignore_errors=True)

    def _start_telemetry(self, control):
        """Start the HTTP /metrics + /health endpoint for this job.

        ``/metrics`` folds in the latest snapshot each worker pushed
        through the KV store; ``/health`` reports round/membership
        state.  Scrape-time only — zero cost to the driver loop."""
        import json as _json

        from .telemetry_http import TelemetryServer

        def workers_fn():
            out = []
            for slot in list(self._last_assignments):
                try:
                    raw = control.get(
                        "__metrics__", f"rank_{slot.rank}", timeout_ms=0
                    )
                except Exception:
                    raw = None
                if raw:
                    try:
                        out.append((slot.rank, _json.loads(raw)))
                    except ValueError:
                        pass
            return out

        def health_fn():
            slots = self.host_manager.available_slots()
            return {
                "status": "ok" if slots >= self.min_np else "degraded",
                "round": self.rounds,
                "round_active": self._round_active,
                "workers": len(self._last_assignments),
                "min_np": self.min_np,
                "max_np": self.max_np,
                "available_slots": slots,
                "current_hosts": self.host_manager.current_hosts,
            }

        def trace_fn():
            # Cross-rank straggler detection (trace/straggler.py) over
            # the per-rank phase summaries the workers' heartbeats
            # already push: one pass per scrape, verdicts published as
            # trace.straggler{rank=,phase=} gauges AND returned as the
            # /trace body, with round context so an operator can line
            # the summary up against /health.
            from ..trace import straggler

            per_rank = {rank: snap for rank, snap in workers_fn()}
            payload = straggler.trace_payload(per_rank)
            payload["round"] = self.rounds
            payload["workers"] = len(self._last_assignments)
            return payload

        def tenants_fn():
            # Per-tenant accounting for the multi-tenant arbiter
            # (svc/arbiter.py, docs/multitenant.md): queue depth, rail
            # bytes, and wait quantiles per tenant aggregated from the
            # same per-rank KV pushes, with round context so share
            # shifts can be lined up against membership changes.
            from ..svc.arbiter import tenants_payload

            per_rank = {rank: snap for rank, snap in workers_fn()}
            payload = tenants_payload(per_rank)
            payload["round"] = self.rounds
            payload["workers"] = len(self._last_assignments)
            return payload

        def prof_fn():
            # GET /prof: the device-time profiling plane (prof/,
            # docs/observability.md) — per-rank host-gap / MFU /
            # regression digests from the same KV pushes, with round
            # context like /trace and /tenants.
            from .. import prof

            per_rank = {rank: snap for rank, snap in workers_fn()}
            payload = prof.prof_payload(per_rank)
            payload["round"] = self.rounds
            payload["workers"] = len(self._last_assignments)
            return payload

        self._slo = self._build_slo(control)
        self._slo_workers_fn = workers_fn
        slo_fn = None
        if self._slo is not None:
            controller = self._slo

            def slo_fn():
                # GET /slo: the watchdog's last window + remediation
                # history, with round context like /trace and /tenants
                # — plus per-action worker ack counts, so a handoff
                # that no worker enacted is visible as such.
                payload = controller.payload()
                payload["round"] = self.rounds
                payload["workers"] = len(self._last_assignments)
                enact = getattr(self, "_slo_enactment_fn", None)
                if enact is not None:
                    try:
                        payload["enactment"] = enact()
                    except Exception:  # pragma: no cover - defensive
                        pass
                return payload

        from .telemetry_http import probe_payload

        return TelemetryServer(
            port=self.telemetry_port, health_fn=health_fn,
            workers_fn=workers_fn, schedule_store=self.schedule_store(),
            trace_fn=trace_fn, tenants_fn=tenants_fn, slo_fn=slo_fn,
            prof_fn=prof_fn, probe_fn=probe_payload,
        )

    def _build_slo(self, control):
        """Build the SLO controller (watchdog + remediation ladder)
        when ``HVD_TPU_SLO_SPEC`` names any tenant; None otherwise.

        The driver's actuators publish every rung on the KV store
        (``__slo__/preempt|degrade|placement``, seq-stamped) and the
        workers' heartbeat threads consume and enact them in-process
        (``runner/slo_consumer.py``): preempt gates the worker's
        arbiter lanes, degrade applies the knob flip there, and a
        placement handoff shifts the arbiter's tenant weights (rail
        shares follow slices at the next scheduling cycle) and reaches
        registered states at their next commit boundary through
        ``on_placement_updated`` — no restarts.  Rollback republishes
        the old placement, and the degrade revert published by
        :meth:`~horovod_tpu.elastic.remediate.Remediator.reset` on SLO
        recovery rides the same degrade channel.  Each worker acks
        what it enacted; ``GET /slo`` folds the ack counts in
        (``enactment``), so the history reports what workers DID, not
        just what the driver said.
        """
        import itertools
        import json as _json

        from ..elastic import remediate
        from . import slo as slo_mod
        from .slo_consumer import ack_key

        seq_counter = itertools.count(1)
        published: Dict[str, int] = {}

        def publish(key: str, payload: Dict) -> None:
            # Advisory channel: a KV hiccup must fail the RUNG (so its
            # RetryPolicy retries), not the driver loop — hence raise.
            seq = next(seq_counter)
            payload = dict(payload, seq=seq)
            control.put("__slo__", key, _json.dumps(payload).encode())
            published[key] = seq

        def preempt(tenant, breach):
            from ..svc import service as service_mod

            svc = service_mod.get_service_or_none()
            if svc is not None:
                svc.arbiter.request_preempt(tenant)
            publish("preempt", {"tenant": tenant,
                                "kind": breach.get("kind")})

        def degrade(tenant, breach):
            changes = remediate._default_degrade(tenant, breach)
            publish("degrade", {"tenant": tenant, "changes": changes})
            return changes

        def undegrade(tenant, restored):
            # Remediator.reset() reverting degraded mode after SLO
            # recovery: workers un-apply through the same channel
            # (null value = unset the knob).
            publish("degrade", {"tenant": tenant, "changes": restored,
                                "revert": True})

        def handoff(old_placement, new_placement, breach):
            publish("placement", {
                "placement": new_placement,
                "tenant": breach.get("tenant"),
                "previous": old_placement,
            })

        def rollback(old_placement, new_placement, breach):
            publish("placement", {
                "placement": old_placement,
                "tenant": breach.get("tenant"),
                "rollback": True,
            })

        def enactment() -> Dict:
            # Which ranks acked the latest publication of each action —
            # the /slo proof that a remediation was enacted, not merely
            # announced.  Non-blocking KV reads, scrape-time only.
            out: Dict[str, Dict] = {}
            for key, seq in published.items():
                acked = []
                for slot in list(self._last_assignments):
                    try:
                        if control.get(
                            "__slo__", ack_key(key, seq, slot.rank),
                            timeout_ms=0,
                        ) is not None:
                            acked.append(slot.rank)
                    except Exception:
                        pass
                out[key] = {
                    "seq": seq,
                    "acked_ranks": sorted(acked),
                    "workers": len(self._last_assignments),
                }
            return out

        self._slo_enactment_fn = enactment
        remediator = remediate.Remediator(actuators={
            "preempt": preempt, "degrade": degrade,
            "undegrade": undegrade,
            "handoff": handoff, "rollback": rollback,
        })
        return slo_mod.SLOController.from_env(remediator)

    def _publish_schedules(self, control) -> None:
        """Seed the round's workers with the schedule DB: the store's
        entries ride the rendezvous KV (``__schedules__/db``) so a
        worker can warm-start its ``ScheduleTuner`` before its first
        window (``elastic_worker.py`` fetches at startup).  Fleet
        serving's in-job half — the HTTP ``/schedules`` endpoint covers
        cross-job."""
        import json as _json

        try:
            entries = self.schedule_store().entries()
            control.put(
                "__schedules__", "db",
                _json.dumps({"entries": entries}).encode(),
            )
        except Exception as e:  # advisory channel: never fail a round
            get_logger().warning("schedule publish failed: %s", e)

    def _collect_schedules(self, control) -> None:
        """Fold worker-pushed schedule entries (``__schedules__/
        rank_<r>``, pushed by the heartbeat thread when the worker's
        local DB changes) into the driver store — one tuned worker
        seeds every later identical job."""
        import json as _json

        merged = 0
        for slot in list(self._last_assignments):
            try:
                raw = control.get(
                    "__schedules__", f"rank_{slot.rank}", timeout_ms=0
                )
            except Exception:
                raw = None
            if not raw:
                continue
            try:
                merged += self.schedule_store().merge(
                    _json.loads(raw).get("entries", {})
                )
            except Exception as e:
                get_logger().warning(
                    "bad schedule push from rank %s: %s", slot.rank, e
                )
        if merged:
            metrics.inc_counter("sched.tune.db_collected", merged)

    def _watch_round(
        self,
        workers: List[exec_utils.WorkerProcess],
        assignments: List[hosts_mod.SlotInfo],
        control,
        round_id: int,
    ) -> int:
        """Wait for the round to end.  Membership change -> signal workers
        (they exit RESTART_CODE at the next commit); failure -> blacklist
        and terminate; success of all -> 0.

        Health monitoring: workers that run ``hvd.elastic.run`` publish
        heartbeats into the KV store (``__elastic__/hb_<round>_<rank>``,
        elastic_worker.py).  A worker whose process is alive but whose
        heartbeat stopped advancing for ``hang_timeout_s`` is declared
        HUNG — without this, a wedged worker (deadlocked collective,
        stuck I/O) stalls the job forever, indistinguishable from slow
        progress.  Crash and hang are counted separately
        (``elastic.worker_crash`` / ``elastic.worker_hang``) because
        they point at different root causes.  Workers that never
        heartbeat (plain scripts) are exempt from hang detection.
        ``round_timeout_s`` additionally bounds the whole round.
        """
        pending = set(range(len(workers)))
        saw_failure = 0
        t_round_start = time.monotonic()
        # rank -> (last heartbeat payload, monotonic time it changed)
        hb_seen: Dict[int, tuple] = {}
        last_hb_check = t_round_start

        def _fail_worker(i: int, why: str) -> None:
            nonlocal saw_failure, pending
            metrics.inc_counter(f"elastic.worker_{why}")
            events.emit(
                events.WORKER_CRASH if why == "crash" else events.WORKER_HANG,
                round=round_id, worker_rank=assignments[i].rank,
                host=assignments[i].hostname, verdict=why,
            )
            self.host_manager.blacklist(assignments[i].hostname)
            # a dead peer wedges collectives: end the round
            for j in pending:
                workers[j].terminate()
            for j in pending:
                workers[j].wait()
            pending = set()

        while pending:
            if self._membership_changed.is_set():
                self._membership_changed.clear()
                remeshed = None
                if self.remesh:
                    remeshed = self._try_remesh(
                        workers, assignments, control, round_id
                    )
                if remeshed is not None:
                    # Live reshard succeeded: the round continues with
                    # the NEW worker set — no respawn, no checkpoint
                    # restore on the hot path.
                    workers, assignments = remeshed
                    pending = set(range(len(workers)))
                    hb_seen.clear()
                    metrics.set_gauge("elastic.workers", len(workers))
                    self._last_assignments = assignments
                else:
                    control.put(
                        "__elastic__", f"hosts_updated_{round_id}", b"1"
                    )
            for i in sorted(pending):
                rc = workers[i].returncode
                if rc is None:
                    continue
                pending.discard(i)
                if rc == 0:
                    continue
                if rc == REMESH_SHED_CODE:
                    # resharded away by a remesh: clean departure, the
                    # host stays in rotation
                    continue
                if rc == RESTART_CODE:
                    # graceful restart request: drain the others too
                    control.put(
                        "__elastic__", f"hosts_updated_{round_id}", b"1"
                    )
                    saw_failure = saw_failure or RESTART_CODE
                    continue
                get_logger().warning(
                    "worker %d on %s crashed (exit %d)",
                    assignments[i].rank, assignments[i].hostname, rc,
                )
                saw_failure = rc
                _fail_worker(i, "crash")
                break
            now = time.monotonic()
            if pending and self.hang_timeout_s > 0 and (
                now - last_hb_check >= 1.0
            ):
                last_hb_check = now
                hung = self._find_hung_worker(
                    pending, assignments, control, round_id, hb_seen
                )
                if hung is not None:
                    get_logger().error(
                        "worker %d on %s is HUNG (no heartbeat for "
                        "%.1fs, process alive) — terminating",
                        assignments[hung].rank,
                        assignments[hung].hostname, self.hang_timeout_s,
                    )
                    pending.discard(hung)
                    workers[hung].terminate()
                    workers[hung].wait()
                    saw_failure = saw_failure or 1
                    _fail_worker(hung, "hang")
            if pending and self.round_timeout_s > 0 and (
                time.monotonic() - t_round_start > self.round_timeout_s
            ):
                get_logger().error(
                    "round %d exceeded watchdog timeout %.1fs; "
                    "restarting", round_id, self.round_timeout_s,
                )
                metrics.inc_counter("elastic.round_timeout")
                events.emit(
                    events.WATCHDOG_TIMEOUT, round=round_id,
                    timeout_s=self.round_timeout_s,
                )
                for j in pending:
                    workers[j].terminate()
                for j in pending:
                    workers[j].wait()
                pending = set()
                saw_failure = saw_failure or RESTART_CODE
            if self._slo is not None and self._slo_workers_fn is not None:
                # SLO watchdog tick (runner/slo.py): rate-limited to
                # HVD_TPU_SLO_CHECK_INTERVAL internally and never
                # raises — a breach remediates, it never ends a round.
                self._slo.maybe_tick(lambda: {
                    r: snap for r, snap in self._slo_workers_fn()
                })
            time.sleep(0.1)
        for w in workers:
            w.wait()
        if saw_failure == RESTART_CODE:
            return RESTART_CODE
        if saw_failure:
            return RESTART_CODE if self.host_manager.available_slots() >= self.min_np else saw_failure
        return 0

    # -- in-process remesh coordination (elastic/remesh.py) --------------
    def _await_remesh_keys(self, control, keys, deadline: float,
                           workers=None) -> bool:
        """Poll the KV store until every key in ``keys`` exists or the
        deadline passes.  With ``workers``, a worker death while
        waiting fails the attempt immediately (a dead peer can never
        ack)."""
        remaining = set(keys)
        while remaining:
            for key in list(remaining):
                try:
                    if control.get("__remesh__", key,
                                   timeout_ms=0) is not None:
                        remaining.discard(key)
                except Exception:
                    pass
            if not remaining:
                return True
            if workers is not None and any(
                w.returncode not in (None, 0, REMESH_SHED_CODE)
                for w in workers
            ):
                get_logger().warning(
                    "remesh: a worker died while waiting for %s",
                    sorted(remaining),
                )
                return False
            if time.monotonic() > deadline:
                get_logger().warning(
                    "remesh: timed out waiting for %s", sorted(remaining)
                )
                return False
            time.sleep(0.05)
        return True

    def _plan_remesh_world(self, workers, assignments, new_np: int,
                           new_hosts):
        """Old world -> new world placement: survivors keep their host
        (new ranks assigned in old-rank order), shed workers are those
        on removed hosts or beyond the new size, joiner slots fill the
        remaining capacity.  Returns (survivors {old->new}, shed old
        ranks, joiner SlotInfos, full new SlotInfo list by new rank)."""
        capacity = dict(new_hosts)
        keep: List[int] = []  # old ranks surviving, in old-rank order
        shed: List[int] = []
        for slot in assignments:
            if len(keep) < new_np and capacity.get(slot.hostname, 0) > 0:
                capacity[slot.hostname] -= 1
                keep.append(slot.rank)
            else:
                shed.append(slot.rank)
        survivors = {old: new for new, old in enumerate(keep)}
        host_of: Dict[int, str] = {}
        by_old = {s.rank: s for s in assignments}
        for old, new in survivors.items():
            host_of[new] = by_old[old].hostname
        joiner_ranks = list(range(len(keep), new_np))
        for nr in joiner_ranks:
            for h in sorted(capacity):
                if capacity[h] > 0:
                    capacity[h] -= 1
                    host_of[nr] = h
                    break
            else:
                return None  # capacity accounting failed
        # per-host local/cross numbering over the final placement
        hosts_in_order: List[str] = []
        for nr in range(new_np):
            if host_of[nr] not in hosts_in_order:
                hosts_in_order.append(host_of[nr])
        local_index: Dict[str, int] = {h: 0 for h in hosts_in_order}
        slots: List[hosts_mod.SlotInfo] = []
        per_host = {
            h: list(host_of.values()).count(h) for h in hosts_in_order
        }
        for nr in range(new_np):
            h = host_of[nr]
            slots.append(hosts_mod.SlotInfo(
                hostname=h, rank=nr,
                local_rank=local_index[h],
                cross_rank=hosts_in_order.index(h),
                size=new_np,
                local_size=per_host[h],
                cross_size=len(hosts_in_order),
            ))
            local_index[h] += 1
        joiners = [slots[nr] for nr in joiner_ranks]
        return survivors, shed, joiners, slots

    def _try_remesh(self, workers, assignments, control, round_id):
        """Attempt a zero-downtime in-process remesh for the current
        membership change.  Returns ``(workers, assignments)`` for the
        new world on success; ``None`` falls back to the respawn-round
        path (the caller then publishes the restart signal).  Every
        failure mode is bounded by ``remesh_timeout_s`` and ends in
        either success or a clean fallback — never a wedged round."""
        from ..elastic.remesh import RemeshRequest

        try:
            new_assignments = self.current_assignments()
        except RuntimeError as e:
            get_logger().warning("remesh: %s", e)
            return None
        np_old, np_new = len(assignments), len(new_assignments)
        live = [w for w in workers if w.returncode is None]
        if len(live) != np_old:
            # someone already died: that is the crash path's job
            return None
        new_hosts: Dict[str, int] = {}
        for a in new_assignments:
            new_hosts[a.hostname] = new_hosts.get(a.hostname, 0) + 1
        if np_new == np_old and all(
            new_hosts.get(s.hostname, 0) > 0 for s in assignments
        ):
            return None  # not a resize; nothing to reshard
        planned = self._plan_remesh_world(
            workers, assignments, np_new, new_hosts
        )
        if planned is None:
            return None
        survivors, shed, joiners, new_slots = planned
        if not survivors:
            return None  # no survivor to carry state: full restart
        metrics.inc_counter("remesh.driver_attempts")
        self._remesh_seq += 1
        rid = self._remesh_seq
        coord_host = (
            "127.0.0.1"
            if exec_utils.is_local(new_slots[0].hostname)
            else new_slots[0].hostname
        )
        request = RemeshRequest(
            remesh_id=rid, round_id=round_id,
            np_old=np_old, np_new=np_new,
            coordinator_addr=f"{coord_host}:{free_port()}",
            survivors=survivors,
            deadline_s=self.remesh_timeout_s,
        )
        events.emit(
            events.REMESH_START, remesh_id=rid, round=round_id,
            np_old=np_old, np_new=np_new,
            survivors=sorted(survivors), shed=sorted(shed),
            joiners=[s.rank for s in joiners],
        )
        get_logger().warning(
            "remesh %d: %d -> %d worker(s) (%d survivor(s), %d shed, "
            "%d joining) — resharding in place",
            rid, np_old, np_new, len(survivors), len(shed), len(joiners),
        )
        control.put("__remesh__", f"begin_{round_id}",
                    request.to_json().encode())
        deadline = time.monotonic() + self.remesh_timeout_s
        old_ranks = sorted(s.rank for s in assignments)
        joiner_procs = []

        def fallback(why: str):
            metrics.inc_counter("remesh.driver_fallback")
            events.emit(
                events.REMESH_FALLBACK, remesh_id=rid, round=round_id,
                error=why,
            )
            get_logger().warning(
                "remesh %d failed (%s); falling back to the respawn "
                "round", rid, why,
            )
            try:
                control.put("__remesh__", f"abort_{rid}", b"1")
            except Exception:
                pass
            for p in joiner_procs:
                p.terminate()
            for p in joiner_procs:
                p.wait()
            return None

        # Phase 1+2: every live old rank pauses at a step boundary and
        # publishes its shards (pause acks piggyback on the heartbeat
        # KV channel).
        if not self._await_remesh_keys(
            control, [f"pause_{rid}_{r}" for r in old_ranks],
            deadline, workers,
        ):
            return fallback("pause ack timeout")
        if not self._await_remesh_keys(
            control, [f"snapshot_{rid}_{r}" for r in old_ranks],
            deadline, workers,
        ):
            return fallback("snapshot ack timeout")

        # Phase 3: spawn joiners into the NEW world, then authorize the
        # exchange.  Joiners rendezvous on the new coordinator with the
        # reinit-ing survivors.
        ctx = self._round_spawn or {}
        make_worker = ctx.get("make_worker", exec_utils.WorkerProcess)
        for slot in joiners:
            env = make_worker_env(
                slot, request.coordinator_addr, ctx.get("rdv_addr"),
                ctx.get("rdv_port"), ctx.get("secret"),
                ctx.get("extra_env"),
            )
            env["HVD_TPU_ELASTIC"] = "1"
            env["HVD_TPU_ELASTIC_ROUND"] = str(round_id)
            env["HVD_TPU_REMESH_JOIN"] = str(rid)
            try:
                joiner_procs.append(self.spawn_retry.call(
                    lambda slot=slot, env=env: make_worker(
                        slot.rank, slot.hostname, ctx.get("command"),
                        env, ssh_port=ctx.get("ssh_port"),
                        ssh_identity_file=ctx.get("ssh_identity_file"),
                    )
                ))
            except Exception as e:
                return fallback(f"joiner spawn on {slot.hostname}: {e}")
        control.put("__remesh__", f"go_{rid}", b"1")

        # Phase 4: survivors reinit + fetch, joiners fetch; shed ranks
        # leave.  Done acks are keyed by NEW ranks.
        new_ranks = list(range(np_new))
        if not self._await_remesh_keys(
            control,
            [f"done_{rid}_{r}" for r in new_ranks]
            + [f"shed_{rid}_{r}" for r in shed],
            deadline + self.remesh_timeout_s,  # reinit is the long pole
            list(live) + joiner_procs,
        ):
            return fallback("exchange/reinit timeout")

        # Reap shed workers (clean exits, hosts stay in rotation).
        by_old = {s.rank: i for i, s in enumerate(assignments)}
        survivor_procs = {}
        for old, new in survivors.items():
            survivor_procs[new] = workers[by_old[old]]
        for r in shed:
            workers[by_old[r]].wait()
        new_workers = [
            survivor_procs[nr] if nr in survivor_procs
            else joiner_procs[[s.rank for s in joiners].index(nr)]
            for nr in range(np_new)
        ]
        metrics.inc_counter("remesh.driver_success")
        metrics.set_gauge("elastic.remesh", rid)
        events.emit(
            events.REMESH_OK, remesh_id=rid, round=round_id, np=np_new,
        )
        get_logger().warning(
            "remesh %d complete: round %d continues with %d worker(s)",
            rid, round_id, np_new,
        )
        return new_workers, new_slots

    def _find_hung_worker(
        self,
        pending,
        assignments: List[hosts_mod.SlotInfo],
        control,
        round_id: int,
        hb_seen: Dict[int, tuple],
    ) -> Optional[int]:
        """First pending worker whose heartbeat registered and then went
        silent past ``hang_timeout_s``; updates ``hb_seen`` in place."""
        now = time.monotonic()
        for i in sorted(pending):
            rank = assignments[i].rank
            try:
                val = control.get(
                    "__elastic__", f"hb_{round_id}_{rank}", timeout_ms=0
                )
            except Exception:
                val = None
            if val is None:
                continue  # never heartbeat: plain script, exempt
            prev = hb_seen.get(rank)
            if prev is None or prev[0] != val:
                hb_seen[rank] = (val, now)
                continue
            if now - prev[1] > self.hang_timeout_s:
                return i
        return None
