"""Worker-side entry for ``horovod_tpu.runner.run()``.

Reference analog: ``horovod/runner/task_fn.py`` + the run-func wrapper —
each worker fetches the pickled function from the launcher's KV store,
executes it with the runtime initialized, and publishes its result.
"""

from __future__ import annotations

import os
import pickle
import sys
import traceback


def main() -> int:
    # Env set before any jax import: CPU forcing for integration tests.
    if os.environ.get("HVD_TPU_FORCE_CPU") == "1":
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=1"
        )
    rank = int(os.environ["HVD_TPU_CROSS_RANK"])
    addr = os.environ["HVD_TPU_RENDEZVOUS_ADDR"]
    port = int(os.environ["HVD_TPU_RENDEZVOUS_PORT"])
    secret = os.environ["HVD_TPU_SECRET"]

    from . import controller_py

    client = controller_py.make_client(addr, port, secret, rank)
    # Elastic rounds scope the key by round id: an orphaned worker from
    # a dead round must never collide with the succeeding round's
    # results.
    rnd = os.environ.get("HVD_TPU_ELASTIC_ROUND")
    result_key = f"r{rnd}:{rank}" if rnd else str(rank)
    try:
        blob = client.get("__run__", "func", timeout_ms=30_000)
        if blob is None:
            raise RuntimeError("no function published by launcher")
        import cloudpickle

        func, args, kwargs = cloudpickle.loads(blob)
        if os.environ.get("HVD_TPU_FORCE_CPU") == "1":
            import jax

            jax.config.update("jax_platforms", "cpu")
        result = func(*args, **kwargs)
        client.put("__results__", result_key, pickle.dumps(("ok", result)))
        return 0
    except Exception:
        err = traceback.format_exc()
        try:
            client.put("__results__", result_key,
                       pickle.dumps(("error", err)))
        except Exception:
            pass
        sys.stderr.write(err)
        return 1
    finally:
        client.close()


if __name__ == "__main__":
    sys.exit(main())
