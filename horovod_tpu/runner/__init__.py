"""Launcher package (reference ``horovod/runner/``).

``hvdrun`` CLI: ``python -m horovod_tpu.runner -np 4 python train.py``.
Programmatic API: ``horovod_tpu.runner.run(func, np=4)`` pickles ``func``,
executes it on every worker, and returns the per-rank results (reference
``horovod.run()``, ``horovod/runner/__init__.py:92``, which ships results
through the launcher's KV store the same way).
"""

from __future__ import annotations

import pickle
import secrets as pysecrets
import socket
import sys
import time
from typing import Any, Callable, Dict, List, Optional

from . import controller_py, exec_utils, hosts as hosts_mod
from .launch import free_port, launch_static, make_worker_env, run_commandline  # noqa: F401


def run(
    func: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    *,
    np: int = 1,
    hosts: Optional[str] = None,
    use_cpu_devices: bool = False,
    extra_env: Optional[Dict[str, str]] = None,
    verbose: bool = False,
) -> List[Any]:
    """Run ``func(*args, **kwargs)`` on ``np`` workers; returns the list
    of per-rank return values (rank order).

    ``use_cpu_devices=True`` forces workers onto the CPU backend (used by
    the integration tests, mirroring the reference's localhost gloo runs).
    """
    host_list = (
        hosts_mod.parse_hosts(hosts) if hosts else [hosts_mod.HostInfo("localhost", np)]
    )
    assignments = hosts_mod.get_host_assignments(host_list, np)
    secret = pysecrets.token_hex(16)
    server = controller_py.make_server(secret, np)
    rendezvous_addr = "127.0.0.1" if all(
        exec_utils.is_local(a.hostname) for a in assignments
    ) else socket.gethostbyname(socket.gethostname())
    coordinator_host = (
        "127.0.0.1" if exec_utils.is_local(assignments[0].hostname)
        else assignments[0].hostname
    )
    coordinator_addr = f"{coordinator_host}:{free_port()}"

    # Publish the pickled function for the task runners (reference
    # horovod.run puts the pickled func in the KV store).
    publisher = controller_py.make_client(
        "127.0.0.1", server.port, secret, rank=-1
    )
    # cloudpickle ships closures/lambdas like the reference's run API
    import cloudpickle

    publisher.put(
        "__run__", "func", cloudpickle.dumps((func, args, kwargs or {}))
    )

    env_extra = dict(extra_env or {})
    if use_cpu_devices:
        env_extra.update({
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "HVD_TPU_FORCE_CPU": "1",
            # override any inherited forced device count (e.g. pytest's)
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
    workers = []
    try:
        for slot in assignments:
            env = make_worker_env(
                slot, coordinator_addr, rendezvous_addr, server.port, secret,
                env_extra,
            )
            workers.append(
                exec_utils.WorkerProcess(
                    slot.rank, slot.hostname,
                    [sys.executable, "-m", "horovod_tpu.runner.task_runner"],
                    env, prefix_output=verbose,
                )
            )
        for w in workers:
            rc = w.wait()
            if rc != 0:
                raise RuntimeError(
                    f"worker rank {w.rank} exited with code {rc}"
                )
        results = []
        for r in range(np):
            blob = publisher.get("__results__", str(r), timeout_ms=10_000)
            if blob is None:
                raise RuntimeError(f"no result from rank {r}")
            status, payload = pickle.loads(blob)
            if status == "error":
                raise RuntimeError(f"rank {r} failed: {payload}")
            results.append(payload)
        return results
    finally:
        for w in workers:
            w.terminate()
        publisher.close()
        server.stop()
