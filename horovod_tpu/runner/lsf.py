"""LSF cluster detection and ``jsrun`` launch.

Reference: ``horovod/runner/util/lsf.py`` (LSFUtils — cluster detection
+ host/core/gpu discovery via IBM CSM) and ``horovod/runner/js_run.py``
(jsrun command + ERF rankfile construction).

TPU re-design: the reference resolves its allocation through the CSM
daemons found on Summit-class machines and binds one process per GPU;
here the allocation is read straight from the standard LSF job env
(``LSB_DJOB_HOSTFILE`` / ``LSB_MCPU_HOSTS`` / ``LSB_HOSTS`` — present
under every LSF, CSM or not), and a "slot" is a worker process (one per
host by default, owning that host's chips — same convention as
:mod:`horovod_tpu.runner.hosts`).  ``jsrun`` remains only a *process
launcher*: the data plane is XLA, so the jsrun command wraps each
worker in the :mod:`horovod_tpu.runner.mpi_worker` shim, which
translates the PMIx rank env jsrun provides into this framework's
worker env contract.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from shlex import quote
from typing import Dict, List, Optional

from . import hosts as hosts_mod


def using_lsf(environ=None) -> bool:
    """True when running inside an LSF job allocation (reference
    ``LSFUtils.using_lsf``: presence of ``LSB_JOBID``)."""
    e = environ if environ is not None else os.environ
    return "LSB_JOBID" in e


def _hosts_from_djob_hostfile(path: str) -> Dict[str, int]:
    """``LSB_DJOB_HOSTFILE`` lists one hostname per allocated slot
    (repeated per core); collapse repeats into host -> slot count."""
    counts: Dict[str, int] = {}
    with open(path) as fh:
        for line in fh:
            host = line.strip()
            if host:
                counts[host] = counts.get(host, 0) + 1
    return counts


def _hosts_from_mcpu(spec: str) -> Dict[str, int]:
    """``LSB_MCPU_HOSTS`` is ``"host1 n1 host2 n2 ..."``."""
    toks = spec.split()
    if len(toks) % 2:
        raise ValueError(f"malformed LSB_MCPU_HOSTS: {spec!r}")
    counts: Dict[str, int] = {}
    for host, n in zip(toks[0::2], toks[1::2]):
        counts[host] = counts.get(host, 0) + int(n)
    return counts


def _hosts_from_lsb_hosts(spec: str) -> Dict[str, int]:
    """``LSB_HOSTS`` repeats each hostname once per slot."""
    counts: Dict[str, int] = {}
    for host in spec.split():
        counts[host] = counts.get(host, 0) + 1
    return counts


def get_allocated_hosts(environ=None) -> Dict[str, int]:
    """Ordered ``{host: cores}`` for the current LSF allocation.

    Precedence mirrors LSF's own documentation: the job hostfile is
    authoritative, ``LSB_MCPU_HOSTS`` is its compact form, and
    ``LSB_HOSTS`` (which caps at a few thousand chars) is the fallback.
    The first host listed is the launch host, as LSF guarantees.
    """
    e = environ if environ is not None else os.environ
    path = e.get("LSB_DJOB_HOSTFILE")
    if path and os.path.exists(path):
        return _hosts_from_djob_hostfile(path)
    if e.get("LSB_MCPU_HOSTS"):
        return _hosts_from_mcpu(e["LSB_MCPU_HOSTS"])
    if e.get("LSB_HOSTS"):
        return _hosts_from_lsb_hosts(e["LSB_HOSTS"])
    raise RuntimeError(
        "inside an LSF job (LSB_JOBID set) but none of LSB_DJOB_HOSTFILE/"
        "LSB_MCPU_HOSTS/LSB_HOSTS describe the allocation"
    )


def get_compute_hosts(environ=None) -> List[str]:
    """Compute hostnames in allocation order (reference
    ``LSFUtils.get_compute_hosts`` — which queries CSM for the compute
    node list, implicitly excluding Summit-style launch nodes).

    Without CSM the launch node is recognized by its signature: the
    FIRST listed host (LSF guarantees that is the launch host) holding
    exactly one slot while every other host holds more.  Such a host
    cannot run jsrun tasks and owns no chips, so it is dropped.  Set
    ``HVD_TPU_LSF_INCLUDE_LAUNCH_HOST=1`` to keep it (e.g. single-host
    or genuinely heterogeneous allocations are never dropped anyway).
    """
    e = environ if environ is not None else os.environ
    counts = get_allocated_hosts(environ)
    hosts = list(counts)
    if (len(hosts) >= 2
            and counts[hosts[0]] == 1
            and all(counts[h] > 1 for h in hosts[1:])
            and e.get("HVD_TPU_LSF_INCLUDE_LAUNCH_HOST", "") != "1"):
        # A genuinely heterogeneous allocation with a 1-core compute
        # host matches this signature too — say what was dropped so a
        # misclassification is diagnosable, and name the override.
        from ..utils.logging import get_logger

        get_logger().warning(
            "LSF: dropping first allocated host %s (1 slot while all "
            "others have more — launch-node signature). If it is a real "
            "compute host, set HVD_TPU_LSF_INCLUDE_LAUNCH_HOST=1.",
            hosts[0],
        )
        return hosts[1:]
    return hosts


def get_num_cores(environ=None) -> int:
    """Cores allocated on the first compute host (reference
    ``LSFUtils.get_num_cores``)."""
    counts = get_allocated_hosts(environ)
    return counts[get_compute_hosts(environ)[0]]


def lsf_host_list(
    environ=None, np_: Optional[int] = None
) -> List[hosts_mod.HostInfo]:
    """The allocation as launcher ``HostInfo`` records.

    Default is one worker process per host (the TPU convention — one
    process owns all chips on a host), not one per core as the
    reference's GPU binding would.  When an explicit ``np_`` exceeds
    the host count, slots grow evenly (``spread_workers``) so
    ``get_host_assignments`` can place every requested worker.
    """
    hosts = get_compute_hosts(environ)
    if np_ is not None and np_ > len(hosts):
        slots = spread_workers(np_, hosts)
        return [hosts_mod.HostInfo(h, s) for h, s in slots.items()]
    return [hosts_mod.HostInfo(h, 1) for h in hosts]


# ---------------------------------------------------------------------------
# jsrun
# ---------------------------------------------------------------------------

def is_jsrun_installed() -> bool:
    """Reference ``js_run.is_jsrun_installed``."""
    return shutil.which("jsrun") is not None


def generate_jsrun_rankfile(
    num_proc: int,
    host_slots: Dict[str, int],
    cores_per_proc,
    path: Optional[str] = None,
) -> str:
    """Write an ERF (explicit resource file) splitting each host's cores
    evenly among its worker processes (reference
    ``js_run.generate_jsrun_rankfile`` — same file format, but core
    counts come from the LSF env instead of CSM queries).

    ``cores_per_proc`` is an int (uniform) or a ``{host: cores}`` dict —
    LSF allocations are often heterogeneous (the launch/batch host
    typically has fewer slots than the compute hosts), so per-host core
    budgets keep the cpu ranges valid on every host.
    """
    remaining = num_proc
    lines = ["overlapping_rs: allow", "cpu_index_using: logical"]
    rank = 0
    for host, slots in host_slots.items():
        if remaining <= 0:
            break
        take = min(slots, remaining)
        remaining -= take
        per = (cores_per_proc.get(host, 1)
               if isinstance(cores_per_proc, dict) else cores_per_proc)
        per = max(1, per)
        lines.append("")
        cpu = 0
        for _ in range(take):
            lines.append(
                f"rank: {rank}: {{ hostname: {host}; "
                f"cpu: {{{cpu}-{cpu + per - 1}}} }}"
            )
            rank += 1
            cpu += per
    if remaining > 0:
        raise ValueError(
            f"LSF allocation provides {num_proc - remaining} slot(s), "
            f"{num_proc} requested"
        )
    # create the temp file only after validation so a raise leaks nothing
    if path is None:
        fd, path = tempfile.mkstemp(prefix="hvd_tpu_jsrun_", suffix=".erf")
        os.close(fd)
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return path


def spread_workers(np_: int, hostnames: List[str]) -> Dict[str, int]:
    """Spread ``np_`` workers evenly across hosts (one worker per host
    when np_ == nhosts — the TPU convention: a worker owns its host's
    chips — generalizing to balanced counts when np_ > nhosts)."""
    nhosts = len(hostnames)
    base, extra = divmod(np_, nhosts) if nhosts else (0, 0)
    out = {
        h: base + (1 if i < extra else 0) for i, h in enumerate(hostnames)
    }
    return {h: s for h, s in out.items() if s > 0}


def get_jsrun_command(
    np_: int,
    command: List[str],
    *,
    rankfile: Optional[str] = None,
    output_filename: Optional[str] = None,
    extra_args: Optional[List[str]] = None,
) -> List[str]:
    """Build the jsrun command line (exposed for tests).

    jsrun starts ``np_`` resource-set tasks; each task runs the
    ``mpi_worker`` shim (jsrun exports ``PMIX_RANK``), which rewrites
    rank env and execs the user command.  Env forwarding is implicit —
    jsrun propagates the launch environment — so unlike ``mpirun`` no
    ``-x`` flags are needed.
    """
    import sys

    cmd = ["jsrun"]
    if rankfile:
        cmd += ["--erf_input", rankfile]
    else:
        # one task per resource set, one resource set per process
        cmd += ["--nrs", str(np_), "--tasks_per_rs", "1"]
    if output_filename:
        cmd += ["--stdio_stdout", output_filename,
                "--stdio_stderr", output_filename]
    cmd += list(extra_args or [])
    cmd += [sys.executable, "-m", "horovod_tpu.runner.mpi_worker"]
    cmd += list(command)
    return cmd


def js_run(
    np_: int,
    command: List[str],
    *,
    hosts: Optional[Dict[str, int]] = None,
    extra_env: Optional[Dict[str, str]] = None,
    extra_args: Optional[List[str]] = None,
    output_filename: Optional[str] = None,
    verbose: bool = False,
) -> int:
    """Launch ``np_`` workers through jsrun inside an LSF allocation
    (reference ``js_run.js_run``).  The rendezvous controller runs in
    this process, exactly like the mpirun path.

    ``hosts`` (``{host: slots}``) overrides worker placement (the
    ``hvdrun -H`` path, reference ``settings.hosts``); hosts must
    belong to the allocation and slot counts must fit its cores.
    """
    import subprocess

    from .launch import start_job_services
    from ..utils.logging import get_logger

    if not using_lsf():
        raise RuntimeError(
            "--use-jsrun requires an LSF job allocation (LSB_JOBID is "
            "not set); submit through bsub or use another launcher"
        )
    if not is_jsrun_installed():
        raise RuntimeError(
            "jsrun not found on PATH (reference js_run raises the same); "
            "run inside an LSF/JSM allocation or use another launcher"
        )
    host_cores = get_allocated_hosts()
    if hosts is not None:
        unknown = [h for h in hosts if h not in host_cores]
        if unknown:
            raise ValueError(
                f"-H host(s) {unknown} are not part of the LSF "
                f"allocation {list(host_cores)}"
            )
        if sum(hosts.values()) < np_:
            raise ValueError(
                f"-H provides {sum(hosts.values())} slot(s), "
                f"{np_} requested"
            )
        # Normalize the -H request to the workers actually PLACED (the
        # rankfile fills hosts in order up to np_): capacity checks and
        # core budgets must reflect placement, not the raw request.
        worker_slots = {}
        remaining = np_
        for h, s in hosts.items():
            if remaining <= 0:
                break
            take = min(s, remaining)
            worker_slots[h] = take
            remaining -= take
    else:
        # Workers spread evenly across the compute hosts, NOT packed
        # onto the first host: each worker owns a host's chips.
        worker_slots = spread_workers(np_, get_compute_hosts())
    over = {h: s for h, s in worker_slots.items() if s > host_cores[h]}
    if over:
        capacity = sum(host_cores[h] for h in worker_slots)
        raise ValueError(
            f"allocation provides {capacity} core slot(s) on "
            f"{list(worker_slots)}, {np_} worker(s) requested "
            f"(oversubscribed: {over})"
        )
    rankfile = generate_jsrun_rankfile(
        np_, worker_slots,
        {h: host_cores[h] // s for h, s in worker_slots.items()},
    )
    # Worker 0 (the jax.distributed coordinator) runs on the first
    # rankfile host; the shared helper points the coordinator addr
    # there and the rendezvous addr at this launcher process.
    server, service_env = start_job_services(np_, list(worker_slots), nic_probe=False)
    env = dict(os.environ)
    env.update(service_env)
    if extra_env:
        env.update(extra_env)
    cmd = get_jsrun_command(
        np_, command, rankfile=rankfile,
        output_filename=output_filename, extra_args=extra_args,
    )
    if verbose:
        get_logger().warning("jsrun launch: %s",
                             " ".join(quote(c) for c in cmd))
    try:
        return subprocess.run(cmd, env=env).returncode
    finally:
        server.stop()
        try:
            os.unlink(rankfile)
        except OSError:
            pass
