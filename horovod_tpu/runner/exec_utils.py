"""Process execution: local subprocess or ssh, with rank-prefixed output
streaming (reference ``horovod/runner/gloo_run.py:187-211`` execs
per-slot commands over ssh and threads stream stdout/stderr with a rank
prefix; ``safe_shell_exec`` handles termination).
"""

from __future__ import annotations

import os
import shlex
import socket
import subprocess
import sys
import threading
from typing import Dict, List, Optional

LOCAL_HOSTNAMES = {"localhost", "127.0.0.1", "::1"}

# NIC-probe results per remote-host set (see probe_routable_addr).
_probe_cache: Dict[tuple, str] = {}


def is_local(hostname: str) -> bool:
    return hostname in LOCAL_HOSTNAMES or hostname == socket.gethostname()


def routable_addr(assignments) -> str:
    """Address remote workers should dial to reach a service running in
    this (driver) process: loopback when every slot is local, else this
    host's resolvable address.  Shared by the static, elastic, and jsrun
    launch paths so they cannot diverge.  Accepts SlotInfo-likes (with a
    ``hostname`` attr) or plain hostname strings.

    This is the zero-cost heuristic; :func:`probe_routable_addr` runs
    the reference-style mutual-interface check on top of it."""
    names = [getattr(a, "hostname", a) for a in assignments]
    if all(is_local(h) for h in names):
        return "127.0.0.1"
    return socket.gethostbyname(socket.gethostname())


def _local_candidate_addrs(remote_hosts) -> List[str]:
    """Candidate local addresses remote hosts might reach us on.

    Per-destination outbound interfaces via the UDP-connect trick
    (kernel routing decides, nothing is sent), plus the resolved
    hostname; loopback excluded, order preserved."""
    cands: List[str] = []
    for h in remote_hosts:
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect((h, 9))
                cands.append(s.getsockname()[0])
            finally:
                s.close()
        except OSError:
            continue
    try:
        cands.append(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    out: List[str] = []
    for c in cands:
        if c and not c.startswith("127.") and c != "::1" and c not in out:
            out.append(c)
    return out


def _ssh_dial(host, addrs, port, token, ssh_port, ssh_identity_file,
              timeout_s):
    """Run a one-shot dial script ON ``host`` (via ssh) that tries every
    candidate address and prints the ones whose echo handshake worked."""
    script = (
        "import socket,sys\n"
        "ok=[]\n"
        f"for a in {list(addrs)!r}:\n"
        "    try:\n"
        "        s=socket.create_connection((a, %d), timeout=3)\n"
        "        s.sendall(%r.encode()+b'\\n')\n"
        "        if s.recv(64).strip()==%r.encode(): ok.append(a)\n"
        "        s.close()\n"
        "    except OSError: pass\n"
        "print(','.join(ok))\n" % (port, token, token)
    )
    # Own ssh argv (host is always remote here): BatchMode forbids
    # password prompts and ConnectTimeout bounds a firewalled port —
    # a hung probe must never stall the launch.
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no",
           "-o", "BatchMode=yes", "-o", "ConnectTimeout=5"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    if ssh_identity_file:
        ssh += ["-i", ssh_identity_file]
    # The launcher's sys.executable may not exist at the same prefix on
    # a heterogeneous remote; fall back to `python3` there rather than
    # paying the full probe timeout and caching the heuristic fallback.
    # Wrapped in `sh -c` because sshd hands the command string to the
    # remote USER's login shell, which may not parse POSIX syntax.
    fallback = (
        f"PY={shlex.quote(sys.executable)}; "
        f'command -v "$PY" >/dev/null 2>&1 || PY=python3; '
        f'"$PY" -c {shlex.quote(script)}'
    )
    remote_cmd = f"sh -c {shlex.quote(fallback)}"
    argv = ssh + [host, remote_cmd]
    try:
        res = subprocess.run(argv, capture_output=True, text=True,
                             timeout=timeout_s)
        if res.returncode != 0:
            return set()
        return {a for a in res.stdout.strip().split(",") if a}
    except Exception:
        return set()


def probe_routable_addr(assignments, ssh_port=None, ssh_identity_file=None,
                        timeout_s: float = 20.0, _dial=None) -> str:
    """Mutually-verified driver address (the reference NIC-probe
    protocol, ``runner/driver/driver_service.py`` ``_run_probe`` +
    ``task_service.py:383`` recast): the launch host listens with a
    token echo, every REMOTE host dials back each candidate local
    address, and the first address reachable from ALL remote hosts
    wins — a multi-NIC launch host can no longer hand workers an
    interface they cannot route to.

    Falls back to :func:`routable_addr` (with a warning naming the
    per-host results) when no candidate is mutually reachable or
    probing is disabled via ``HVD_TPU_NIC_PROBE=0``."""
    from ..utils.env import get_bool
    from ..utils.logging import get_logger

    names = [getattr(a, "hostname", a) for a in assignments]
    remotes = sorted({h for h in names if not is_local(h)})
    if not remotes:
        return "127.0.0.1"
    if not get_bool("NIC_PROBE", True):
        return routable_addr(assignments)
    # One ssh round-trip per remote host is fine at launch but not per
    # elastic round: cache per remote-host set.
    cache_key = (tuple(remotes), ssh_port, ssh_identity_file)
    if _dial is None and cache_key in _probe_cache:
        return _probe_cache[cache_key]
    cands = _local_candidate_addrs(remotes)
    if not cands:
        get_logger().warning(
            "NIC probe: no candidate local addresses for remotes %s; "
            "falling back to the resolver heuristic", remotes,
        )
        return routable_addr(assignments)

    import secrets as _secrets
    import threading as _threading

    token = _secrets.token_hex(8)
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("", 0))
    srv.listen(64)
    srv.settimeout(0.5)
    port = srv.getsockname()[1]
    stop = _threading.Event()

    def echo_loop():
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                line = conn.recv(64)
                if line.strip() == token.encode():
                    conn.sendall(token.encode() + b"\n")
            except OSError:
                pass
            finally:
                conn.close()

    t = _threading.Thread(target=echo_loop, daemon=True)
    t.start()
    dial = _dial or (lambda h: _ssh_dial(
        h, cands, port, token, ssh_port, ssh_identity_file, timeout_s
    ))
    try:
        # Dial hosts concurrently: each probe is an independent ssh, so
        # an unreachable cluster costs one timeout, not hosts x timeout.
        reachable: Dict[str, set] = {}
        dial_threads = []
        for h in remotes:
            def run(h=h):
                reachable[h] = dial(h)

            th = threading.Thread(target=run, daemon=True)
            th.start()
            dial_threads.append(th)
        for th in dial_threads:
            th.join(timeout=timeout_s + 5)
        for h in remotes:
            reachable.setdefault(h, set())
    finally:
        stop.set()
        srv.close()
        t.join(timeout=2)
    common = [c for c in cands if all(c in reachable[h] for h in remotes)]
    if common:
        addr = common[0]
    else:
        get_logger().warning(
            "NIC probe: no local address reachable from every remote "
            "host (candidates %s, per-host results %s); falling back to "
            "the resolver heuristic — set the driver address explicitly "
            "if workers fail to connect", cands, reachable,
        )
        addr = routable_addr(assignments)
    if _dial is None:
        # Cache fallbacks too: elastic respawns must not repay the
        # probe timeout every recovery round.
        _probe_cache[cache_key] = addr
    return addr


def build_command(
    hostname: str,
    command: List[str],
    env: Dict[str, str],
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
) -> List[str]:
    """Local commands run directly with env; remote wrap in ssh with
    inline exports (reference ``get_remote_command``)."""
    if is_local(hostname):
        return command
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(env.items())
    )
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    if ssh_identity_file:
        ssh += ["-i", ssh_identity_file]
    remote = f"cd {shlex.quote(os.getcwd())} && env {exports} " + " ".join(
        shlex.quote(c) for c in command
    )
    return ssh + [hostname, remote]


class WorkerProcess:
    """One launched worker with output streaming."""

    def __init__(
        self,
        rank: int,
        hostname: str,
        command: List[str],
        env: Dict[str, str],
        ssh_port: Optional[int] = None,
        ssh_identity_file: Optional[str] = None,
        prefix_output: bool = True,
    ):
        self.rank = rank
        self.hostname = hostname
        full_env = dict(os.environ)
        full_env.update(env)
        argv = build_command(hostname, command, env, ssh_port, ssh_identity_file)
        self.proc = subprocess.Popen(
            argv,
            env=full_env if is_local(hostname) else None,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            bufsize=1,
        )
        self._streamer = threading.Thread(
            target=self._stream, args=(prefix_output,), daemon=True
        )
        self._streamer.start()

    def _stream(self, prefix: bool) -> None:
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            if prefix:
                sys.stdout.write(f"[{self.rank}]<stdout>: {line}")
            else:
                sys.stdout.write(line)
            sys.stdout.flush()

    def wait(self, timeout: Optional[float] = None) -> int:
        rc = self.proc.wait(timeout)
        self._streamer.join(timeout=5)
        return rc

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    @property
    def returncode(self) -> Optional[int]:
        return self.proc.poll()
