"""Process execution: local subprocess or ssh, with rank-prefixed output
streaming (reference ``horovod/runner/gloo_run.py:187-211`` execs
per-slot commands over ssh and threads stream stdout/stderr with a rank
prefix; ``safe_shell_exec`` handles termination).
"""

from __future__ import annotations

import os
import shlex
import socket
import subprocess
import sys
import threading
from typing import Dict, List, Optional

LOCAL_HOSTNAMES = {"localhost", "127.0.0.1", "::1"}


def is_local(hostname: str) -> bool:
    return hostname in LOCAL_HOSTNAMES or hostname == socket.gethostname()


def routable_addr(assignments) -> str:
    """Address remote workers should dial to reach a service running in
    this (driver) process: loopback when every slot is local, else this
    host's resolvable address.  Shared by the static, elastic, and jsrun
    launch paths so they cannot diverge.  Accepts SlotInfo-likes (with a
    ``hostname`` attr) or plain hostname strings."""
    names = [getattr(a, "hostname", a) for a in assignments]
    if all(is_local(h) for h in names):
        return "127.0.0.1"
    return socket.gethostbyname(socket.gethostname())


def build_command(
    hostname: str,
    command: List[str],
    env: Dict[str, str],
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
) -> List[str]:
    """Local commands run directly with env; remote wrap in ssh with
    inline exports (reference ``get_remote_command``)."""
    if is_local(hostname):
        return command
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(env.items())
    )
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    if ssh_identity_file:
        ssh += ["-i", ssh_identity_file]
    remote = f"cd {shlex.quote(os.getcwd())} && env {exports} " + " ".join(
        shlex.quote(c) for c in command
    )
    return ssh + [hostname, remote]


class WorkerProcess:
    """One launched worker with output streaming."""

    def __init__(
        self,
        rank: int,
        hostname: str,
        command: List[str],
        env: Dict[str, str],
        ssh_port: Optional[int] = None,
        ssh_identity_file: Optional[str] = None,
        prefix_output: bool = True,
    ):
        self.rank = rank
        self.hostname = hostname
        full_env = dict(os.environ)
        full_env.update(env)
        argv = build_command(hostname, command, env, ssh_port, ssh_identity_file)
        self.proc = subprocess.Popen(
            argv,
            env=full_env if is_local(hostname) else None,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            bufsize=1,
        )
        self._streamer = threading.Thread(
            target=self._stream, args=(prefix_output,), daemon=True
        )
        self._streamer.start()

    def _stream(self, prefix: bool) -> None:
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            if prefix:
                sys.stdout.write(f"[{self.rank}]<stdout>: {line}")
            else:
                sys.stdout.write(line)
            sys.stdout.flush()

    def wait(self, timeout: Optional[float] = None) -> int:
        rc = self.proc.wait(timeout)
        self._streamer.join(timeout=5)
        return rc

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    @property
    def returncode(self) -> Optional[int]:
        return self.proc.poll()
