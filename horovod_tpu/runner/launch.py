"""``hvdrun`` — the launcher CLI.

Reference: ``horovodrun`` (``horovod/runner/launch.py``, 774 LoC): parses
np/hosts/elastic flags plus every HOROVOD_* knob, starts the rendezvous
server, computes host assignments, and execs workers over ssh with
per-slot env.  The TPU launcher keeps that surface but drops the
MPI/gloo controller choice (the data plane is XLA) and the NIC-discovery
driver (the JAX coordination service exchanges addresses itself).

Worker env contract (read by ``runtime._init_distributed`` /
``Runtime``):
  HVD_TPU_COORDINATOR_ADDR  host:port of the jax.distributed coordinator
                            (runs inside worker 0)
  HVD_TPU_CROSS_RANK/SIZE   process id / process count
  HVD_TPU_RENDEZVOUS_ADDR/PORT/SECRET  the controller KV store
"""

from __future__ import annotations

import argparse
import secrets as pysecrets
import socket
import sys
import time
from typing import Dict, List, Optional

from ..utils.logging import get_logger
from ..version import __version__
from . import controller_py, exec_utils, hosts as hosts_mod


def free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_worker_env(
    slot: hosts_mod.SlotInfo,
    coordinator_addr: str,
    rendezvous_addr: str,
    rendezvous_port: int,
    secret: str,
    extra_env: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    env = {
        "HVD_TPU_COORDINATOR_ADDR": coordinator_addr,
        "HVD_TPU_CROSS_RANK": str(slot.rank),
        "HVD_TPU_CROSS_SIZE": str(slot.size),
        "HVD_TPU_LOCAL_RANK": str(slot.local_rank),
        "HVD_TPU_LOCAL_SIZE": str(slot.local_size),
        "HVD_TPU_HOSTNAME": slot.hostname,
        "HVD_TPU_RENDEZVOUS_ADDR": rendezvous_addr,
        "HVD_TPU_RENDEZVOUS_PORT": str(rendezvous_port),
        "HVD_TPU_SECRET": secret,
    }
    if extra_env:
        env.update(extra_env)
    return env


def launch_static(
    np_: int,
    host_list: List[hosts_mod.HostInfo],
    command: List[str],
    *,
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
    extra_env: Optional[Dict[str, str]] = None,
    verbose: bool = False,
) -> int:
    """Static (fixed world) launch (reference ``launch_gloo``,
    ``runner/gloo_run.py:226``).  Returns the first non-zero exit code,
    terminating the remaining workers on failure like the reference.
    """
    assignments = hosts_mod.get_host_assignments(host_list, np_)
    secret = pysecrets.token_hex(16)
    server = controller_py.make_server(secret, np_)
    rendezvous_addr = exec_utils.routable_addr(assignments)
    coordinator_host = (
        "127.0.0.1"
        if exec_utils.is_local(assignments[0].hostname)
        else assignments[0].hostname
    )
    coordinator_addr = f"{coordinator_host}:{free_port()}"
    if verbose:
        get_logger().warning(
            "launching %d process(es) on %d host(s); rendezvous %s:%d",
            np_, assignments[-1].cross_size, rendezvous_addr, server.port,
        )
    workers = []
    try:
        for slot in assignments:
            env = make_worker_env(
                slot, coordinator_addr, rendezvous_addr, server.port, secret,
                extra_env,
            )
            workers.append(
                exec_utils.WorkerProcess(
                    slot.rank, slot.hostname, command, env,
                    ssh_port=ssh_port, ssh_identity_file=ssh_identity_file,
                )
            )
        exit_code = 0
        pending = set(range(len(workers)))
        while pending:
            for i in sorted(pending):
                rc = workers[i].returncode
                if rc is not None:
                    pending.discard(i)
                    if rc != 0:
                        exit_code = exit_code or rc
                        # fail fast: a dead peer wedges collectives
                        for j in pending:
                            workers[j].terminate()
                        pending = set()
                        break
            time.sleep(0.1)
        for w in workers:
            w.wait()
        return exit_code
    finally:
        for w in workers:
            w.terminate()
        server.stop()


def check_build(out=None) -> None:
    """Print the capability report (reference ``check_build``,
    ``runner/launch.py:110`` — 'Available Frameworks/Controllers/Tensor
    Operations' box)."""
    def flag(ok: bool) -> str:
        return "[X]" if ok else "[ ]"

    lines = [f"horovod_tpu v{__version__}:", "", "Available Frameworks:"]
    for mod, name in [("jax", "JAX"), ("flax", "Flax"), ("optax", "Optax"),
                      ("orbax.checkpoint", "Orbax")]:
        try:
            __import__(mod)
            ok = True
        except ImportError:
            ok = False
        lines.append(f"    {flag(ok)} {name}")
    # Like the reference, report configured capabilities without
    # initializing backends (jax.devices() would block on TPU runtime
    # bring-up, which can take minutes over a cold tunnel).
    import os

    lines += ["", "Configured Device Backends:"]
    platforms = os.environ.get("JAX_PLATFORMS", "")
    tpu_configured = bool(
        os.environ.get("PALLAS_AXON_POOL_IPS")
        or os.environ.get("TPU_NAME")
        or "tpu" in platforms
    ) and platforms != "cpu"
    lines.append(f"    {flag(tpu_configured)} TPU")
    lines.append(f"    {flag(True)} CPU (XLA host)")
    lines += ["", "Available Components:"]
    from .. import native

    lines.append(f"    {flag(native.available())} native core (C++)")
    try:
        from jax.experimental import pallas  # noqa: F401

        has_pallas = True
    except ImportError:
        has_pallas = False
    lines.append(f"    {flag(has_pallas)} Pallas kernels")
    for ok, name in [(True, "process sets"), (True, "elastic"),
                     (True, "timeline"), (True, "autotune"),
                     (True, "Adasum")]:
        lines.append(f"    {flag(ok)} {name}")
    print("\n".join(lines), file=out)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job "
        "(the horovodrun equivalent).",
    )
    parser.add_argument("--version", action="version", version=__version__)
    parser.add_argument("-np", "--num-proc", type=int, dest="np",
                        help="total number of worker processes")
    parser.add_argument("-H", "--hosts",
                        help="comma list of host:slots (default localhost:np)")
    parser.add_argument("--hostfile",
                        help="hostfile with 'host slots=N' lines")
    parser.add_argument("-p", "--ssh-port", type=int, dest="ssh_port")
    parser.add_argument("-i", "--ssh-identity-file", dest="ssh_identity_file")
    parser.add_argument("--verbose", action="store_true")
    # elastic flags (reference --min-np/--max-np/--host-discovery-script)
    parser.add_argument("--min-np", type=int, dest="min_np")
    parser.add_argument("--max-np", type=int, dest="max_np")
    parser.add_argument("--host-discovery-script", dest="discovery_script")
    # knob flags -> env (reference config_parser.py maps flags to env)
    parser.add_argument("--fusion-threshold-mb", type=int)
    parser.add_argument("--timeline-filename")
    parser.add_argument("--autotune", action="store_true")
    parser.add_argument("--autotune-log-file")
    parser.add_argument("--log-level")
    parser.add_argument("--use-mpi", action="store_true",
                        help="launch workers via mpirun (reference "
                        "horovodrun --use-mpi; MPI is launcher-only — "
                        "collectives still ride XLA)")
    parser.add_argument("--mpi-args", default="",
                        help="extra args appended to the mpirun line")
    parser.add_argument("--config-file",
                        help="JSON/YAML config with the same knobs "
                        "(CLI flags win on conflict)")
    parser.add_argument("--check-build", action="store_true",
                        help="print the capability report and exit")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="worker command, e.g. python train.py")
    args = parser.parse_args(argv)
    if args.check_build:
        return args
    if args.config_file:
        from .config_parser import apply_config_to_args, parse_config_file

        apply_config_to_args(args, parse_config_file(args.config_file))
    if not args.command:
        parser.error("no worker command given")
    if args.command[0] == "--":
        args.command = args.command[1:]
    if args.np is None and args.min_np is None:
        parser.error("-np (or --min-np for elastic) is required")
    return args


def env_from_args(args: argparse.Namespace) -> Dict[str, str]:
    """Map CLI knob flags onto HVD_TPU_* env (reference
    ``runner/common/util/config_parser.py``)."""
    env: Dict[str, str] = {}
    if args.fusion_threshold_mb is not None:
        env["HVD_TPU_FUSION_THRESHOLD"] = str(args.fusion_threshold_mb << 20)
    if args.timeline_filename:
        env["HVD_TPU_TIMELINE"] = args.timeline_filename
    if args.autotune:
        env["HVD_TPU_AUTOTUNE"] = "1"
    if args.autotune_log_file:
        env["HVD_TPU_AUTOTUNE_LOG"] = args.autotune_log_file
    if args.log_level:
        env["HVD_TPU_LOG_LEVEL"] = args.log_level
    return env


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.check_build:
        check_build()
        return 0
    if args.discovery_script or args.min_np is not None:
        from .elastic_launch import launch_elastic

        return launch_elastic(args)
    if args.use_mpi:
        import shlex

        from .mpi_run import mpi_run

        hosts = args.hosts
        if args.hostfile and not hosts:
            # translate the hostfile to mpirun -H syntax
            hosts = ",".join(
                f"{h.hostname}:{h.slots}"
                for h in hosts_mod.parse_host_files(args.hostfile)
            )
        return mpi_run(
            args.np, hosts, args.command,
            extra_env=env_from_args(args),
            mpi_args=shlex.split(args.mpi_args) if args.mpi_args else None,
            verbose=args.verbose,
        )
    if args.hostfile:
        host_list = hosts_mod.parse_host_files(args.hostfile)
    elif args.hosts:
        host_list = hosts_mod.parse_hosts(args.hosts)
    else:
        host_list = [hosts_mod.HostInfo("localhost", args.np)]
    return launch_static(
        args.np,
        host_list,
        args.command,
        ssh_port=args.ssh_port,
        ssh_identity_file=args.ssh_identity_file,
        extra_env=env_from_args(args),
        verbose=args.verbose,
    )


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
