"""``hvdrun`` — the launcher CLI.

Reference: ``horovodrun`` (``horovod/runner/launch.py``, 774 LoC): parses
np/hosts/elastic flags plus every HOROVOD_* knob, starts the rendezvous
server, computes host assignments, and execs workers over ssh with
per-slot env.  The TPU launcher keeps that surface but drops the
MPI/gloo controller choice (the data plane is XLA) and the NIC-discovery
driver (the JAX coordination service exchanges addresses itself).

Worker env contract (read by ``runtime._init_distributed`` /
``Runtime``):
  HVD_TPU_COORDINATOR_ADDR  host:port of the jax.distributed coordinator
                            (runs inside worker 0)
  HVD_TPU_CROSS_RANK/SIZE   process id / process count
  HVD_TPU_RENDEZVOUS_ADDR/PORT/SECRET  the controller KV store
"""

from __future__ import annotations

import argparse
import secrets as pysecrets
import shlex
import socket
import sys
import time
from typing import Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from ..version import __version__
from . import controller_py, exec_utils, hosts as hosts_mod


def free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def start_job_services(
    np_: int,
    worker_hosts: List[str],
    *,
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
    nic_probe: bool = True,
) -> Tuple[object, Dict[str, str]]:
    """Start the KV/rendezvous controller in this (launcher) process and
    build the service env every launch path exports — one implementation
    shared by the static, mpirun, and jsrun paths so they cannot drift.

    ``worker_hosts`` is ordered: worker 0 — which hosts the
    ``jax.distributed`` coordinator per the env contract above — runs on
    ``worker_hosts[0]``.  Loopback addresses are only used when every
    worker is local to the launcher.  Returns ``(server, env)``; the
    caller owns ``server.stop()``.
    """
    secret = pysecrets.token_hex(16)
    server = controller_py.make_server(secret, np_)
    all_local = all(exec_utils.is_local(h) for h in worker_hosts)
    # Mutually-verified launcher address (the reference NIC-probe
    # protocol): one probe covers both the rendezvous KV and a
    # launcher-local coordinator.  Launchers that do not reach workers
    # over ssh (mpirun/jsrun own the remote exec) pass nic_probe=False
    # and keep the heuristic.
    if all_local:
        launcher_addr = "127.0.0.1"
    elif nic_probe:
        launcher_addr = exec_utils.probe_routable_addr(
            worker_hosts, ssh_port=ssh_port,
            ssh_identity_file=ssh_identity_file,
        )
    else:
        launcher_addr = exec_utils.routable_addr(worker_hosts)
    if all_local:
        coordinator_host = "127.0.0.1"
    elif exec_utils.is_local(worker_hosts[0]):
        # worker 0 runs on this launcher host but peers are remote: they
        # must dial a routable name, not the literal "localhost".
        coordinator_host = launcher_addr
    else:
        coordinator_host = worker_hosts[0]
    env = {
        "HVD_TPU_COORDINATOR_ADDR": f"{coordinator_host}:{free_port()}",
        "HVD_TPU_CROSS_SIZE": str(np_),
        "HVD_TPU_RENDEZVOUS_ADDR": launcher_addr,
        "HVD_TPU_RENDEZVOUS_PORT": str(server.port),
        "HVD_TPU_SECRET": secret,
    }
    return server, env


def slot_env_entries(slot: hosts_mod.SlotInfo) -> Dict[str, str]:
    """The per-slot half of the worker env contract."""
    return {
        "HVD_TPU_CROSS_RANK": str(slot.rank),
        "HVD_TPU_CROSS_SIZE": str(slot.size),
        "HVD_TPU_LOCAL_RANK": str(slot.local_rank),
        "HVD_TPU_LOCAL_SIZE": str(slot.local_size),
        "HVD_TPU_HOSTNAME": slot.hostname,
    }


def make_worker_env(
    slot: hosts_mod.SlotInfo,
    coordinator_addr: str,
    rendezvous_addr: str,
    rendezvous_port: int,
    secret: str,
    extra_env: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    env = {
        "HVD_TPU_COORDINATOR_ADDR": coordinator_addr,
        "HVD_TPU_RENDEZVOUS_ADDR": rendezvous_addr,
        "HVD_TPU_RENDEZVOUS_PORT": str(rendezvous_port),
        "HVD_TPU_SECRET": secret,
        **slot_env_entries(slot),
    }
    if extra_env:
        env.update(extra_env)
    return env


def launch_static(
    np_: int,
    host_list: List[hosts_mod.HostInfo],
    command: List[str],
    *,
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
    extra_env: Optional[Dict[str, str]] = None,
    verbose: bool = False,
) -> int:
    """Static (fixed world) launch (reference ``launch_gloo``,
    ``runner/gloo_run.py:226``).  Returns the first non-zero exit code,
    terminating the remaining workers on failure like the reference.
    """
    assignments = hosts_mod.get_host_assignments(host_list, np_)
    server, service_env = start_job_services(
        np_, [a.hostname for a in assignments],
        ssh_port=ssh_port, ssh_identity_file=ssh_identity_file,
    )
    if verbose:
        get_logger().warning(
            "launching %d process(es) on %d host(s); rendezvous %s:%s",
            np_, assignments[-1].cross_size,
            service_env["HVD_TPU_RENDEZVOUS_ADDR"],
            service_env["HVD_TPU_RENDEZVOUS_PORT"],
        )
    workers = []
    try:
        for slot in assignments:
            env = dict(service_env)
            env.update(slot_env_entries(slot))
            if extra_env:
                env.update(extra_env)
            workers.append(
                exec_utils.WorkerProcess(
                    slot.rank, slot.hostname, command, env,
                    ssh_port=ssh_port, ssh_identity_file=ssh_identity_file,
                )
            )
        exit_code = 0
        pending = set(range(len(workers)))
        while pending:
            for i in sorted(pending):
                rc = workers[i].returncode
                if rc is not None:
                    pending.discard(i)
                    if rc != 0:
                        exit_code = exit_code or rc
                        # fail fast: a dead peer wedges collectives
                        for j in pending:
                            workers[j].terminate()
                        pending = set()
                        break
            time.sleep(0.1)
        for w in workers:
            w.wait()
        return exit_code
    finally:
        for w in workers:
            w.terminate()
        server.stop()


def check_build(out=None) -> None:
    """Print the capability report (reference ``check_build``,
    ``runner/launch.py:110`` — 'Available Frameworks/Controllers/Tensor
    Operations' box)."""
    def flag(ok: bool) -> str:
        return "[X]" if ok else "[ ]"

    lines = [f"horovod_tpu v{__version__}:", "", "Available Frameworks:"]
    for mod, name in [("jax", "JAX"), ("flax", "Flax"), ("optax", "Optax"),
                      ("orbax.checkpoint", "Orbax")]:
        try:
            __import__(mod)
            ok = True
        except ImportError:
            ok = False
        lines.append(f"    {flag(ok)} {name}")
    # Like the reference, report configured capabilities without
    # initializing backends (jax.devices() would block on TPU runtime
    # bring-up, which can take minutes over a cold tunnel).
    import os

    lines += ["", "Configured Device Backends:"]
    platforms = os.environ.get("JAX_PLATFORMS", "")
    tpu_configured = bool(
        os.environ.get("PALLAS_AXON_POOL_IPS")
        or os.environ.get("TPU_NAME")
        or "tpu" in platforms
    ) and platforms != "cpu"
    lines.append(f"    {flag(tpu_configured)} TPU")
    lines.append(f"    {flag(True)} CPU (XLA host)")
    lines += ["", "Available Components:"]
    from .. import native

    lines.append(f"    {flag(native.available())} native core (C++)")
    try:
        from jax.experimental import pallas  # noqa: F401

        has_pallas = True
    except ImportError:
        has_pallas = False
    lines.append(f"    {flag(has_pallas)} Pallas kernels")
    for ok, name in [(True, "process sets"), (True, "elastic"),
                     (True, "timeline"), (True, "autotune"),
                     (True, "Adasum"), (True, "ZeRO/FSDP"),
                     (True, "TP/PP/SP/MoE"),
                     (True, "sequence packing"),
                     (True, "differentiable bridge collectives")]:
        lines.append(f"    {flag(ok)} {name}")
    lines += ["", "Available Bindings:"]
    import importlib.util as _ilu

    for mod, name in [("torch", "PyTorch (interop.torch)"),
                      ("tensorflow", "TensorFlow/Keras (interop.tf)"),
                      ("mxnet", "MXNet (interop.mxnet)")]:
        # find_spec, not import: a capability report must not pay
        # framework import time (or crash on a broken install)
        try:
            ok = _ilu.find_spec(mod) is not None
        except (ImportError, ValueError):
            ok = False
        lines.append(f"    {flag(ok)} {name}")
    lines += ["", "Available Launchers:"]
    import shutil as _shutil

    lines.append(f"    {flag(True)} static ssh (hvdrun)")
    lines.append(f"    {flag(_shutil.which('mpirun') is not None)} mpirun "
                 "(--use-mpi)")
    from . import lsf as _lsf

    lines.append(f"    {flag(_lsf.is_jsrun_installed())} jsrun "
                 "(--use-jsrun)")
    lines.append(f"    {flag(True)} elastic (--min-np/--max-np)")
    try:
        has_pyspark = _ilu.find_spec("pyspark") is not None
    except (ImportError, ValueError):
        has_pyspark = False
    lines.append(f"    {flag(has_pyspark)} elastic on Spark "
                 "(spark.run_elastic)")
    print("\n".join(lines), file=out)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job "
        "(the horovodrun equivalent).",
    )
    parser.add_argument("--version", action="version", version=__version__)
    parser.add_argument("-np", "--num-proc", type=int, dest="np",
                        help="total number of worker processes")
    parser.add_argument("-H", "--hosts",
                        help="comma list of host:slots (default localhost:np)")
    parser.add_argument("--hostfile",
                        help="hostfile with 'host slots=N' lines")
    parser.add_argument("-p", "--ssh-port", type=int, dest="ssh_port")
    parser.add_argument("-i", "--ssh-identity-file", dest="ssh_identity_file")
    parser.add_argument("--verbose", action="store_true")
    # elastic flags (reference --min-np/--max-np/--host-discovery-script)
    parser.add_argument("--min-np", type=int, dest="min_np")
    parser.add_argument("--max-np", type=int, dest="max_np")
    parser.add_argument("--host-discovery-script", dest="discovery_script")
    # knob flags -> env (reference config_parser.py maps flags to env)
    parser.add_argument("--fusion-threshold-mb", type=int)
    parser.add_argument("--timeline-filename")
    parser.add_argument("--timeline-mark-cycles", action="store_true",
                        help="mark each train-step cycle on the timeline "
                        "(reference HOROVOD_TIMELINE_MARK_CYCLES; maps to "
                        "HVD_TPU_TIMELINE_MARK_CYCLES)")
    parser.add_argument("--telemetry-port", type=int, default=None,
                        help="serve HTTP /metrics + /health from the "
                        "elastic driver on this port (0 = OS-assigned; "
                        "maps to HVD_TPU_TELEMETRY_PORT)")
    parser.add_argument("--autotune", action="store_true")
    parser.add_argument("--autotune-log-file")
    parser.add_argument("--log-level")
    parser.add_argument("--use-mpi", action="store_true",
                        help="launch workers via mpirun (reference "
                        "horovodrun --use-mpi; MPI is launcher-only — "
                        "collectives still ride XLA)")
    parser.add_argument("--use-jsrun", action="store_true",
                        help="launch workers via jsrun inside an LSF "
                        "allocation (reference js_run.py; launcher-only)")
    parser.add_argument("--mpi-args", default="",
                        help="extra args appended to the mpirun (or, "
                        "with --use-jsrun, the jsrun) command line")
    parser.add_argument("--config-file",
                        help="JSON/YAML config with the same knobs "
                        "(CLI flags win on conflict)")
    parser.add_argument("--check-build", action="store_true",
                        help="print the capability report and exit")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="worker command, e.g. python train.py")
    args = parser.parse_args(argv)
    if args.check_build:
        return args
    if args.config_file:
        from .config_parser import apply_config_to_args, parse_config_file

        apply_config_to_args(args, parse_config_file(args.config_file))
    # Launcher-conflict validation runs AFTER the config file is folded
    # in, so elastic knobs declared there are caught too.
    if args.use_mpi and args.use_jsrun:
        parser.error("--use-mpi and --use-jsrun are mutually exclusive")
    if args.use_jsrun and (args.min_np is not None or args.max_np is not None
                           or args.discovery_script):
        parser.error("--use-jsrun cannot be combined with elastic flags "
                     "(--min-np/--max-np/--host-discovery-script)")
    if not args.command:
        parser.error("no worker command given")
    if args.command[0] == "--":
        args.command = args.command[1:]
    if args.np is None and args.min_np is None:
        from . import lsf

        if not lsf.using_lsf():
            parser.error("-np (or --min-np for elastic) is required "
                         "(inferred from the allocation under LSF)")
    return args


def env_from_args(args: argparse.Namespace) -> Dict[str, str]:
    """Map CLI knob flags onto HVD_TPU_* env (reference
    ``runner/common/util/config_parser.py``)."""
    env: Dict[str, str] = {}
    if args.fusion_threshold_mb is not None:
        env["HVD_TPU_FUSION_THRESHOLD"] = str(args.fusion_threshold_mb << 20)
    if args.timeline_filename:
        env["HVD_TPU_TIMELINE"] = args.timeline_filename
    if getattr(args, "timeline_mark_cycles", False):
        env["HVD_TPU_TIMELINE_MARK_CYCLES"] = "1"
    if args.autotune:
        env["HVD_TPU_AUTOTUNE"] = "1"
    if args.autotune_log_file:
        env["HVD_TPU_AUTOTUNE_LOG"] = args.autotune_log_file
    if args.log_level:
        env["HVD_TPU_LOG_LEVEL"] = args.log_level
    return env


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.check_build:
        check_build()
        return 0
    from . import lsf

    if args.np is None and args.min_np is None:
        # np was allowed to be omitted only under LSF: infer one worker
        # per allocated host BEFORE any launch branch consumes args.np —
        # but never against an explicit -H/--hostfile, whose slot layout
        # the user chose deliberately.
        if args.hosts or args.hostfile:
            print("hvdrun: -np is required when -H/--hostfile is given "
                  "(LSF inference applies only to allocation-derived "
                  "hosts)", file=sys.stderr)
            return 2
        try:
            args.np = len(lsf.get_compute_hosts())
        except RuntimeError as e:
            print(f"hvdrun: {e}", file=sys.stderr)
            return 2
    if args.discovery_script or args.min_np is not None:
        from .elastic_launch import launch_elastic

        return launch_elastic(args)
    if args.use_mpi:
        from .mpi_run import mpi_run

        hosts = args.hosts
        if args.hostfile and not hosts:
            # translate the hostfile to mpirun -H syntax
            hosts = ",".join(
                f"{h.hostname}:{h.slots}"
                for h in hosts_mod.parse_host_files(args.hostfile)
            )
        if not hosts and lsf.using_lsf():
            # Same allocation-derived hosts the static branch uses —
            # otherwise mpirun gets no -H and packs every worker onto
            # the launch host.
            hosts = ",".join(
                f"{h.hostname}:{h.slots}"
                for h in lsf.lsf_host_list(np_=args.np)
            )
        return mpi_run(
            args.np, hosts, args.command,
            extra_env=env_from_args(args),
            mpi_args=shlex.split(args.mpi_args) if args.mpi_args else None,
            verbose=args.verbose,
        )
    if args.use_jsrun:
        jsrun_hosts = None
        if args.hostfile and not args.hosts:
            jsrun_hosts = {
                h.hostname: h.slots
                for h in hosts_mod.parse_host_files(args.hostfile)
            }
        elif args.hosts:
            jsrun_hosts = {
                h.hostname: h.slots
                for h in hosts_mod.parse_hosts(args.hosts)
            }
        return lsf.js_run(
            args.np, args.command,
            hosts=jsrun_hosts,
            extra_env=env_from_args(args),
            extra_args=shlex.split(args.mpi_args) if args.mpi_args else None,
            verbose=args.verbose,
        )
    if args.hostfile:
        host_list = hosts_mod.parse_host_files(args.hostfile)
    elif args.hosts:
        host_list = hosts_mod.parse_hosts(args.hosts)
    elif lsf.using_lsf():
        # Inside an LSF allocation with no explicit hosts: use the
        # job's allocated hosts, one worker process per host — growing
        # slots when an explicit -np exceeds the host count (reference
        # launch.py consults LSFUtils the same way before defaulting to
        # localhost).
        host_list = lsf.lsf_host_list(np_=args.np)
    else:
        host_list = [hosts_mod.HostInfo("localhost", args.np)]
    return launch_static(
        args.np,
        host_list,
        args.command,
        ssh_port=args.ssh_port,
        ssh_identity_file=args.ssh_identity_file,
        extra_env=env_from_args(args),
        verbose=args.verbose,
    )


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
