"""MPI launch path: drive workers through ``mpirun``.

Reference: ``horovod/runner/mpi_run.py`` — builds an
``mpirun --allow-run-as-root -np N -H hosts -x ENV... <cmd>`` line with
Open MPI / Intel MPI flavor detection, binding flags, and env
forwarding.  TPU re-design: MPI is only the *process launcher* (there
is no MPI data plane — collectives ride XLA), so the command wraps each
worker in :mod:`horovod_tpu.runner.mpi_worker`, a shim that translates
the MPI-provided rank env (``OMPI_COMM_WORLD_RANK`` / ``PMI_RANK``)
into this framework's worker env contract before exec'ing the user
command.  The launcher still runs the rendezvous/KV controller and
exports its address through ``-x``, exactly like the static launcher.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
from typing import Dict, List, Optional

from . import hosts as hosts_mod
from .launch import start_job_services
from ..utils.logging import get_logger

# env vars forwarded to workers (reference mpi_run.py's -x list is the
# analogous framework env surface)
_FORWARD_PREFIXES = ("HVD_TPU_", "HOROVOD_", "JAX_", "XLA_", "TPU_",
                     "PYTHONPATH", "PATH", "LD_LIBRARY_PATH")


def is_mpi_available() -> bool:
    """Reference ``mpi_available()`` (``runner/mpi_run.py``): can we
    find a usable ``mpirun``?"""
    return shutil.which("mpirun") is not None


def get_mpi_command(
    np_: int,
    hosts: Optional[str],
    command: List[str],
    env: Dict[str, str],
    *,
    mpi_args: Optional[List[str]] = None,
    forward_names: Optional[List[str]] = None,
) -> List[str]:
    """Build the full mpirun command line (exposed for tests, like the
    reference's unit-tested command construction)."""
    cmd = [
        "mpirun",
        "--allow-run-as-root",
        "-np", str(np_),
    ]
    if hosts:
        # hosts syntax "h1:slots,h2:slots" maps to mpirun -H
        cmd += ["-H", hosts]
    # forward the framework env plus anything the caller set explicitly
    names = sorted(
        {k for k in env if k.startswith(_FORWARD_PREFIXES)}
        | set(forward_names or ())
    )
    for k in names:
        cmd += ["-x", k]
    cmd += list(mpi_args or [])
    cmd += [
        sys.executable, "-m", "horovod_tpu.runner.mpi_worker",
    ] + list(command)
    return cmd


def mpi_run(
    np_: int,
    hosts: Optional[str],
    command: List[str],
    *,
    extra_env: Optional[Dict[str, str]] = None,
    mpi_args: Optional[List[str]] = None,
    verbose: bool = False,
) -> int:
    """Launch ``np_`` workers via mpirun; returns mpirun's exit code.

    The controller (KV/barrier/rendezvous) runs in this process for the
    job's lifetime, as in ``launch_static``.
    """
    if not is_mpi_available():
        raise RuntimeError(
            "mpirun not found on PATH (reference mpi_run.py raises the "
            "same); install Open MPI or use the default launcher"
        )
    host_list = (
        hosts_mod.parse_hosts(hosts) if hosts
        else [hosts_mod.HostInfo("localhost", np_)]
    )
    assignments = hosts_mod.get_host_assignments(host_list, np_)
    server, service_env = start_job_services(
        np_, [a.hostname for a in assignments], nic_probe=False
    )
    env = dict(os.environ)
    env.update(service_env)
    if extra_env:
        env.update(extra_env)
    cmd = get_mpi_command(
        np_, hosts, command, env, mpi_args=mpi_args,
        forward_names=sorted(extra_env) if extra_env else None,
    )
    if verbose:
        get_logger().warning("mpirun launch: %s", " ".join(cmd))
    try:
        return subprocess.run(cmd, env=env).returncode
    finally:
        server.stop()
