"""Host parsing and slot assignment.

Reference: ``horovod/runner/common/util/hosts.py`` — parses
``host:slots`` lists / hostfiles and computes per-slot rank assignments
(``get_host_assignments``, ``hosts.py:100``) producing ``SlotInfo``
records {rank, local_rank, cross_rank, sizes}.

On TPU a "slot" is a worker process (normally one per host, owning all
of that host's chips), so slots default to 1 instead of the reference's
GPU count.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int = 1

    @staticmethod
    def from_string(spec: str) -> "HostInfo":
        spec = spec.strip()
        if ":" in spec:
            host, slots = spec.rsplit(":", 1)
            return HostInfo(host, int(slots))
        return HostInfo(spec, 1)


@dataclasses.dataclass
class SlotInfo:
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """Parse ``host1:slots,host2:slots`` (reference ``parse_hosts``)."""
    return [HostInfo.from_string(h) for h in hosts_string.split(",") if h.strip()]


def parse_host_files(filename: str) -> List[HostInfo]:
    """Parse a hostfile with ``host slots=N`` lines (reference
    ``parse_host_files``)."""
    hosts = []
    with open(filename) as fh:
        for line in fh:
            line = line.split("#")[0].strip()
            if not line:
                continue
            m = re.match(r"^(\S+)\s+slots\s*=\s*(\d+)$", line)
            if m:
                hosts.append(HostInfo(m.group(1), int(m.group(2))))
            else:
                hosts.append(HostInfo.from_string(line))
    return hosts


def get_host_assignments(
    hosts: List[HostInfo], min_np: int, max_np: Optional[int] = None
) -> List[SlotInfo]:
    """Assign ranks to host slots (reference ``hosts.py:100``).

    Fills hosts in order; ranks are contiguous per host so local_rank
    matches position on the host and cross_rank indexes hosts.  Raises
    when fewer than ``min_np`` slots are available.
    """
    total = sum(h.slots for h in hosts)
    if total < min_np:
        raise ValueError(
            f"requested {min_np} processes but hosts provide only {total} "
            f"slot(s); add hosts or raise slots (host:slots)"
        )
    np_ = min(total, max_np) if max_np else min_np
    assignments: List[SlotInfo] = []
    rank = 0
    used_hosts = []
    for cross_rank, h in enumerate(hosts):
        if rank >= np_:
            break
        local = min(h.slots, np_ - rank)
        used_hosts.append((h, local))
        for local_rank in range(local):
            assignments.append(
                SlotInfo(
                    hostname=h.hostname,
                    rank=rank,
                    local_rank=local_rank,
                    cross_rank=cross_rank,
                    size=np_,
                    local_size=local,
                    cross_size=0,  # fixed up below
                )
            )
            rank += 1
    cross_size = len(used_hosts)
    for a in assignments:
        a.cross_size = cross_size
    return assignments
