"""The exchange IR: every collective-shaped transfer as explicit data.

Horovod's core architectural bet is that *all* communication flows
through one fusion/scheduling engine (arXiv:1802.05799 §4: tensor
fusion, response cache, cycle dispatch).  Before this module, our
reproduction honored that bet only for dense DP gradients — the
``sched/`` pipeline's (bucket, wire, lowering, groups) tuple was
implicit in ``Bucket`` fields and ``execute.py`` control flow, and the
other collective-shaped workloads (MoE all_to_all, Ulysses head/seq
flips, sparse embedding exchange, pipeline ppermute, FSDP RS+AG)
called raw ``lax`` and bypassed the quantized wire, the hierarchical
lowering, and the persistent tuner.

An :class:`ExchangeProgram` makes the tuple explicit: an ordered list
of :class:`ExchangeOp` records, each naming *what* moves (op +
payload attrs), *where* (axis / replica groups), and *how* (wire
format, lowering, bucket id, error-feedback eligibility).  The program
is pure metadata — hashable, deterministic across SPMD ranks, and
usable as a tuner/store key — and is given meaning by two passes:

* ``lower.py`` resolves ``lowering="auto"`` against the topology cost
  model and downgrades wire requests per op-class eligibility;
* ``interp.py`` emits the existing phase primitives
  (``ops/quantized.py``, ``topo/hierarchical.py``, stock ``lax``) and
  accounts bytes/lanes in the metrics registry.

Op set (``OPS``): ``all_reduce``, ``reduce_scatter``, ``all_gather``,
``all_to_all``, ``permute``, ``gather_dense_from_sparse``.  See
docs/exchange_ir.md for attribute semantics and the per-workload
interaction table.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

from ..exceptions import HorovodTpuError

OPS = (
    "all_reduce",
    "reduce_scatter",
    "all_gather",
    "all_to_all",
    "permute",
    "gather_dense_from_sparse",
)

# Wire formats an op may request (same vocabulary as the scheduler's
# plan stage).  Eligibility is per op class — see ``eligible_wire``.
WIRE_CHOICES = ("off", "bf16", "int8", "fp8")

# Lowerings an op may carry.  "auto" is resolved by the lowering pass;
# a lowered program contains only "flat"/"hier"/"hier_adasum" (the
# last — Adasum's adaptive cross-slice combine — only on float
# reduce-shaped ops, and never from "auto": it changes the reduction
# algorithm, so it must be requested explicitly).
LOWER_CHOICES = ("flat", "hier", "hier_adasum", "auto")

# Ops the hierarchical (ICI/DCN two-level) lowering exists for.  The
# shuffle-shaped ops (all_to_all / permute / sparse gather) have no
# staged form — every element changes owner, so there is no 1/k shard
# to ship across DCN — and always lower flat.
REDUCE_OPS = ("all_reduce", "reduce_scatter", "all_gather")

# Workload-kind discriminators programs are built with.  Free-form
# strings are allowed (the kind folds into tuner/store keys and metric
# labels); these are the ones the repo's own workloads use.
KINDS = (
    "dense_grad",   # sched/ bucketed DP gradient exchange
    "moe",          # parallel/moe.py dispatch + combine all_to_all
    "ulysses",      # parallel/ulysses.py head/sequence flips
    "sparse_embed", # ops/sparse.py allgather-of-slices
    "pipeline",     # parallel/pipeline.py stage-to-stage ppermute
    "fsdp",         # optim/zero.py fsdp_train_step RS + AG
)


def _freeze(value: Any) -> Any:
    """Recursively hashable form of an attribute value."""
    if isinstance(value, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in value.items()))
    if isinstance(value, (list, tuple)):
        return tuple(_freeze(v) for v in value)
    return value


@dataclasses.dataclass(frozen=True)
class ExchangeOp:
    """One collective exchange: the explicit (what, where, how) record.

    ``axis`` is a named mesh axis (or a 2-tuple of factored sub-axes
    for the hierarchical addressing mode).  ``groups`` carries explicit
    equal-size ``axis_index_groups`` (process-set subgroups); ``None``
    means the whole axis.  ``bucket`` is the op's position in its
    program's bucket order (the scheduler's bucket id).  ``ef`` marks
    error-feedback eligibility — the interpreter only threads residuals
    through ops that set it (quantized reduce-shaped ops; shuffle ops
    are bit-moving and never carry EF).  ``attrs`` holds op-specific
    payload metadata (``split_axis``/``concat_axis`` for all_to_all,
    ``perm`` for permute, ``reduce`` ∈ {"sum", "mean"} for the
    reduce-shaped ops, ``nbytes``/``dtype`` for byte accounting).
    """

    op: str
    axis: Any
    wire: str = "off"
    lowering: str = "auto"
    bucket: int = 0
    ef: bool = False
    groups: Optional[Tuple[Tuple[int, ...], ...]] = None
    attrs: Tuple[Tuple[str, Any], ...] = ()

    def __post_init__(self):
        if self.op not in OPS:
            raise HorovodTpuError(
                f"unknown exchange op {self.op!r}; expected one of {OPS}"
            )
        if self.wire not in WIRE_CHOICES:
            raise HorovodTpuError(
                f"unknown wire {self.wire!r}; expected one of "
                f"{WIRE_CHOICES}"
            )
        if self.lowering not in LOWER_CHOICES:
            raise HorovodTpuError(
                f"unknown lowering {self.lowering!r}; expected one of "
                f"{LOWER_CHOICES}"
            )
        if self.groups is not None:
            object.__setattr__(
                self,
                "groups",
                tuple(tuple(int(i) for i in g) for g in self.groups),
            )
        object.__setattr__(
            self,
            "axis",
            tuple(self.axis) if isinstance(self.axis, list) else self.axis,
        )
        object.__setattr__(self, "attrs", _freeze(dict(self.attrs)))

    def attr(self, name: str, default: Any = None) -> Any:
        for k, v in self.attrs:
            if k == name:
                return v
        return default

    def replace(self, **kw) -> "ExchangeOp":
        if "attrs" not in kw:
            return dataclasses.replace(self, **kw)
        merged = dict(self.attrs)
        merged.update(kw.pop("attrs"))
        return dataclasses.replace(
            self, attrs=tuple(sorted(merged.items())), **kw
        )

    def signature(self) -> Tuple:
        return (
            self.op, self.axis, self.wire, self.lowering, self.bucket,
            self.ef, self.groups, self.attrs,
        )


@dataclasses.dataclass(frozen=True)
class ExchangeProgram:
    """An ordered exchange plan for one workload.

    ``kind`` is the workload discriminator — it labels metric series
    and timeline lanes, and folds into the persistent tuner/store key
    so two different exchange shapes with the same payload signature
    never collide in the DB (``sched/store.py``).
    """

    kind: str
    ops: Tuple[ExchangeOp, ...]
    # Trace correlation (trace/context.py), attached by the producer
    # that built the program.  Excluded from equality and signature():
    # trace ids differ per submission, while the signature must stay
    # the ResponseCache/tune-DB identity of the exchange *shape*.
    trace: Any = dataclasses.field(
        default=None, compare=False, repr=False
    )

    def __post_init__(self):
        object.__setattr__(self, "ops", tuple(self.ops))

    def __len__(self) -> int:
        return len(self.ops)

    def signature(self) -> Tuple:
        """Hashable identity: equal signatures emit identical exchange
        subgraphs (the determinism contract plan signatures already
        carry, extended with the workload kind)."""
        return (self.kind, tuple(op.signature() for op in self.ops))

    def with_trace(self, ctx) -> "ExchangeProgram":
        """Copy carrying a :class:`~horovod_tpu.trace.context.
        TraceContext` — signature/equality (and thus every cache key)
        unchanged."""
        return dataclasses.replace(self, trace=ctx)

    @property
    def lowered(self) -> bool:
        return all(op.lowering != "auto" for op in self.ops)

    def total_nbytes(self) -> int:
        return sum(int(op.attr("nbytes") or 0) for op in self.ops)


def eligible_wire(op: str, wire: str, dtype: Any = None) -> str:
    """Downgrade a requested wire to what the op class supports.

    Reduce-shaped ops accept the full menu (the quantized phase
    primitives serve them); shuffle-shaped ops (all_to_all / permute /
    sparse gather) move *values that must arrive exactly where they
    were sent*, so the blockwise quantize→dequant round trip has no
    accumulation to hide in — only the bf16 cast wire applies, and
    int8/fp8 requests fall back to ``off`` (never a half-applied
    quantization).  Non-floating payloads are always dense.
    """
    if wire == "off":
        return wire
    if wire not in WIRE_CHOICES:
        raise HorovodTpuError(
            f"unknown wire {wire!r}; expected one of {WIRE_CHOICES}"
        )
    if dtype is not None:
        import jax.numpy as jnp

        if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
            return "off"
        if wire == "bf16" and jnp.dtype(dtype) == jnp.bfloat16:
            return "off"  # already on the bf16 wire; cast is a no-op
    if op in REDUCE_OPS:
        return wire
    return "bf16" if wire == "bf16" else "off"


def eligible_lowering(op: str, lowering: str, dtype: Any = None) -> str:
    """Downgrade a requested lowering to what the op class supports.

    Only ``hier_adasum`` has eligibility rules of its own: the adaptive
    combination is reduce-shaped (there is nothing to adaptively sum in
    an all_gather or a shuffle) and its pair coefficients divide by
    gradient norms, so it serves ``all_reduce``/``reduce_scatter`` ops
    with floating payloads only — everything else falls back to
    ``flat`` (plain sum; never a half-applied algorithm change).
    """
    if lowering != "hier_adasum":
        return lowering
    if op not in ("all_reduce", "reduce_scatter"):
        return "flat"
    if dtype is not None:
        import jax.numpy as jnp

        if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
            return "flat"
    return lowering


# ------------------------------------------------------------ builders

def _payload_attrs(nbytes: Optional[int], dtype: Any,
                   extra: Dict[str, Any]) -> Tuple[Tuple[str, Any], ...]:
    attrs = dict(extra)
    if nbytes is not None:
        attrs["nbytes"] = int(nbytes)
    if dtype is not None:
        attrs["dtype"] = str(dtype)
    return tuple(sorted(attrs.items()))


def all_reduce(axis, *, reduce: str = "sum", wire: str = "off",
               lowering: str = "auto", bucket: int = 0, ef: bool = False,
               groups=None, nbytes: Optional[int] = None,
               dtype: Any = None) -> ExchangeOp:
    return ExchangeOp(
        "all_reduce", axis, wire=wire, lowering=lowering, bucket=bucket,
        ef=ef, groups=groups,
        attrs=_payload_attrs(nbytes, dtype, {"reduce": reduce}),
    )


def reduce_scatter(axis, *, reduce: str = "sum", wire: str = "off",
                   lowering: str = "auto", bucket: int = 0,
                   ef: bool = False, groups=None,
                   nbytes: Optional[int] = None,
                   dtype: Any = None) -> ExchangeOp:
    return ExchangeOp(
        "reduce_scatter", axis, wire=wire, lowering=lowering,
        bucket=bucket, ef=ef, groups=groups,
        attrs=_payload_attrs(nbytes, dtype, {"reduce": reduce}),
    )


def all_gather(axis, *, wire: str = "off", lowering: str = "auto",
               bucket: int = 0, groups=None, nbytes: Optional[int] = None,
               dtype: Any = None) -> ExchangeOp:
    return ExchangeOp(
        "all_gather", axis, wire=wire, lowering=lowering, bucket=bucket,
        groups=groups, attrs=_payload_attrs(nbytes, dtype, {}),
    )


def all_to_all(axis, *, split_axis: int, concat_axis: int,
               wire: str = "off", bucket: int = 0, groups=None,
               nbytes: Optional[int] = None,
               dtype: Any = None) -> ExchangeOp:
    return ExchangeOp(
        "all_to_all", axis, wire=wire, lowering="flat", bucket=bucket,
        groups=groups,
        attrs=_payload_attrs(nbytes, dtype, {
            "split_axis": int(split_axis),
            "concat_axis": int(concat_axis),
        }),
    )


def permute(axis, perm: Sequence[Tuple[int, int]], *, wire: str = "off",
            bucket: int = 0, nbytes: Optional[int] = None,
            dtype: Any = None) -> ExchangeOp:
    return ExchangeOp(
        "permute", axis, wire=wire, lowering="flat", bucket=bucket,
        attrs=_payload_attrs(nbytes, dtype, {
            "perm": tuple((int(s), int(d)) for s, d in perm),
        }),
    )


def gather_dense_from_sparse(axis, *, wire: str = "off", bucket: int = 0,
                             set_ranks: Optional[Sequence[int]] = None,
                             nbytes: Optional[int] = None,
                             dtype: Any = None) -> ExchangeOp:
    """The sparse embedding exchange: allgather of (indices, values)
    slices (the reference's IndexedSlices lowering,
    ``tensorflow/__init__.py:95-162``).  The indices leg is always
    dense int wire; a ``wire`` request applies to the values leg only.
    ``set_ranks`` records a process-set restriction in the signature
    (the runtime ``ProcessSet`` object is passed to the interpreter)."""
    extra: Dict[str, Any] = {}
    if set_ranks is not None:
        extra["set_ranks"] = tuple(int(r) for r in set_ranks)
    return ExchangeOp(
        "gather_dense_from_sparse", axis, wire=wire, lowering="flat",
        bucket=bucket, attrs=_payload_attrs(nbytes, dtype, extra),
    )


def program(kind: str, ops: Sequence[ExchangeOp]) -> ExchangeProgram:
    return ExchangeProgram(kind=kind, ops=tuple(ops))
