"""Unified exchange IR: one plan→lower→execute pipeline for every
collective-shaped workload.

``xir`` closes the gap ROADMAP item 2 names: the scheduler's
(bucket, wire, lowering, groups) tuple becomes an explicit,
deterministic :class:`~horovod_tpu.xir.ir.ExchangeProgram`, and the
workloads that used to call raw ``lax`` — MoE all_to_all
(``parallel/moe.py``), Ulysses head/sequence flips
(``parallel/ulysses.py``), sparse embedding exchange
(``ops/sparse.py``), pipeline ppermute (``parallel/pipeline.py``),
FSDP RS+AG (``optim/zero.py``) — route through the same three stages
the dense-gradient path already enjoys:

* **plan** — builders in :mod:`~horovod_tpu.xir.ir` (or
  :func:`from_schedule` for a ``sched/`` bucket schedule);
* **lower** — :mod:`~horovod_tpu.xir.lower` resolves flat-vs-hier per
  op from the (fitted) topology cost model, gates wire compression by
  op-class eligibility, and keys the program in the persistent tune DB
  with its workload kind;
* **execute** — :mod:`~horovod_tpu.xir.interp` emits the existing
  phase primitives (``ops/quantized.py``, ``topo/hierarchical.py``,
  stock ``lax``) with per-exchange metrics and timeline lanes.

A fourth pass — **schedule** (:mod:`~horovod_tpu.xir.pipeline`, the
rail pipeliner) — phase-interleaves the ICI and DCN rails across
buckets and merges co-scheduled programs with disjoint rails
(``HVD_TPU_XIR_PIPELINE``; ordering-only, losses bitwise-identical).

``HVD_TPU_XIR=off`` restores every direct call path (bitwise-identical
by the interpreter's parity contract).  See docs/exchange_ir.md.
"""

from . import interp, ir, lower, pipeline  # noqa: F401
from .interp import (  # noqa: F401
    account,
    enabled,
    execute,
    execute_merged,
    run_op,
    set_enabled_override,
    wire_request,
)
from .ir import (  # noqa: F401
    KINDS,
    OPS,
    REDUCE_OPS,
    WIRE_CHOICES,
    ExchangeOp,
    ExchangeProgram,
    all_gather,
    all_reduce,
    all_to_all,
    eligible_lowering,
    eligible_wire,
    gather_dense_from_sparse,
    permute,
    program,
    reduce_scatter,
)
from .lower import (  # noqa: F401
    estimate_program_cost,
    lower as lower_program,
    op_network_bytes,
    op_wire_nbytes,
    program_bytes,
    resolve_lowering,
    tuner_key,
)


def from_schedule(schedule, kind: str = "dense_grad",
                  ef: bool = False, axis=None) -> ExchangeProgram:
    """The dense-gradient bridge: express a
    :class:`~horovod_tpu.sched.plan.BucketSchedule` as an exchange
    program — one op per bucket, already lowered (the plan stage
    resolved wire + lowering per bucket).  ``mode="allreduce"`` buckets
    become ``all_reduce`` ops; ``mode="reduce_scatter"`` buckets become
    ``reduce_scatter`` ops tagged ``paired_all_gather`` (the RS+AG
    decomposition with the optional ZeRO-1 shard update between the
    phases).  ``ef`` marks quantized buckets error-feedback eligible.
    """
    from ..runtime import WORLD_AXIS

    if axis is None:
        axis = WORLD_AXIS
    ops = []
    for bi, b in enumerate(schedule.buckets):
        dtype = b.wire_dtypes[0] if b.wire_dtypes else None
        if schedule.mode == "reduce_scatter":
            op = reduce_scatter(
                axis, wire=b.wire, lowering=b.lowering, bucket=bi,
                ef=ef and b.wire in ("int8", "fp8"),
                nbytes=b.nbytes, dtype=dtype,
            ).replace(attrs={"paired_all_gather": True,
                             "leaves": len(b.indices)})
        else:
            op = all_reduce(
                axis, wire=b.wire, lowering=b.lowering, bucket=bi,
                ef=ef and b.wire in ("int8", "fp8"),
                nbytes=b.nbytes, dtype=dtype,
            ).replace(attrs={"leaves": len(b.indices)})
        ops.append(op)
    return program(kind, ops)
