"""Interpreter: run an exchange program by emitting phase primitives.

The interpreter gives a lowered :class:`~horovod_tpu.xir.ir.ExchangeProgram`
meaning inside a traced step: each op emits exactly the primitive the
pre-IR call sites used —

* ``wire="off"``, ``lowering="flat"`` → the stock ``lax`` collective
  with identical arguments, so an IR-routed exchange is **bitwise
  identical** to the direct call it replaced (the parity contract
  tests/test_collective_matrix.py's XIR column pins);
* ``wire="bf16"`` → the cast-around-the-wire scheme
  (``sched/execute.bf16_wire``'s semantics, applied per op);
* ``wire="int8"/"fp8"`` on reduce-shaped ops → the
  ``ops/quantized.py`` phase primitives (with optional error
  feedback);
* ``lowering="hier"`` on reduce-shaped ops → the
  ``topo/hierarchical.py`` ICI/DCN staging.

Observability per program: the planned bytes land in the *existing*
``sched.wire_bytes{wire=}`` and ``topo.dcn_bytes``/``topo.ici_bytes``
families — labeled with ``kind=`` so MoE / Ulysses / sparse traffic
reads as its own series instead of clobbering the dense-gradient
gauges — plus ``xir.*`` counters and one timeline lane per workload
kind (``MOE_EXCHANGE``, ``ULYSSES_EXCHANGE``, ...).  All recording
happens at trace time, like the scheduler's own exchange metrics: the
gauges describe the planned program, the device profiler owns the
wall-clock attribution.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .. import metrics
from ..exceptions import HorovodTpuError
from ..utils import env
from . import ir, lower as lower_mod

# Trace-time enable override (the sched config-override pattern):
# tests and in-script parity checks pin the engine without touching
# the environment.
_enabled_override: Optional[bool] = None


def set_enabled_override(value: Optional[bool]) -> None:
    global _enabled_override
    _enabled_override = value


def enabled() -> bool:
    """Whether exchanges route through the IR (``HVD_TPU_XIR``, default
    on).  Off restores every workload's direct-``lax`` call path —
    bitwise identical by the interpreter's own contract, so the knob is
    a triage lever, not a numerics one."""
    if _enabled_override is not None:
        return _enabled_override
    return env.get_bool("XIR", True)


# ----------------------------------------------------- onestep knob
#
# Whole-step emission (HVD_TPU_ONESTEP, ROADMAP item 4): fold every
# dispatch unit a step would launch separately — fused service
# buffers, bucket chains, the optimizer-update closure — into ONE
# compiled program, so the host pays a single dispatch round-trip per
# step.  Same mode grammar and override pattern as the rail pipeliner
# (xir/pipeline.py): off | on | auto (default).  Engagement is a
# scheduling decision only; the stitched emission is bitwise-identical
# to the per-unit one (optimization_barrier ties are identity on
# values and per-unit op order never changes).

ONESTEP_MODES = ("off", "on", "auto")

_onestep_override: Optional[str] = None


def set_onestep_override(mode: Optional[str]) -> None:
    """Trace/test-time knob override (the sched config-override
    pattern): pin the whole-step emission without touching the
    environment."""
    global _onestep_override
    if mode is not None and mode not in ONESTEP_MODES:
        raise HorovodTpuError(
            f"onestep mode override must be one of {ONESTEP_MODES}, "
            f"got {mode!r}"
        )
    _onestep_override = mode


def onestep_mode() -> str:
    """``HVD_TPU_ONESTEP`` policy: ``off`` | ``on`` | ``auto``
    (default).  ``off`` keeps every per-unit dispatch path exactly as
    it was; ``auto`` folds when a step has >= 2 dispatch units; ``on``
    always folds."""
    if _onestep_override is not None:
        return _onestep_override
    raw = (env.get_env(env.ONESTEP, "auto") or "auto").strip().lower()
    if raw in ("0", "false", "no", "none", ""):
        raw = "off"
    if raw in ("1", "true", "yes"):
        raw = "on"
    if raw not in ONESTEP_MODES:
        raise HorovodTpuError(
            f"HVD_TPU_ONESTEP must be off|on|auto, got {raw!r}"
        )
    return raw


def onestep_engaged(n_units: int) -> bool:
    """Whether whole-step emission folds ``n_units`` dispatch units
    (fused buffers, solo programs, the update closure) into one
    program.  ``off`` never folds; ``on`` always does; ``auto`` folds
    only when there are at least two units — with one unit the fold
    would change nothing."""
    m = onestep_mode()
    if m == "off":
        return False
    if m == "on":
        return n_units >= 1
    return n_units >= 2


def emit_step(reduced: Sequence[Any], update, *, src: str = "sched"):
    """Stitch a caller's update closure onto freshly-reduced exchange
    outputs INSIDE the same traced emission: the closure's inputs are
    barrier-tied to the reduced tensors (identity on values), so XLA
    sees one program with an explicit exchange→update edge instead of
    two independently dispatched subgraphs.  Returns whatever the
    closure returns.  Values are bitwise-identical to applying the
    closure after the exchange returns — the tie adds ordering edges
    only."""
    from .. import prof, trace

    leaves = list(reduced)
    arrays = [i for i, t in enumerate(leaves)
              if isinstance(t, jax.Array) or hasattr(t, "dtype")]
    if arrays:
        tied = lax.optimization_barrier(
            tuple(leaves[i] for i in arrays)
        )
        for i, t in zip(arrays, tied):
            leaves[i] = t
    metrics.inc_counter("xir.onestep.steps")
    prof.note_emission(f"onestep.{src}", 1)
    with trace.span(
        "onestep.update", "exchange", onestep=1, src=src,
    ), jax.named_scope(f"hvd_onestep_update_{src}"):
        return update(leaves)


def execute_onestep(programs: Sequence[ir.ExchangeProgram],
                    args_lists: Sequence[Sequence[Any]],
                    *,
                    axis_size: Optional[int] = None,
                    process_set=None,
                    store: bool = False,
                    update=None) -> Any:
    """Whole-step emission of a program list: every program's ops —
    and optionally the caller's ``update`` closure over the full
    output list — lower into ONE traced region under a single
    ``onestep``-marked span, instead of one :func:`execute` call (= one
    potential dispatch) per program.  Per-program op order is
    preserved exactly, so outputs are bitwise-identical to N separate
    :func:`execute` calls; the fold only removes dispatch boundaries.
    Returns one output list per program (or, with ``update``, whatever
    the closure returns when applied to that list-of-lists)."""
    from .. import trace

    programs = [
        p if p.lowered else lower_mod.lower(p, axis_size, store=store)
        for p in programs
    ]
    for p, args in zip(programs, args_lists):
        if len(args) != len(p.ops):
            raise HorovodTpuError(
                f"program {p.kind!r} has {len(p.ops)} ops but "
                f"{len(args)} payloads were passed"
            )
    metrics.inc_counter("xir.onestep.programs", len(programs))
    outs: List[List[Any]] = []
    with trace.span(
        "exchange.onestep", "exchange", onestep=1,
        programs=len(programs),
        kind="+".join(p.kind for p in programs),
    ):
        for p, args in zip(programs, args_lists):
            account(p, axis_size)
            prog_outs = []
            for op, x in zip(p.ops, args):
                with jax.named_scope(
                    f"hvd_onestep_{p.kind}_{op.op}{op.bucket}"
                    f"_{op.wire}_{op.lowering}"
                ):
                    prog_outs.append(
                        run_op(op, x, process_set=process_set)
                    )
            outs.append(prog_outs)
        if update is not None:
            flat = [t for prog in outs for t in prog]
            tied = emit_step(flat, lambda ts: ts, src="execute")
            it = iter(tied)
            outs = [[next(it) for _ in prog] for prog in outs]
            return update(outs)
    return outs


def wire_request() -> str:
    """The wire format non-gradient IR workloads request
    (``HVD_TPU_XIR_WIRE``, default ``off``).  Deliberately NOT
    inherited from ``HVD_TPU_SCHED_WIRE``: that knob compresses
    *gradients* (error feedback absorbs the rounding); these ops move
    activations and embedding rows, where compression is a separate
    numerics decision.  Eligibility gating per op class still applies —
    shuffle ops cap at bf16."""
    raw = env.get_env("XIR_WIRE", "off") or "off"
    w = raw.strip().lower()
    if w in ("none", "0", "false", "no"):
        w = "off"
    if w == "e4m3":
        w = "fp8"
    if w not in ir.WIRE_CHOICES:
        raise HorovodTpuError(
            f"HVD_TPU_XIR_WIRE must be one of {ir.WIRE_CHOICES}, "
            f"got {raw!r}"
        )
    return w


def _axis_n(op: ir.ExchangeOp) -> int:
    if op.groups is not None:
        return len(op.groups[0])
    if isinstance(op.axis, tuple):
        n = 1
        for a in op.axis:
            n *= lax.axis_size(a)
        return n
    return lax.axis_size(op.axis)


def _bf16_around(x: jax.Array, run) -> jax.Array:
    if not jnp.issubdtype(x.dtype, jnp.floating) or x.dtype == jnp.bfloat16:
        return run(x)
    # The down/up casts around the wire are single VMEM-tiled kernels
    # (ops/pallas_kernels.cast_buffer — the reference's ScaleBuffer
    # device kernel), not separate convert HLOs; values are identical
    # to a plain astype pair.
    from ..ops.pallas_kernels import cast_buffer

    return cast_buffer(run(cast_buffer(x, jnp.bfloat16)), x.dtype)


def _run_all_reduce(op: ir.ExchangeOp, x: jax.Array, residual=None):
    from ..ops.traced import Average, Sum

    mean = (op.attr("reduce") or "sum") == "mean"
    red = Average if mean else Sum
    if op.lowering == "hier_adasum":
        from ..topo import hierarchical_adasum_all_reduce

        return hierarchical_adasum_all_reduce(
            x, op.axis, op=red, wire=op.wire
        )
    if op.lowering == "hier":
        from ..topo import hierarchical_all_reduce

        return hierarchical_all_reduce(x, op.axis, op=red, wire=op.wire)
    if op.wire in ("int8", "fp8"):
        if op.ef and residual is not None:
            from ..ops.quantized import quantized_allreduce_ef

            return quantized_allreduce_ef(
                x, residual, op.axis, op=red, wire=op.wire,
                backend=op.attr("qbackend"),
            )
        from ..ops.quantized import quantized_allreduce

        return quantized_allreduce(
            x, op.axis, op=red, wire=op.wire,
            groups=[list(g) for g in op.groups] if op.groups else None,
            backend=op.attr("qbackend"),
        ).astype(x.dtype)

    def dense(v):
        if op.groups is not None:
            from ..ops.traced import _grouped_sum

            y = _grouped_sum(
                v, op.axis, [list(g) for g in op.groups],
                len(op.groups[0]),
            )
        elif isinstance(op.axis, tuple):
            y = lax.psum(v, op.axis)
        else:
            y = lax.psum(v, op.axis)
        return y / _axis_n(op) if mean else y

    if op.wire == "bf16":
        return _bf16_around(x, dense)
    return dense(x)


def _run_reduce_scatter(op: ir.ExchangeOp, x: jax.Array):
    from ..ops.traced import Average, Sum

    mean = (op.attr("reduce") or "sum") == "mean"
    red = Average if mean else Sum
    if op.lowering == "hier_adasum":
        # A standalone adasum reduce_scatter has no meaning: the
        # adaptive combine needs the paired all_gather (the scheduler's
        # RS+AG exchange drives hier_adasum buckets through
        # sched/execute.hier_adasum_flat, never this runner).
        raise HorovodTpuError(
            "reduce_scatter ops cannot run lowering='hier_adasum' "
            "standalone; use the scheduler's paired RS+AG exchange "
            "(or an all_reduce op)"
        )
    if op.lowering == "hier":
        from ..topo import hierarchical_reduce_scatter

        return hierarchical_reduce_scatter(
            x, op.axis, op=red, wire=op.wire
        )
    if op.wire in ("int8", "fp8"):
        from ..ops.quantized import quantized_reduce_scatter

        out = quantized_reduce_scatter(
            x, op.axis, op=red, wire=op.wire,
            groups=[list(g) for g in op.groups] if op.groups else None,
            backend=op.attr("qbackend"),
        )
        return out.astype(x.dtype) if hasattr(out, "astype") else out
    n = _axis_n(op)
    if x.shape[0] % n != 0:
        raise HorovodTpuError(
            f"reduce_scatter payload of {x.shape[0]} rows does not "
            f"divide over {n} participants; pad before building the op"
        )

    def dense(v):
        shard = lax.psum_scatter(
            v, op.axis, scatter_dimension=0, tiled=True,
            axis_index_groups=(
                [list(g) for g in op.groups] if op.groups else None
            ),
        )
        return shard / n if mean else shard

    if op.wire == "bf16":
        return _bf16_around(x, dense)
    return dense(x)


def _run_all_gather(op: ir.ExchangeOp, x: jax.Array):
    if op.lowering == "hier":
        from ..topo import hierarchical_all_gather

        return hierarchical_all_gather(x, op.axis, wire=op.wire)
    if op.wire in ("int8", "fp8"):
        from ..ops.quantized import quantized_all_gather

        return quantized_all_gather(
            x, op.axis, wire=op.wire,
            groups=[list(g) for g in op.groups] if op.groups else None,
            backend=op.attr("qbackend"),
        ).astype(x.dtype)

    def dense(v):
        return lax.all_gather(
            v, op.axis, tiled=True,
            axis_index_groups=(
                [list(g) for g in op.groups] if op.groups else None
            ),
        )

    if op.wire == "bf16":
        return _bf16_around(x, dense)
    return dense(x)


def _run_all_to_all(op: ir.ExchangeOp, x: jax.Array):
    split = int(op.attr("split_axis"))
    concat = int(op.attr("concat_axis"))

    def dense(v):
        return lax.all_to_all(
            v, op.axis, split_axis=split, concat_axis=concat, tiled=True,
            axis_index_groups=(
                [list(g) for g in op.groups] if op.groups else None
            ),
        )

    if op.wire == "bf16":
        return _bf16_around(x, dense)
    return dense(x)


def _run_permute(op: ir.ExchangeOp, x: jax.Array):
    perm = [tuple(p) for p in (op.attr("perm") or ())]

    def dense(v):
        return lax.ppermute(v, op.axis, perm)

    if op.wire == "bf16":
        return _bf16_around(x, dense)
    return dense(x)


def _run_gather_sparse(op: ir.ExchangeOp, x, process_set=None):
    """x = (indices, values); returns the gathered pair, same order of
    collectives as the pre-IR ``sparse_allreduce`` (indices first)."""
    from ..ops import traced

    indices, values = x
    idx = traced.allgather(indices, axis=op.axis, process_set=process_set)
    if op.wire == "bf16":
        vals = _bf16_around(
            values,
            lambda v: traced.allgather(
                v, axis=op.axis, process_set=process_set
            ),
        )
    else:
        vals = traced.allgather(
            values, axis=op.axis, process_set=process_set
        )
    return idx, vals


_RUNNERS = {
    "all_reduce": _run_all_reduce,
    "reduce_scatter": _run_reduce_scatter,
    "all_gather": _run_all_gather,
    "all_to_all": _run_all_to_all,
    "permute": _run_permute,
}


def run_op(op: ir.ExchangeOp, x, *, process_set=None, residual=None):
    """Execute one lowered op on its payload.  ``process_set`` feeds
    the sparse gather (the op's signature carries only the rank tuple);
    ``residual`` engages error feedback on EF-eligible reduce ops
    (the call then returns ``(out, new_residual)``)."""
    if op.lowering == "auto":
        op = op.replace(lowering=lower_mod.resolve_lowering(op))
    if op.op == "gather_dense_from_sparse":
        return _run_gather_sparse(op, x, process_set=process_set)
    if op.op == "all_reduce":
        return _run_all_reduce(op, x, residual=residual)
    return _RUNNERS[op.op](op, x)


def account(program: ir.ExchangeProgram,
            axis_size: Optional[int] = None,
            timeline: Any = None) -> None:
    """Publish one program's planned traffic: ``xir.*`` counters, the
    kind-labeled ``sched.wire_bytes{wire=,kind=}`` +
    ``topo.dcn_bytes{kind=}``/``topo.ici_bytes{kind=}`` gauge series,
    the shared ``topo.*_bytes_total`` running counters, and one
    timeline-lane event per op (lane = ``<KIND>_EXCHANGE``)."""
    per_wire, net = lower_mod.program_bytes(program, axis_size)
    kind = program.kind
    metrics.inc_counter("xir.programs")
    metrics.inc_counter(f"xir.programs.{kind}")
    metrics.inc_counter("xir.ops", len(program.ops))
    for w, nbytes in per_wire.items():
        metrics.set_gauge(
            "sched.wire_bytes", nbytes, {"wire": w, "kind": kind}
        )
        metrics.inc_counter(f"sched.wire_bytes.{w}", nbytes)
    metrics.set_gauge("topo.dcn_bytes", net["dcn"], {"kind": kind})
    metrics.set_gauge("topo.ici_bytes", net["ici"], {"kind": kind})
    metrics.inc_counter("topo.dcn_bytes_total", net["dcn"])
    metrics.inc_counter("topo.ici_bytes_total", net["ici"])
    if timeline is None:
        from ..runtime import get_runtime_or_none

        rt = get_runtime_or_none()
        timeline = rt.timeline if rt is not None else None
    if timeline is not None:
        lane = f"{kind.upper()}_EXCHANGE"
        for op in program.ops:
            timeline.record_op(
                f"{op.op}{op.bucket}[wire={op.wire},"
                f"lower={op.lowering}]",
                lane, lower_mod.op_wire_nbytes(op),
            )


def execute_merged(programs: Sequence[ir.ExchangeProgram],
                   args_lists: Sequence[Sequence[Any]],
                   *,
                   axis_size: Optional[int] = None,
                   process_set=None,
                   store: bool = False) -> List[List[Any]]:
    """Run several co-scheduled programs as ONE rail-interleaved
    emission (the cross-workload merge of ``xir/pipeline.py``): when
    the programs' rails are disjoint — a slice-local MoE all_to_all or
    Ulysses flip riding the dense-grad hop loop — their ops emit in
    the merged order with per-rail ``optimization_barrier`` chains, so
    each workload's collectives land in the other's idle windows.

    Values are identical to executing each program separately (the
    chains are ordering-only and the programs share no payloads);
    ineligible combinations — pipelining off, overlapping rails —
    fall back to the **same-rail concatenation mode** instead when the
    service fusion buffer is on (``svc/fuse.py``): ops in the same
    fusion class coalesce into one padded buffer behind ONE collective
    (elementwise reductions commute with concatenation, so f32 dense
    values stay bitwise identical), still rail-interleaved with the
    remaining solo ops.  Only when neither mode applies does the call
    degrade to plain sequential execution, so the entry point is
    always safe to call.  Returns one output list per program, in
    input order."""
    from . import pipeline

    programs = [
        p if p.lowered else lower_mod.lower(p, axis_size, store=store)
        for p in programs
    ]
    for p, args in zip(programs, args_lists):
        if len(args) != len(p.ops):
            raise HorovodTpuError(
                f"program {p.kind!r} has {len(p.ops)} ops but "
                f"{len(args)} payloads were passed"
            )
    merged = pipeline.merge(programs, axis_size)
    if merged is None:
        units = pipeline.merge_concat(programs, axis_size)
        if units is not None:
            return _execute_concat(
                programs, args_lists, units,
                axis_size=axis_size, process_set=process_set,
            )
        return [
            execute(p, a, axis_size=axis_size, process_set=process_set,
                    store=store)
            for p, a in zip(programs, args_lists)
        ]
    from .. import trace

    metrics.inc_counter("xir.pipeline.merged_programs", len(programs))
    for p in programs:
        account(p, axis_size)
    rail = pipeline.RailChain()
    outs: List[List[Any]] = [[None] * len(p.ops) for p in programs]
    with trace.span(
        "exchange.merged", "exchange",
        kind="+".join(p.kind for p in programs),
    ):
        for pi, oi in pipeline.merge_order(programs, axis_size):
            op = programs[pi].ops[oi]
            r = pipeline.op_rail(op, axis_size)
            x = args_lists[pi][oi]
            leaves = list(x) if isinstance(x, tuple) else [x]
            leaves = rail.tie(leaves, (r,))
            x = tuple(leaves) if isinstance(x, tuple) else leaves[0]
            # The merged op's span is rail-attributed at the RailChain
            # boundary it chains on: the measured rail_busy_frac sees
            # the rider's traffic on the rail the merge placed it on.
            with trace.span(
                f"{programs[pi].kind}.{op.op}{op.bucket}",
                "merged_op", rail=r,
                ctx=programs[pi].trace, kind=programs[pi].kind,
            ), jax.named_scope(
                f"hvd_xir_merged_{programs[pi].kind}_{op.op}"
                f"{op.bucket}_{r}"
            ):
                out = run_op(op, x, process_set=process_set)
            rail.bump(out[0] if isinstance(out, tuple) else out, (r,))
            outs[pi][oi] = out
    return outs


def _execute_concat(programs, args_lists, units, *,
                    axis_size=None, process_set=None):
    """The same-rail concatenation emission: each ``("fused", members)``
    unit packs its members' payloads into one block-aligned flat buffer
    (``svc/fuse.pack_group``) and runs ONE collective; solo units run
    as-is.  All units chain through a shared :class:`~horovod_tpu.xir.
    pipeline.RailChain` on their dominant rail, so the fused buffers
    compose with PR 11 rail interleaving — and the whole emission is
    priced by ``lower.estimate_program_cost`` via
    ``svc/fuse.estimate_concat_gain``.  Values are identical to
    sequential execution (bitwise for dense reductions): concatenation
    commutes with elementwise reduction and the chains are ordering-
    only."""
    from .. import trace
    from ..svc import fuse
    from . import pipeline

    metrics.inc_counter("xir.fusion.merged_programs", len(programs))
    for p in programs:
        account(p, axis_size)
    rail = pipeline.RailChain()
    outs: List[List[Any]] = [[None] * len(p.ops) for p in programs]
    with trace.span(
        "exchange.fused", "exchange",
        kind="+".join(p.kind for p in programs),
    ):
        for kind, members in units:
            ops = [programs[pi].ops[oi] for pi, oi in members]
            xs = [args_lists[pi][oi] for pi, oi in members]
            if kind == "solo" or len(members) == 1:
                op, x = ops[0], xs[0]
                r = pipeline.op_rail(op, axis_size)
                leaves = list(x) if isinstance(x, tuple) else [x]
                leaves = rail.tie(leaves, (r,))
                x = tuple(leaves) if isinstance(x, tuple) else leaves[0]
                with trace.span(
                    f"{programs[members[0][0]].kind}.{op.op}{op.bucket}",
                    "merged_op", rail=r,
                ), jax.named_scope(
                    f"hvd_xir_concat_solo_{op.op}{op.bucket}_{r}"
                ):
                    out = run_op(op, x, process_set=process_set)
                rail.bump(out[0] if isinstance(out, tuple) else out, (r,))
                outs[members[0][0]][members[0][1]] = out
                continue
            fused_op = fuse.concat_ops(
                ops, [int(op.attr("nbytes") or 0) for op in ops]
            )
            align = fuse.align_elems(
                fused_op.wire, fused_op.attr("dtype")
            )
            r = pipeline.op_rail(fused_op, axis_size)
            with trace.span(
                "fuse.concat", "fuse", rail=r, members=len(members),
            ), jax.named_scope(
                f"hvd_xir_concat_{fused_op.op}_{r}_m{len(members)}"
            ):
                buf, layout = fuse.pack_group(xs, align)
                buf = rail.tie([buf], (r,))[0]
                fused_out = run_op(
                    fused_op, buf, process_set=process_set
                )
                rail.bump(fused_out, (r,))
                metrics.inc_counter("xir.fusion.buffers")
                metrics.inc_counter("xir.fusion.members", len(members))
                for (pi, oi), out in zip(
                    members, fuse.unpack_group(fused_out, layout)
                ):
                    outs[pi][oi] = out
    return outs


def execute(program: ir.ExchangeProgram,
            args: Sequence[Any],
            *,
            axis_size: Optional[int] = None,
            process_set=None,
            store: bool = True) -> List[Any]:
    """Lower (if needed) and run a program: op *i* consumes ``args[i]``
    and produces output *i*.  The standalone entry point the non-
    gradient workloads use — the bucketed dense-gradient path drives
    the interpreter through ``sched/execute.py`` instead (its payloads
    interleave with backward compute and EF state)."""
    from .. import trace

    if len(args) != len(program.ops):
        raise HorovodTpuError(
            f"program has {len(program.ops)} ops but {len(args)} "
            "payloads were passed"
        )
    if program.trace is None and trace.enabled():
        program = program.with_trace(
            trace.current_context()
            or trace.new_context(f"xir.{program.kind}")
        )
    if not program.lowered:
        # Service producer path (svc/): non-gradient workloads submit
        # their plan at trace time too — a repeat signature resolves
        # from the ResponseCache with zero re-lowering.  Emission
        # stays right here, so SVC on/off is bitwise identical.
        from .. import svc as _svc

        if _svc.enabled():
            program = _svc.get_service().submit_traced(
                program, producer=f"xir.{program.kind}",
                axis_size=axis_size, store=store,
            )
        else:
            program = lower_mod.lower(program, axis_size, store=store)
    elif store:
        program = lower_mod._store_sync(program)
    account(program, axis_size)
    # Emission accounting for the profiling plane: programs/ops emitted
    # through the interpreter, per kind — published at trace time like
    # account() above.
    from .. import prof

    prof.note_emission(f"xir.{program.kind}", len(program.ops))
    outs = []
    with trace.span(
        f"exchange.{program.kind}", "exchange", ctx=program.trace,
        kind=program.kind, ops=len(program.ops),
    ):
        for op, x in zip(program.ops, args):
            with jax.named_scope(
                f"hvd_xir_{program.kind}_{op.op}{op.bucket}_{op.wire}"
                f"_{op.lowering}"
            ):
                outs.append(run_op(op, x, process_set=process_set))
    return outs
