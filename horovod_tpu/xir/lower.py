"""Lowering pass: resolve an exchange program against the topology.

Turns a *requested* program (ops may carry ``lowering="auto"`` and any
wire request) into an *executable* one:

* ``lowering="auto"`` on reduce-shaped ops asks the topology cost
  model (``topo.Topology.estimate_cost`` — the fitted coefficients
  when a measured fit exists, ``topo/fit.py``) to pick flat vs hier
  per op, exactly like the scheduler's per-bucket
  :func:`~horovod_tpu.sched.plan.resolve_lowering`.  Shuffle-shaped
  ops (all_to_all / permute / sparse gather) have no staged form and
  always resolve flat.
* wire requests downgrade through :func:`~horovod_tpu.xir.ir.eligible_wire`
  (shuffle ops: bf16 or dense, never a half-applied quantization), and
  quantized ops carry a resolved ``qbackend`` attribute
  (:func:`resolve_backend`): the fused Pallas backend
  (``HVD_TPU_QUANT_BACKEND=fused``, ops/pallas_quant.py) is eligible
  only for the reduce-shaped op class — shuffle ops have no
  dequant-accumulate to fuse and pin ``phase``.
* when a persistent schedule store is configured
  (``HVD_TPU_TUNE_DB``), the lowered program is keyed in it —
  :func:`tuner_key` folds the workload kind into the
  ``sched/store.py`` key so MoE / Ulysses / sparse programs never
  collide with dense-DP entries of the same payload signature.  A
  stored winner's (wire, lowering) is adopted on hit; a miss records
  the cost-model choice so the fleet-serving path
  (``GET/POST /schedules``) can distribute it.

The pass is pure metadata → metadata: same program + topology + knobs
on every SPMD rank resolve identically (plan determinism).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from .. import metrics
from . import ir


def tuner_key(program: ir.ExchangeProgram) -> str:
    """Persistent-store key of a program: the schedule-store identity
    (topology, jax version, knob fingerprint) over the program's
    signature WITH its workload kind folded in."""
    from ..sched.store import make_key

    return make_key(program.signature(), kind=program.kind)


def resolve_backend(op: ir.ExchangeOp) -> Optional[str]:
    """Quantized-wire backend for one op (``HVD_TPU_QUANT_BACKEND``,
    defaulting through the accelerator backend family —
    ``backend/registry.py``: phase on tpu, fused on gpu), gated per op
    class: only the reduce-shaped ops have a fused ring lowering (the
    pallas_quant/mosaic_quant kernels implement quantize/DMA/dequant-
    accumulate — a shuffle op has no accumulation to fuse), so anything
    else pins ``"phase"``.  Ineligible groups under ``"fused"`` fall
    back to the phase primitives at dispatch time
    (``quantized._fused_mode`` → ``quant.fused_fallback``), never
    silently to dense.  ``None`` for dense/bf16 wires — the backend
    attribute only exists where a quantizer runs."""
    if op.wire not in ("int8", "fp8"):
        return None
    if op.op not in ir.REDUCE_OPS:
        return "phase"
    from ..ops.quantized import quant_backend

    return quant_backend()


def _with_backend(op: ir.ExchangeOp) -> ir.ExchangeOp:
    backend = resolve_backend(op)
    if backend is None:
        return op
    return op.replace(attrs={"qbackend": backend})


def resolve_lowering(op: ir.ExchangeOp,
                     axis_size: Optional[int] = None) -> str:
    """Concrete lowering for one op: shuffle ops are always flat;
    reduce ops honor a forced choice — ``hier_adasum`` gated by
    :func:`~horovod_tpu.xir.ir.eligible_lowering` (float reduce ops
    only) and by the topology (single-slice resolves flat, like the
    plan stage) — and ask the cost model under "auto" (which compares
    the sum-preserving pair only; single-slice topologies and
    non-factorable axes resolve flat, reproducing the pre-topology
    program exactly)."""
    if op.op not in ir.REDUCE_OPS or op.groups is not None:
        return "flat"
    if op.lowering != "auto":
        lowering = ir.eligible_lowering(
            op.op, op.lowering, op.attr("dtype")
        )
        if lowering == "hier_adasum":
            from ..topo import model as topo_model

            n = axis_size
            if n is None and not isinstance(op.axis, tuple):
                n = topo_model.current().world
            if n is not None:
                s, _ = topo_model.current().factor_axis(n)
                if s == 1:
                    return "flat"
        return lowering
    from ..topo import model as topo_model

    topo = topo_model.current()
    if axis_size is None:
        if isinstance(op.axis, tuple):
            return "hier"  # factored sub-axes: the hierarchy is the axis
        axis_size = topo.world
    s, _ = topo.factor_axis(axis_size)
    if s == 1:
        return "flat"
    nbytes = int(op.attr("nbytes") or 0)
    collective = op.op if op.op in ("reduce_scatter", "all_gather") \
        else "all_reduce"
    return topo.choose_lowering(collective, nbytes, axis_size)


# One store lookup/record per distinct lowered program per process:
# tracing re-runs per jit compile, and the JSON store should not be
# re-read (or re-written) on every trace.  Memo keys fold in the
# topo-fit epoch (topo/fit.py:fit_epoch): when the measured cost model
# refits, previously adopted entries must be re-validated against the
# store (whose staleness check prices with the NEW parameters) instead
# of serving pre-fit decisions forever.
_seen_lock = threading.Lock()
_seen_keys: Dict[tuple, Dict] = {}


def reset() -> None:
    """Drop the per-process store-sync memo (tests)."""
    with _seen_lock:
        _seen_keys.clear()


def _store_sync(program: ir.ExchangeProgram) -> ir.ExchangeProgram:
    """Key the lowered program in the persistent tune DB.

    Hit: adopt the stored (wire, lowering) — a converged tuner (or a
    fleet peer) already explored this exchange shape.  Miss: record the
    lowering pass's own choice with a zero score so the entry exists
    for the tuner/fleet to improve (``ScheduleStore.record`` keeps
    best-by-score, so a real tuned score always wins over this seed).
    No store configured → identity.
    """
    from ..sched.store import ScheduleStore

    store = ScheduleStore.from_env()
    if store is None or not program.ops:
        return program
    from ..topo import fit as topo_fit

    key = tuner_key(program)
    memo_key = (key, topo_fit.fit_epoch())
    with _seen_lock:
        cached = _seen_keys.get(memo_key)
    if cached is not None:
        entry = cached
    else:
        entry = store.lookup(key)
        if entry is None:
            lead = program.ops[0]
            entry = store.record(
                key,
                bucket_bytes=program.total_nbytes(),
                wire=lead.wire,
                lowering=lead.lowering,
                score=0.0,
                meta={"kind": program.kind, "ops": len(program.ops)},
            )
            metrics.inc_counter("xir.db_seeded")
        else:
            metrics.inc_counter("xir.db_hit")
        with _seen_lock:
            _seen_keys[memo_key] = entry
    wire = str(entry.get("wire", "off"))
    lowering = str(entry.get("lowering", "flat"))
    if wire not in ir.WIRE_CHOICES:
        wire = "off"
    if lowering not in ("flat", "hier", "hier_adasum"):
        lowering = "flat"
    ops = []
    for op in program.ops:
        new_wire = ir.eligible_wire(op.op, wire, op.attr("dtype"))
        new_lower = ir.eligible_lowering(
            op.op, lowering, op.attr("dtype")
        ) if (op.op in ir.REDUCE_OPS and op.groups is None) else "flat"
        ops.append(_with_backend(
            op.replace(wire=new_wire, lowering=new_lower)
        ))
    synced = ir.program(program.kind, ops)
    return synced.with_trace(program.trace) if program.trace else synced


def lower(program: ir.ExchangeProgram,
          axis_size: Optional[int] = None,
          store: bool = True) -> ir.ExchangeProgram:
    """Resolve a requested program into an executable one (see module
    docstring).  ``axis_size`` sizes the reduction axis for the cost
    model when known at plan time (``None`` prices the full world).
    ``store=False`` skips the persistent-DB sync (the dense-gradient
    path owns its own store handshake through ``ScheduleTuner``)."""
    from .. import trace

    with trace.span(
        f"lower.{program.kind}", "lower",
        ctx=program.trace, kind=program.kind, ops=len(program.ops),
    ):
        ops = []
        for op in program.ops:
            wire = ir.eligible_wire(op.op, op.wire, op.attr("dtype"))
            lowering = resolve_lowering(op, axis_size)
            ops.append(_with_backend(
                op.replace(wire=wire, lowering=lowering)
            ))
        lowered = ir.program(program.kind, ops)
        if program.trace is not None:
            lowered = lowered.with_trace(program.trace)
        if store:
            lowered = _store_sync(lowered)
    return lowered


# ------------------------------------------------------- byte models

def op_wire_nbytes(op: ir.ExchangeOp) -> int:
    """One-phase wire payload bytes of an op under its wire format —
    the same apples-to-apples convention as
    :func:`~horovod_tpu.sched.plan.wire_bytes` (dense bytes for
    ``off``, 2 B/elem for ``bf16``, 1 B/elem + fp32 block scales for
    the quantized formats)."""
    nbytes = int(op.attr("nbytes") or 0)
    if op.wire == "off" or nbytes == 0:
        return nbytes
    import jax.numpy as jnp

    dtype = op.attr("dtype") or "float32"
    itemsize = jnp.dtype(dtype).itemsize
    elems = nbytes // max(itemsize, 1)
    if op.wire == "bf16":
        return elems * 2
    from ..ops.quantized import quant_block

    block = quant_block()
    return elems + 4 * (-(-elems // block))


def op_network_bytes(op: ir.ExchangeOp,
                     axis_size: Optional[int] = None) -> Dict[str, int]:
    """Per-rank wire bytes of one op split by network class
    (``{"dcn": ..., "ici": ...}``), pricing the op's *wire* payload.

    Reduce-shaped ops reuse the topology ring convention
    (:meth:`~horovod_tpu.topo.model.Topology.lowering_bytes`).  The
    shuffle ops get their own models: an all_to_all of a local buffer
    ``B`` over ``n`` ranks sends ``B/n`` to each of the ``n−1`` peers —
    ``k−1`` of them share the slice (ICI), ``n−k`` do not (DCN); a
    permute ships the whole buffer to exactly one peer, DCN when the
    (src, dst) pair crosses a slice boundary.  Explicit subgroups are
    priced ICI-only (they tile inside their groups).
    """
    from ..topo import model as topo_model

    topo = topo_model.current()
    wire_nbytes = op_wire_nbytes(op)
    if wire_nbytes <= 0:
        return {"dcn": 0, "ici": 0}
    if op.groups is not None:
        n = len(op.groups[0])
    elif axis_size is not None:
        n = axis_size
    else:
        n = int(op.attr("axis_size") or topo.world)
    if n <= 1:
        return {"dcn": 0, "ici": 0}
    if op.op in ir.REDUCE_OPS:
        if op.groups is not None:
            moved = (2.0 if op.op == "all_reduce" else 1.0) \
                * wire_nbytes * (n - 1) / n
            return {"dcn": 0, "ici": int(moved)}
        return topo.lowering_bytes(op.op, wire_nbytes, op.lowering, n)
    s, k = (1, n) if op.groups is not None else topo.factor_axis(n)
    if op.op == "permute":
        perm = op.attr("perm") or ()
        pairs = len(perm) or 1
        crossing = sum(1 for src, dst in perm if src // k != dst // k)
        dcn = wire_nbytes * crossing / pairs
        return {"dcn": int(dcn), "ici": int(wire_nbytes - dcn)}
    if op.op == "gather_dense_from_sparse":
        # allgather-of-slices: ring convention on the values payload.
        moved = wire_nbytes * (n - 1) / n
        if s == 1:
            return {"dcn": 0, "ici": int(moved)}
        return {
            "dcn": int(wire_nbytes * (s - 1) / s),
            "ici": int(wire_nbytes * (k - 1) / k),
        }
    # all_to_all
    return {
        "dcn": int(wire_nbytes * (n - k) / n),
        "ici": int(wire_nbytes * (k - 1) / n),
    }


def estimate_program_cost(
    program: ir.ExchangeProgram,
    axis_size: Optional[int] = None,
    *,
    pipelined: Optional[bool] = None,
) -> float:
    """Cost-model seconds for one lowered program: serialized
    (sum-of-phases) or rail-pipelined (max-of-rails,
    ``xir/pipeline.py``).  ``pipelined=None`` prices whichever the
    current ``HVD_TPU_XIR_PIPELINE`` mode would run — the lowering
    pass's hook for comparing schedules the way the executor will
    actually emit them.  Shuffle-shaped ops are priced as one
    all_gather-weight stage on their dominant rail (the ring model has
    no shuffle row; the approximation only matters for merge pricing,
    never numerics)."""
    from . import pipeline

    items = []
    for op in program.ops:
        nbytes = int(op.attr("nbytes") or 0)
        collective = (
            op.op if op.op in ("all_reduce", "reduce_scatter",
                               "all_gather") else "all_gather"
        )
        lowering = op.lowering if op.lowering in (
            "flat", "hier", "hier_adasum") else "flat"
        items.append((collective, nbytes, lowering))
    if pipelined is None:
        pipelined = pipeline.mode() != "off" and pipeline.engaged(
            program.ops if hasattr(program, "ops") else program,
            axis_size,
        )
    return pipeline.estimate_schedule_cost(
        items, axis_size, pipelined=bool(pipelined)
    )


def program_bytes(program: ir.ExchangeProgram,
                  axis_size: Optional[int] = None
                  ) -> Tuple[Dict[str, int], Dict[str, int]]:
    """Aggregate (per-wire payload bytes, per-network bytes) of one
    lowered program — the numbers behind the ``sched.wire_bytes{wire=}``
    and ``topo.dcn_bytes``/``topo.ici_bytes`` series."""
    per_wire: Dict[str, int] = {}
    net = {"dcn": 0, "ici": 0}
    for op in program.ops:
        per_wire[op.wire] = per_wire.get(op.wire, 0) + op_wire_nbytes(op)
        by = op_network_bytes(op, axis_size)
        net["dcn"] += by["dcn"]
        net["ici"] += by["ici"]
    return per_wire, net
